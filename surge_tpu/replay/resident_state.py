"""Device-resident materialized state plane — the KTable as device memory.

The reference serves every aggregate read from a host-side KeyValueStore fed
by the state-topic indexer (AggregateStateStoreKafkaStreams.scala:126-140);
the TPU replay engine only ever ran on cold starts. This module fuses the two
halves (ROADMAP item 2): after a cold-start replay the dense state slab STAYS
on device, a standing refresh loop folds each committed events batch into it
incrementally, and reads are answered by batched device gathers.

Design, against the measured tunnel physics (docs/roofline.md):

- **Slab + directory.** State lives as ``{field: [capacity+1]}`` device
  columns plus an int32 ordinal column (already-folded event count per slot,
  the derived-ordinal base). Row ``capacity`` is a scratch slot that absorbs
  every padded scatter/gather index, so all programs run on power-of-two
  bucketed shapes and the compile count stays bounded. A host-side directory
  maps aggregate id → slot.
- **Refresh loop (one h2d, zero d2h).** A supervised task tails the events
  topic off the same log subscription the :class:`StateStoreIndexer` uses
  (``read`` + ``wait_for_append`` per assigned partition), wire-packs each
  committed batch (surge_tpu.codec.wire — the same bit-packed format the bulk
  replay ships), and dispatches ONE jitted program per refresh window:
  admission scatter → gather lane carries → decode+fold → scatter back. The
  only host⇄device traffic is the packed window riding the dispatch; nothing
  comes back. A per-partition fold watermark tracks progress.
- **Admission / eviction.** The hot set is capacity-bounded. Aggregates are
  admitted when their events arrive (or at seed time); when the slab is full,
  least-recently-touched aggregates NOT in the current batch are evicted —
  their rows are pulled once (the one small d2h exception) into a host spill
  dict, so a later re-admission restores the exact fold point and the
  incremental invariant holds across evict/re-admit cycles (golden-tested).
- **Batched gather reads (single fetch-barriered pull).** Concurrent
  ``read_state`` calls queue onto a gather lane; a drainer coalesces them into
  one device gather and ONE device→host fetch — on a u16 wire when every state
  column is integral (d2h is the 25 MB/s wall; overflow triggers one wide
  refetch, correctness never depends on the guess — the same contract as
  ``ReplayEngine._pull_states``). Reads fall back to the host KV store when
  the aggregate is not resident or the partition's fold watermark lags beyond
  ``surge.replay.resident.max-lag-records``; the entity-init path demands
  ``require_current=True`` (lag 0), because a command processed on a stale
  snapshot would fork the aggregate — bounded staleness is only for read-side
  projections.
- **Rebalance.** ``set_partitions`` follows the indexer's assignment: revoked
  partitions purge their aggregates (resident + spill) outright — a stale row
  must never be servable — and granted partitions re-anchor at offset 0, so
  the refresh loop refolds them from scratch and can never double-fold.

Consistency model (docs/replay.md "Resident state plane"): every resident or
spilled row equals the fold of ALL its partition's committed events below the
partition watermark. Events+state commit atomically in one transaction, so a
row at watermark W is exactly the state snapshot the indexer will hold once it
passes W's transaction — byte-identical after the serialize chain.
"""

from __future__ import annotations

import asyncio
import functools
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from surge_tpu.codec.tensor import encode_events, encode_events_columnar
from surge_tpu.codec.wire import WireFormat
from surge_tpu.common import (Ack, BackgroundTask, Controllable, logger,
                              spawn_reaped)
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec
from surge_tpu.log.transport import page_keyed_records
from surge_tpu.replay.engine import ReplayEngine, make_batch_fold
from surge_tpu.replay.ledger import shard_skew, waste_ratio

__all__ = ["ResidentStatePlane"]


def _pow2(n: int, lo: int = 8) -> int:
    """Next power of two ≥ n (min ``lo``) — the shape bucket every plane
    program runs at, so concurrent batch sizes reuse compiled programs."""
    cap = lo
    while cap < n:
        cap *= 2
    return cap


def _pow8(n: int, lo: int = 8) -> int:
    """Next power of EIGHT ≥ n (min ``lo``) — the refresh program's coarser
    lane bucket. Steady incremental folds see a new batch size almost every
    round; a ×2 ladder would compile a fresh XLA program for half of them
    (~300 ms each on this class of host), which is exactly the latency spike
    the command path must not share the loop with. Padding lanes all target
    the scratch row, so the ≤8× over-dispatch is harmless device work."""
    cap = lo
    while cap < n:
        cap *= 8
    return cap


class ResidentStatePlane(Controllable):
    """Incrementally-maintained on-chip KTable over one events topic."""

    def __init__(self, log, events_topic: str, spec: ReplaySpec, *,
                 config: Config | None = None,
                 partitions: Optional[Sequence[int]] = None,
                 deserialize_event: Callable[[bytes], Any],
                 serialize_state: Callable[[str, Any], bytes],
                 deserialize_events: Callable[[Sequence[bytes]], list] | None = None,
                 encode_event: Callable[[Any], Any] | None = None,
                 decode_state: Callable[[str, Any], Any] | None = None,
                 derived_cols: Mapping[str, str] | None = None,
                 mesh=None, metrics=None,
                 on_signal: Callable[[str, str], None] | None = None,
                 profiler=None, flight=None, ledger=None, tracer=None,
                 faults=None) -> None:
        self.log = log
        self.events_topic = events_topic
        self.spec = spec
        self.config = config or default_config()
        self.deserialize_event = deserialize_event
        # the native-feed fast path (ISSUE 12): one batch deserialize per
        # refresh round (e.g. JsonEventFormatting.read_events_batch — ONE
        # C-level JSON parse per round) riding the native record-index read
        # views, instead of a json.loads + object build per event. The flag
        # is the paired-bench arm AND the operator kill-switch; a failing
        # batch degrades to the per-event path, which finds + poisons the
        # offending aggregate exactly as before.
        self.deserialize_events = (
            deserialize_events if self.config.get_bool(
                "surge.replay.resident.native-feed", True) else None)
        self.serialize_state = serialize_state
        self.encode_event = encode_event
        self.decode_state = decode_state
        self.derived = dict(derived_cols or {})
        self.mesh = mesh
        self.metrics = metrics  # EngineMetrics (resident_* instruments) or None
        self.on_signal = on_signal or (lambda name, level: None)
        self.profiler = profiler
        #: engine flight recorder (optional): seed/evict/re-anchor moves are
        #: incident-timeline material (a rebalance purging slab rows explains
        #: the fallback-read spike that follows it)
        self.flight = flight
        #: refresh-round ledger (surge_tpu.replay.ledger.ReplayLedger,
        #: optional): every round's padding-waste / per-stage anatomy, every
        #: gather drain's coalesce+device legs — the device observatory
        self.ledger = ledger
        #: tracer (optional): the gather lane emits "resident.gather" spans
        #: carrying leg.{coalesce,dispatch,fetch,decode}-ms attributes, so
        #: tail-kept traces break down into device legs in trace anatomy
        self.tracer = tracer
        #: FaultPlane (optional): the refresh executor passes through the
        #: "resident.refresh.dispatch" site — the stall-anatomy e2e's hook
        self._faults = faults

        self.capacity = max(
            self.config.get_int("surge.replay.resident.capacity", 65536), 8)
        # mesh-native slab (surge_tpu.replay.plane_mesh): "local" shards the
        # slab [n_dev, per_dev+1] with device-local gather lanes and
        # per-shard refresh deals; "replicated" keeps the legacy plain-jit
        # programs whose reads replicate the slab (the paired-bench baseline
        # arm). Capacity rounds UP to a device multiple so every shard holds
        # the same row count (the operator's floor is always honored).
        self._mesh_gather = self.config.get_str(
            "surge.replay.mesh.gather", "local")
        if self._mesh_gather not in ("local", "replicated"):
            raise ValueError(
                f"unknown surge.replay.mesh.gather {self._mesh_gather!r} "
                "(local|replicated)")
        self._meshp = None
        if mesh is not None:
            n_dev = int(np.prod(mesh.devices.shape))
            self.capacity = -(-self.capacity // n_dev) * n_dev
        self.max_lag = self.config.get_int(
            "surge.replay.resident.max-lag-records", 4096)
        self._max_poll = self.config.get_int(
            "surge.replay.resident.refresh-max-poll-records", 4096)
        self._poll_timeout = max(self.config.get_seconds(
            "surge.replay.resident.refresh-interval-ms", 50), 0.001)
        self._dispatch = self.config.get_str("surge.replay.dispatch", "switch")
        # refresh window width: the time-chunk rounded to a power of two —
        # rounds longer than one window fold through several chained windows
        self._window = _pow2(
            max(self.config.get_int("surge.replay.time-chunk", 512), 8))
        # refresh dispatch shape (ISSUE 18): "bucketed" (default) deals each
        # round's lanes into pow2 LENGTH buckets and issues one fused
        # admission→fold→scatter program per occupied bucket, so a steady
        # ragged round pays for slots near its occupied count instead of the
        # dense _pow8(lanes) × _pow2(max_len) rectangle; "dense" keeps the
        # single-rectangle dispatch (the paired-bench baseline arm and the
        # rollback switch)
        self._refresh_dispatch = self.config.get_str(
            "surge.replay.resident.refresh-dispatch", "bucketed")
        if self._refresh_dispatch not in ("bucketed", "dense"):
            raise ValueError(
                f"unknown surge.replay.resident.refresh-dispatch "
                f"{self._refresh_dispatch!r} (bucketed|dense)")
        # donate the slab+ordinal columns through every refresh scatter so
        # the round stops copying the slab it writes (kill-switchable like
        # donate-carry; see _build_programs for the read-race contract)
        self._donate_refresh = self.config.get_bool(
            "surge.replay.donate-refresh", True)
        # the ragged Pallas fold tile rides the bucketed plans on the
        # single-device path when the operator EXPLICITLY picks the pallas
        # tile backend (auto keeps the jit rectangle fold — the kernel's
        # interpreter mode on cpu is a correctness arm, not a fast path)
        self._ragged = (
            self._refresh_dispatch == "bucketed"
            and self.config.get_str(
                "surge.replay.tile-backend", "auto") == "pallas")
        #: every (lanes_b, width) pair a refresh program may compile at —
        #: the product of the pow2 lane ladder (8.._pow2(capacity)) and the
        #: pow2 width ladder (2..window). Both the dense sigs (pow8 lanes ⊂
        #: pow2 lanes, widths ≥ 8) and the bucketed sigs draw from this set,
        #: so the compile-signature count per slab layout is bounded by it
        #: however adversarially lane counts / tail lengths vary.
        self.bucket_table = self._build_bucket_table()

        self.partitions: List[int] = sorted(
            partitions if partitions is not None
            else range(log.num_partitions(events_topic)))
        self._watermarks: Dict[int, int] = {}
        self._last_ends: Dict[int, int] = {}
        # anchor generation per partition: bumped by every set_partitions
        # revoke OR grant. A refresh round captures the gens at poll time and
        # commits (fold + watermark advance) only where the gen is unchanged —
        # a revoke→re-grant pair landing while a slow round is in flight must
        # not let that round's commit overwrite the re-grant's 0-anchor (the
        # whole-partition refold would silently be skipped)
        self._anchor_gen: Dict[int, int] = {}
        # the bulk-replay engine used for seeding (its resident fold leaves
        # the cold-start slab on device; we gather rows out of it)
        self.engine = ReplayEngine(spec, config=self.config, mesh=mesh,
                                   profiler=profiler)
        self._wire = WireFormat(spec.registry, self.derived)
        self._fields = spec.registry.state.fields
        self._dtypes = {f.name: np.dtype(f.dtype) for f in self._fields}
        self._make_state = self._build_state_materializer()
        # a remote (broker) log turns end_offset into a blocking RPC — the
        # read path's freshness check must ride the executor there, never
        # the event loop it shares with the command path
        self._remote_log = bool(getattr(log, "is_remote", False))

        # host-side bookkeeping
        self._dir: Dict[str, int] = {}          # id -> slot
        self._free: List[int] = list(range(self.capacity))
        self._spill: Dict[str, Tuple[dict, int]] = {}  # id -> (row, ordinal)
        self._agg_part: Dict[str, int] = {}
        self._poisoned: Dict[str, int] = {}     # id -> partition (unfoldable)
        self._lru: Dict[str, int] = {}
        self._tick = 0
        self._warned_poison = False

        # device state (built on first start/seed)
        self._slab: dict | None = None
        self._ords = None
        self._programs_built = False
        self._signatures: set = set()  # (kind, shape...) — compile detection
        self._ragged_progs: dict = {}  # (lanes_b, width, rows_b) -> jit

        # read gather lane
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._draining = False
        self._drain_tasks: set = set()

        self._task: Optional[BackgroundTask] = None
        self._running = False
        self._stopped = False  # a STOPPED plane must miss: its freshness view
        #                        (_last_ends) is frozen while the log moves on
        self._seeded = False
        #: MaterializedViews (surge_tpu.replay.views) riding this plane's
        #: refresh feed, or None — every committed round folds into the
        #: registered views, and every partition purge drops their partials
        self._views = None
        self.stats = {"rounds": 0, "folded_events": 0, "evictions": 0,
                      "gathers": 0, "gathered_rows": 0, "fallbacks": 0}
        #: why reads fell back, cumulatively ({cause: n}) — the labeled
        #: split of the flat fallbacks counter (see _record_fallback)
        self.fallback_causes: Dict[str, int] = {}
        self._round_causes: Dict[str, int] = {}  # deltas since last round
        # per-round fold accounting (reset each refresh round): padded event
        # slots dispatched vs occupied, device dispatch wall, window count —
        # the padding-waste ledger's raw material
        self._round_acc: Dict[str, Any] = self._fresh_round_acc()
        self._pending_t0: Optional[float] = None  # gather coalesce-wait start

    @staticmethod
    def _fresh_round_acc() -> Dict[str, Any]:
        return {"windows": 0, "dispatched": 0, "occupied": 0,
                "dispatch_s": 0.0, "lanes": 0, "batch": 0, "width": 0,
                "evictions": 0, "programs": 0, "lane_slots": 0, "buckets": []}

    def _build_bucket_table(self) -> frozenset:
        """The bounded compile-signature set: every (lane bucket, window
        width) a refresh program may be shaped at for this capacity/window
        layout. Small by construction — O(log capacity × log window)."""
        lanes, cap = [], 8
        top = _pow2(self.capacity)
        while cap <= top:
            lanes.append(cap)
            cap *= 2
        widths, w = [], 2
        while w <= self._window:
            widths.append(w)
            w *= 2
        return frozenset((lb, wb) for lb in lanes for wb in widths)

    def _build_state_materializer(self):
        """Precompiled row → domain-state constructor, the batch read path's
        per-row cost. Semantically identical to ``StateSchema.from_record`` +
        ``restore._with_aggregate_id`` + ``decode_state``, but with the
        per-field dispatch (np-scalar coercion, excluded-field defaults,
        dataclasses.replace for the id) hoisted out of the per-row loop: the
        gather lane hands it plain Python scalars off one C-speed
        ``ndarray.tolist()`` per column."""
        import dataclasses

        from surge_tpu.codec.tensor import _EXCLUDED_DEFAULTS

        cls = self.spec.registry.state.cls
        names = [f.name for f in self._fields]
        extras: Dict[str, Any] = {}
        has_agg_id = False
        if dataclasses.is_dataclass(cls):
            for f in dataclasses.fields(cls):
                if f.name == "aggregate_id":
                    has_agg_id = True
                    continue
                if (f.name in names
                        or f.default is not dataclasses.MISSING
                        or f.default_factory is not dataclasses.MISSING):  # type: ignore[misc]
                    continue
                ann = (f.type if isinstance(f.type, type)
                       else {"str": str, "int": int, "float": float,
                             "bool": bool}.get(str(f.type)))
                extras[f.name] = _EXCLUDED_DEFAULTS.get(ann, None)
        decode = self.decode_state
        # codegen the constructor call (field names are dataclass
        # identifiers): one keyword call per row indexing straight into the
        # tolist'd columns — no kwargs dict, no per-row tuple. This runs once
        # per gathered row on the read hot path.
        parts = (["aggregate_id=a"] if has_agg_id else [])
        parts += [f"{n}=c[{i}][j]" for i, n in enumerate(names)]
        parts += [f"{n}=_extras[{n!r}]" for n in extras]
        base = eval(  # noqa: S307 — names come from dataclass fields
            f"lambda a, c, j: _cls({', '.join(parts)})",
            {"_cls": cls, "_extras": extras})
        if decode is None:
            return base
        return lambda agg_id, c, j: decode(agg_id, base(agg_id, c, j))

    def _states_of_batch(self, ids: Sequence[str],
                         rows: Mapping[str, np.ndarray], k: int) -> list:
        """Materialize ``k`` gathered rows into domain states. One
        ``tolist()`` per column converts every cell to the exact Python type
        ``from_record`` would produce (bool/int/float by dtype kind), then
        the precompiled constructor runs per row."""
        cols = [rows[f.name][:k].tolist() for f in self._fields]
        make = self._make_state
        return [make(agg, cols, j) for j, agg in enumerate(ids)]

    # -- device programs ----------------------------------------------------------------

    def _sharded(self, arr):
        """The ``mesh.gather = replicated`` arm's slab layout: every device
        holds the WHOLE column and the plain-jit programs run SPMD over the
        replica set (n_dev× the scatter/fold work, n_dev× the memory — the
        baseline the device-local layout is paired against). The old P(axis)
        1-D sharding is gone: capacity+1 never divides the device count, and
        arbitrary-index gathers made XLA replicate it per read anyway."""
        if self.mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    @property
    def _mesh_local(self) -> bool:
        return self.mesh is not None and self._mesh_gather == "local"

    def _ensure_device_state(self) -> None:
        if self._slab is not None:
            return
        if self._mesh_local:
            from surge_tpu.replay.plane_mesh import MeshPlane

            if self._meshp is None:  # kept across a deleted-slab recovery
                self._meshp = MeshPlane(self)
            self._slab, self._ords = self._meshp.init_slab()
            self._build_programs()
            return
        init = self.spec.init_state_tree()
        cap1 = self.capacity + 1  # +1: the scratch row
        self._slab = {f.name: self._sharded(np.full(
            (cap1,), init[f.name], dtype=f.dtype)) for f in self._fields}
        self._ords = self._sharded(np.zeros((cap1,), dtype=np.int32))
        self._build_programs()

    def _build_programs(self) -> None:
        if self._programs_built:
            return
        import jax
        import jax.numpy as jnp

        wire = self._wire
        fold = make_batch_fold(self.spec, dispatch=self._dispatch)
        names = [f.name for f in self._fields]
        # the read wire follows the DEVICE dtypes, not the schema's: with
        # jax_enable_x64 off (the default) a 64-bit schema column is
        # canonicalized to its 32-bit kin on device — decoding a gather by
        # the schema dtype would misparse the buffer. Host decode widens
        # back to the schema dtype (the same contract as the bulk engine's
        # >4-byte per-field-pull guard in ReplayEngine._pull_states).
        dts = [np.dtype(self._slab[n].dtype) for n in names]
        self._dev_dts = dict(zip(names, dts))
        # u32 words per packed field row (2 for a genuine device-64-bit
        # column under jax_enable_x64)
        self._wide_words = [max(dt.itemsize // 4, 1) for dt in dts]
        # u16 read wire eligibility (shared by both slab layouts)
        self._narrow_ok = not any(np.issubdtype(dt, np.floating)
                                  or dt.itemsize > 4 for dt in dts)

        if self._mesh_local:
            # the sharded-slab programs live in plane_mesh (shard_map:
            # device-local refresh deals, one-collective gathers); the
            # single-device jit programs below never build
            self._refresh_prog = None
            self._seed_scatter = None
            self._gather_wide = self._meshp.gather_wide
            self._gather_narrow = (self._meshp.gather_narrow
                                   if self._narrow_ok else None)
            self._fetch_off_loop = jax.default_backend() != "cpu"
            self._programs_built = True
            return

        def refresh(slab, ords, admit_idx, admit_vals, admit_ord,
                    lane_slots, lane_counts, packed, side):
            # 1. admission scatter (spilled carries / init rows re-enter)
            slab = {k: v.at[admit_idx].set(admit_vals[k])
                    for k, v in slab.items()}
            ords = ords.at[admit_idx].set(admit_ord)
            # 2. gather the touched lanes' carries, decode+fold the window
            carry = {k: v[lane_slots] for k, v in slab.items()}
            events = wire.decode(packed, side, ords[lane_slots])
            out = fold(carry, events)
            # 3. scatter back + advance per-slot ordinals (padding lanes all
            # target the scratch row, so duplicate-index writes are harmless)
            slab = {k: v.at[lane_slots].set(out[k]) for k, v in slab.items()}
            ords = ords.at[lane_slots].add(lane_counts)
            return slab, ords

        # slab+ordinal donation (surge.replay.donate-refresh, default on):
        # the refresh scatter consumes the columns it rewrites instead of
        # copying the capacity-sized slab every window (the round-10 ladder's
        # replicated-arm collapse WAS this copy). The gather lane may still
        # hold an in-flight read of the previous slab while a fold
        # dispatches: _fold_group republishes self._slab after every donated
        # window and _drain_batch re-pins + retries on the deleted-buffer
        # error; a dispatch that fails after consuming its inputs rebuilds
        # through _recover_if_slab_deleted. The kill-switch restores the old
        # copying jit wholesale.
        self._refresh_prog = jax.jit(
            refresh, donate_argnums=(0, 1) if self._donate_refresh else ())

        def gather_wide(slab, ords, idx):
            cols = []
            for name, dt in zip(names, dts):
                v = slab[name][idx]
                if np.issubdtype(dt, np.floating) and dt.itemsize < 4:
                    v = jax.lax.bitcast_convert_type(
                        v.astype(jnp.float32), jnp.uint32)
                elif dt == np.bool_ or dt.itemsize < 4:
                    v = v.astype(jnp.uint32)
                elif dt != np.dtype(np.uint32):
                    v = jax.lax.bitcast_convert_type(v, jnp.uint32)
                if v.ndim == 2:  # 64-bit column: one row per u32 word
                    cols.extend(v[:, j] for j in range(v.shape[1]))
                else:
                    cols.append(v)
            return jnp.stack(cols), ords[idx]

        self._gather_wide = jax.jit(gather_wide)

        # u16 read wire: all-integer/bool schemas pull reads at half width
        # with device-computed fit flags at the tail — one flat buffer, one
        # fetch (the same narrow contract as ReplayEngine._pull_states)
        def gather_narrow(slab, idx):
            cols, flags = [], []
            for name, dt in zip(names, dts):
                v = slab[name][idx]
                if dt == np.bool_:
                    fits = jnp.bool_(True)
                elif np.issubdtype(dt, np.signedinteger):
                    fits = jnp.all((v >= -32768) & (v <= 32767))
                else:
                    fits = jnp.all(v <= 65535)
                cols.append(v.astype(jnp.uint16).ravel())
                flags.append(fits.astype(jnp.uint16))
            return jnp.concatenate(cols + [jnp.stack(flags)])

        self._gather_narrow = (jax.jit(gather_narrow)
                               if self._narrow_ok else None)

        def seed_scatter(slab, ords, src_slab, src_pos, dst_slots, lens):
            slab = {k: v.at[dst_slots].set(src_slab[k][src_pos])
                    for k, v in slab.items()}
            ords = ords.at[dst_slots].set(lens)
            return slab, ords

        self._seed_scatter = jax.jit(seed_scatter)
        # the gather lane's fetch runs off-loop only when the fetch is a real
        # device→host transfer (the 25 MB/s tunnel wall); on the host cpu
        # backend np.asarray is a memcpy and the executor hop would cost more
        # than the fetch
        self._fetch_off_loop = jax.default_backend() != "cpu"
        self._programs_built = True

    # -- lifecycle (Controllable) -------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._running

    async def start(self) -> Ack:
        if self._running:
            return Ack()
        self._ensure_device_state()
        if not self._seeded:
            # the cold-start replay: heavy host-side scan/pack runs off the
            # event loop; the folded slab never leaves the device
            await asyncio.get_running_loop().run_in_executor(
                None, self.seed_from_log)
        self._task = BackgroundTask(self._refresh_loop, "resident-refresh")
        self._task.start()
        self._running = True
        self._stopped = False
        return Ack()

    async def stop(self) -> Ack:
        self._running = False
        self._stopped = True
        if self._task is not None:
            await self._task.stop()
            self._task = None
        # fail pending reads over to the host path promptly
        pending, self._pending = self._pending, []
        for target, fut in pending:
            if not fut.done():
                fut.set_result((False, None) if isinstance(target, str)
                               else {})
        return Ack()

    # -- seeding ------------------------------------------------------------------------

    def seed_from_log(self) -> None:
        """Cold-start seed: replay the assigned partitions' events through the
        bulk engine's resident path and gather the folded rows straight into
        the plane slab ON DEVICE (the state columns never round-trip through
        the host on the single-device path). Watermarks anchor at the
        pre-captured end offsets, so the refresh loop resumes exactly past
        what was folded. Aggregates beyond ``capacity`` (admitted
        longest-log-first — the cold heuristic for "hot") are pulled once and
        spilled; they re-admit on their next event or stay served from spill.

        The seed runs in the EXECUTOR (``start`` keeps the loop free), so a
        rebalance landing on the loop mid-seed cannot be fenced at each
        commit the way ``_fold_group`` fences — instead the whole seed is
        reconciled after the fact: any partition whose anchor generation
        moved while the seed flew is purged and de-anchored (a revoked
        partition's rows must never be servable; a re-granted one refolds
        from 0 through the refresh loop, which re-anchors assigned
        partitions via ``setdefault``)."""
        self._ensure_device_state()
        gens = {p: self._anchor_gen.get(p, 0) for p in self.partitions}
        ends = {p: self.log.end_offset(self.events_topic, p) for p in gens}
        try:
            self._seed_scan_fold(ends)
        finally:
            for p in ends:
                if (p not in self.partitions
                        or self._anchor_gen.get(p, 0) != gens.get(p, 0)):
                    self._purge_partition(p)
                    self._watermarks.pop(p, None)
        if self.flight is not None:
            self.flight.record("resident.seed",
                               partitions=sorted(ends),
                               resident=len(self._dir),
                               spilled=len(self._spill))

    def _seed_scan_fold(self, ends: Dict[int, int]) -> None:
        logs: Dict[str, list] = {}
        part_of: Dict[str, int] = {}
        for p in ends:
            for rec in page_keyed_records(self.log, self.events_topic, p,
                                          upto=ends[p]):
                ev = self._encode_checked(rec.key, rec.value, p)
                if ev is None:
                    logs.pop(rec.key, None)
                    continue
                logs.setdefault(rec.key, []).append(ev)
                part_of[rec.key] = p
        self._watermarks.update(ends)
        self._seeded = True
        if self._views is not None and self._views.active_or_pending:
            # the seed IS round zero for every registered view: fold the same
            # scanned logs, anchored at the same end offsets (pending views
            # activate here — the seed covers them from offset 0). Partitions
            # re-anchored mid-seed are reconciled by seed_from_log's purge,
            # which drops their view partials too.
            self._views.fold_round(logs, part_of, dict(ends),
                                   activate_pending=True)
        if not logs:
            self._record_gauges()
            return
        # longest logs first: they are the expensive-to-refold rows, keep them
        ids = sorted(logs, key=lambda a: len(logs[a]), reverse=True)
        lengths = np.asarray([len(logs[a]) for a in ids], dtype=np.int32)
        colev = encode_events_columnar(self.spec.registry,
                                       [logs[a] for a in ids])
        colev.derived_cols = dict(self.derived)

        if self.mesh is not None:
            # mesh-sharded cold start (ShardedResident): fold across devices,
            # then deal-indexed gather into the sharded plane slab
            from surge_tpu.replay.resident_mesh import fold_resident_sharded

            sharded = self.engine.prepare_resident_sharded(colev)
            slab_dev = fold_resident_sharded(self.engine, sharded)
            host = {k: np.asarray(v) for k, v in slab_dev.items()}
            states = {k: np.empty((len(ids),), dtype=self._dtypes[k])
                      for k in host}
            perm = sharded.wire_host.perm
            for d, lanes in enumerate(sharded.deals):
                for k in states:
                    # lanes are sorted ranks; perm maps rank -> original index
                    orig = lanes if perm is None else perm[lanes]
                    states[k][orig] = host[k][d, : len(lanes)]
            self._seed_from_host_rows(ids, states, lengths, part_of)
            self._record_gauges()
            return

        wire = self.engine.pack_resident(colev)
        corpus = self.engine.upload_resident(wire)
        corpus.cache["oneshot"] = True  # folded exactly once
        slab_sorted, _ = self.engine.fold_resident_slab(corpus)
        # sorted position of original aggregate i: inv_perm[i]
        b = len(ids)
        if corpus.perm is None:
            inv = np.arange(b, dtype=np.int32)
        else:
            inv = np.empty((b,), dtype=np.int32)
            inv[corpus.perm] = np.arange(b, dtype=np.int32)
        n_res = min(b, self.capacity)
        dst = np.fromiter((self._free.pop() for _ in range(n_res)),
                          dtype=np.int32, count=n_res)
        k_b = _pow2(n_res)
        src_p = np.zeros((k_b,), dtype=np.int32)
        src_p[:n_res] = inv[:n_res]
        dst_p = np.full((k_b,), self.capacity, dtype=np.int32)
        dst_p[:n_res] = dst
        lens_p = np.zeros((k_b,), dtype=np.int32)
        lens_p[:n_res] = lengths[:n_res]
        self._slab, self._ords = self._seed_scatter(
            self._slab, self._ords, slab_sorted, src_p, dst_p, lens_p)
        for j, agg in enumerate(ids[:n_res]):
            self._dir[agg] = int(dst[j])
            self._agg_part[agg] = part_of[agg]
            self._touch(agg)
        if b > n_res:
            # overflow: one pull of the cold rows into the host spill
            over_pos = inv[n_res:]
            rows, _ = self._pull_positions(slab_sorted, over_pos)
            for j, agg in enumerate(ids[n_res:]):
                self._spill[agg] = ({k: rows[k][j] for k in rows},
                                    int(lengths[n_res + j]))
                self._agg_part[agg] = part_of[agg]

    def _seed_from_host_rows(self, ids, states, lengths, part_of) -> None:
        """Admit host-side state columns (the mesh seed path) into the slab."""
        n_res = min(len(ids), self.capacity)
        dst = np.fromiter((self._free.pop() for _ in range(n_res)),
                          dtype=np.int32, count=n_res)
        k_b = _pow2(max(n_res, 1))
        dst_p = np.full((k_b,), self.capacity, dtype=np.int32)
        dst_p[:n_res] = dst
        vals = {k: np.zeros((k_b,), dtype=self._dtypes[k]) for k in states}
        for k in states:
            vals[k][:n_res] = states[k][:n_res]
        lens_p = np.zeros((k_b,), dtype=np.int32)
        lens_p[:n_res] = lengths[:n_res]
        if self._mesh_local:
            # sharded-slab admission: values ride replicated, every device
            # keeps only the rows it owns (plane_mesh.seed_rows)
            self._slab, self._ords = self._meshp.seed_rows(
                self._slab, self._ords, vals, dst_p, lens_p)
        else:
            # reuse the admission half of the refresh program via
            # seed_scatter on an identity source: scatter host values
            # through a device_put
            slab_src = {k: self._sharded(vals[k]) for k in vals}
            pos = np.arange(k_b, dtype=np.int32)
            self._slab, self._ords = self._seed_scatter(
                self._slab, self._ords, slab_src, pos, dst_p, lens_p)
        for j, agg in enumerate(ids[:n_res]):
            self._dir[agg] = int(dst[j])
            self._agg_part[agg] = part_of[agg]
            self._touch(agg)
        for j, agg in enumerate(ids[n_res:]):
            self._spill[agg] = ({k: states[k][n_res + j] for k in states},
                                int(lengths[n_res + j]))
            self._agg_part[agg] = part_of[agg]

    # -- consistency audit surface (observability/audit.py) -----------------------------

    def audit_pull(self, agg_ids: Sequence[str]) -> Dict[str, tuple]:
        """ONE gather of the LIVE slab rows + fold ordinals for the given
        aggregates (the shadow-replay audit's ground truth). Call ON the
        loop: the (row, ordinal) pairs come out of a single device gather
        against the pinned slab, so they are atomic w.r.t. fold commits —
        a row is always the fold of exactly its ordinal's event prefix.
        Aggregates not resident (spilled/evicted/poisoned) are omitted;
        returns ``{agg: ({field: scalar}, ordinal)}``."""
        ids = [a for a in agg_ids if a in self._dir]
        if not ids:
            return {}
        idx = np.fromiter((self._dir[a] for a in ids), dtype=np.int32,
                          count=len(ids))
        rows, ords = self._pull_positions(self._slab, idx, ords=self._ords)
        return {a: ({k: rows[k][j] for k in rows}, int(ords[j]))
                for j, a in enumerate(ids)}

    def shadow_replay_rows(self, event_logs: List[list]
                           ) -> Dict[str, np.ndarray]:
        """Re-fold per-aggregate event lists FROM SCRATCH through the same
        device fold that built the live rows (the seed path:
        ``pack_resident`` → ``fold_resident_slab``) and pull the folded rows
        to host — the auditor's shadow replay. Pure w.r.t. plane state: the
        fold runs on a fresh one-shot corpus, nothing scatters into the live
        slab. Heavy (encode + pack + device dispatch) — run in the executor.
        Returns ``{field: np[b]}`` in ``event_logs`` order."""
        b = len(event_logs)
        colev = encode_events_columnar(self.spec.registry, event_logs)
        colev.derived_cols = dict(self.derived)
        if self.mesh is not None:
            from surge_tpu.replay.resident_mesh import fold_resident_sharded

            sharded = self.engine.prepare_resident_sharded(colev)
            slab_dev = fold_resident_sharded(self.engine, sharded)
            host = {k: np.asarray(v) for k, v in slab_dev.items()}
            states = {k: np.empty((b,), dtype=self._dtypes[k]) for k in host}
            perm = sharded.wire_host.perm
            for d, lanes in enumerate(sharded.deals):
                for k in states:
                    orig = lanes if perm is None else perm[lanes]
                    states[k][orig] = host[k][d, : len(lanes)]
            return states
        wire = self.engine.pack_resident(colev)
        corpus = self.engine.upload_resident(wire)
        corpus.cache["oneshot"] = True  # folded exactly once
        slab_sorted, _ = self.engine.fold_resident_slab(corpus)
        if corpus.perm is None:
            inv = np.arange(b, dtype=np.int32)
        else:
            inv = np.empty((b,), dtype=np.int32)
            inv[corpus.perm] = np.arange(b, dtype=np.int32)
        rows, _ = self._pull_positions(slab_sorted, inv)
        return rows

    def _corrupt_resident_row(self) -> Optional[str]:
        """Flip one bit in one LIVE resident slab row (the armed
        ``corrupt.slab-row`` fault firing): the log stays correct, the
        device row now lies — exactly the silent rot only the shadow-replay
        audit can see. The row's fold ordinal is preserved (the corruption
        must look like a validly-folded row, not an admission glitch). Flips
        the raw top byte's sign bit so the change survives any on-wire
        dtype narrowing. Returns the corrupted aggregate id, or None when
        nothing is resident."""
        if not self._dir:
            return None
        agg = next(iter(self._dir))
        slot = self._dir[agg]
        rows, ords = self._pull_positions(
            self._slab, np.asarray([slot], dtype=np.int32), ords=self._ords)
        victim = next((f.name for f in self._fields
                       if f.dtype != np.bool_), self._fields[0].name)
        k_b = _pow2(1)
        dst_p = np.full((k_b,), self.capacity, dtype=np.int32)
        dst_p[0] = slot
        lens_p = np.zeros((k_b,), dtype=np.int32)
        lens_p[0] = int(ords[0])
        vals_p = {k: np.zeros((k_b,), dtype=self._dtypes[k]) for k in rows}
        for k in rows:
            v = rows[k][:1].copy()
            if k == victim:
                if v.dtype == np.bool_:
                    v[0] = not v[0]
                else:
                    v.view(np.uint8)[-1] ^= 0x80
            vals_p[k][0] = v[0]
        if self._mesh_local:
            self._slab, self._ords = self._meshp.seed_rows(
                self._slab, self._ords, vals_p, dst_p, lens_p)
        else:
            slab_src = {k: self._sharded(vals_p[k]) for k in vals_p}
            pos = np.arange(k_b, dtype=np.int32)
            self._slab, self._ords = self._seed_scatter(
                self._slab, self._ords, slab_src, pos, dst_p, lens_p)
        logger.warning("fault plane corrupted resident row of %r "
                       "(field %s)", agg, victim)
        return agg

    def prime(self, watermarks: Dict[int, int]) -> None:
        """Fast-forward fold watermarks after an out-of-band seed covered the
        offsets (the :meth:`StateStoreIndexer.prime` analog — only valid
        together with a slab seed of the same coverage)."""
        for p, off in watermarks.items():
            if p in self._watermarks:
                self._watermarks[p] = max(self._watermarks[p], off)

    # -- rebalance ----------------------------------------------------------------------

    def set_partitions(self, partitions: Sequence[int]) -> None:
        """Retarget the assigned partitions (follows the indexer's rebalance).
        Revoked partitions purge their aggregates — resident rows, spill AND
        poison marks — because the plane stops folding them and a stale row
        must never be servable. Granted partitions re-anchor at offset 0: the
        refresh loop refolds the whole partition through fresh admissions, so
        a revoke→re-grant cycle can never double-fold an event."""
        new = sorted(set(partitions))
        if new == self.partitions:
            return
        removed = [p for p in self.partitions if p not in new]
        added = [p for p in new if p not in self.partitions]
        self.partitions = new
        for p in removed:
            self._watermarks.pop(p, None)
            self._anchor_gen[p] = self._anchor_gen.get(p, 0) + 1
            self._purge_partition(p)
        for p in added:
            self._purge_partition(p)  # defensive: must never double-fold
            self._watermarks[p] = 0
            self._anchor_gen[p] = self._anchor_gen.get(p, 0) + 1
        if self.flight is not None:
            self.flight.record("resident.re-anchor", granted=added,
                               revoked=removed, resident=len(self._dir))
        self._record_gauges()

    # -- materialized views (surge_tpu.replay.views) ------------------------------------

    def attach_views(self, views) -> None:
        """Hand the plane the engine's :class:`MaterializedViews`: every
        committed refresh round (and the cold-start seed) folds into them,
        and every re-anchor path drops their per-partition partials."""
        self._views = views

    def register_view(self, vdef) -> None:
        """Register a view against this plane's feed. Before the seed it
        simply activates (the seed fold covers it from offset 0); on a
        seeded plane it parks PENDING and the refresh loop backfills the
        already-folded prefix between rounds — registration never races a
        fold."""
        if self._views is None:
            raise RuntimeError(
                "no MaterializedViews attached to this resident plane")
        self._views.register(vdef, active=not self._seeded)

    def _backfill_pending_views(self) -> None:
        """Executor half of register-while-running: re-read each assigned
        partition's committed prefix [0, watermark) and fold it into every
        pending view. Runs between refresh rounds (the loop awaits it), so
        it never races a fold; a rebalance landing mid-backfill is fenced
        exactly like the seed — partitions whose anchor generation moved are
        dropped from the commit."""
        views = self._views
        gens = {p: self._anchor_gen.get(p, 0) for p in self.partitions}
        wms = {p: self._watermarks.get(p, 0) for p in gens}
        logs: Dict[str, list] = {}
        part_of: Dict[str, int] = {}
        for p, wm in wms.items():
            if wm <= 0:
                continue
            for rec in page_keyed_records(self.log, self.events_topic, p,
                                          upto=wm):
                ev = self._encode_checked(rec.key, rec.value, p)
                if ev is None:
                    logs.pop(rec.key, None)
                    continue
                logs.setdefault(rec.key, []).append(ev)
                part_of[rec.key] = p
        committed = {p: wm for p, wm in wms.items()
                     if p in self._watermarks
                     and self._anchor_gen.get(p, 0) == gens[p]}
        for name in [v["view"] for v in views.summary() if not v["active"]]:
            views.fold_view_backfill(name, logs, part_of, committed)

    def _purge_partition(self, p: int) -> None:
        if self._views is not None:
            self._views.drop_partition(p)
        for agg in [a for a, ap in self._agg_part.items() if ap == p]:
            slot = self._dir.pop(agg, None)
            if slot is not None:
                self._free.append(slot)
            self._spill.pop(agg, None)
            self._lru.pop(agg, None)
            self._agg_part.pop(agg, None)
        for agg in [a for a, ap in self._poisoned.items() if ap == p]:
            self._poisoned.pop(agg, None)

    # -- refresh loop -------------------------------------------------------------------

    async def _refresh_loop(self) -> None:
        backoff = 0.25
        while True:
            try:
                t0 = time.perf_counter()
                if await self._refresh_once():
                    backoff = 0.25
                    # PACE the loop: at most one fold round per refresh
                    # interval. Without this a continuous publisher turns the
                    # loop into a spin — hundreds of tiny rounds/s each
                    # paying the poll+dispatch overhead — instead of one
                    # round per interval folding the whole committed batch.
                    # The interval is therefore also the plane's staleness
                    # cadence (docs/replay.md).
                    spent = time.perf_counter() - t0
                    if spent < self._poll_timeout:
                        await asyncio.sleep(self._poll_timeout - spent)
                    continue
                await self._wait_for_any_append()
                backoff = 0.25
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep the plane alive
                logger.exception("resident refresh round failed; retrying "
                                 "in %.2fs", backoff)
                try:
                    self.on_signal("surge.replay.resident.refresh-error",
                                   "error")
                except Exception:  # noqa: BLE001
                    logger.exception("on_signal failed")
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    async def _wait_for_any_append(self) -> None:
        if not self.partitions:
            await asyncio.sleep(self._poll_timeout)
            return
        waiters = [asyncio.ensure_future(
            self.log.wait_for_append(self.events_topic, p,
                                     self._watermarks.get(p, 0)))
            for p in self.partitions]
        try:
            await asyncio.wait(waiters, timeout=self._poll_timeout,
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            for w in waiters:
                if not w.done():
                    w.cancel()
                else:
                    w.exception()  # retrieve, avoid un-awaited warnings

    def _poll_batches(self, watermarks: Dict[int, int]):
        """Executor half of the poll: read each partition's committed tail
        past its watermark. Log reads stat/open real files on a FileLog —
        polling ON the loop every interval is exactly the latency tax the
        command path must not pay. Returns ``(batches, ends)`` — ``ends``
        carries every polled partition's end offset for gauge/fast-forward
        use without another on-loop log call.

        The PR-6 sustained-fold wall was this read's host-side decode: on a
        FileLog these reads now ride the native record-index decoder
        (csrc/txn.cc ``surge_seg_index`` via ``segment.decode_records``),
        guarded by the same ``surge.log.native.enabled`` fallback flag as
        the broker hot path — unbuilt/disabled checkouts keep the pure-
        Python uvarint walk, record-identical."""
        batches: Dict[int, list] = {}
        ends: Dict[int, int] = {}
        for p, wm in watermarks.items():
            recs = self.log.read(self.events_topic, p, wm,
                                 max_records=self._max_poll)
            if recs:
                batches[p] = recs
                ends[p] = recs[-1].offset + 1
            else:
                ends[p] = self.log.end_offset(self.events_topic, p)
        return batches, ends

    async def _refresh_once(self) -> bool:
        """One refresh round: read each partition's committed tail, fold it
        into the slab (admitting/evicting as needed), advance watermarks.
        Returns False when nothing was pending."""
        loop = asyncio.get_running_loop()
        if self._views is not None and self._views.has_pending:
            # register-while-running: backfill the committed prefix into the
            # pending views BETWEEN rounds (the loop awaits; no fold races)
            await loop.run_in_executor(None, self._backfill_pending_views)
        wms = {p: self._watermarks.setdefault(p, 0)
               for p in list(self.partitions)}
        gens = {p: self._anchor_gen.get(p, 0) for p in wms}
        feed_t0 = time.perf_counter()
        batches, ends = await loop.run_in_executor(
            None, self._poll_batches, wms)
        self._last_ends = ends
        for p, end in ends.items():
            if (p in batches or p not in self._watermarks
                    or self._anchor_gen.get(p, 0) != gens[p]):
                continue
            if end > self._watermarks[p]:
                # compaction hole at the tail: fast-forward like the indexer
                self._watermarks[p] = end
        if not batches:
            self._record_gauges()
            return False
        t0 = time.perf_counter()
        self._round_acc = self._fresh_round_acc()
        # the heavy host-side work — per-record deserialize + tensor encode —
        # runs OFF the event loop: a fold round must not stall the command
        # path it shares the loop with (only state mutation + the program
        # dispatches run on-loop, in await-free sections)
        logs, part_of, n_events, poisons = await loop.run_in_executor(
            None, self._decode_batches, batches)
        feed_s = time.perf_counter() - feed_t0
        if self.metrics is not None:
            # the feed's host leg: committed-tail read (native record-index
            # views) + event deserialize (one batch decode on the native
            # feed) — what the ≥100k ev/s sustained-fold target is about
            self.metrics.resident_feed_timer.record_ms(feed_s * 1000.0)
        for agg, p in poisons.items():
            self._poison(agg, p)
        enc_s = time.perf_counter() - t0
        ids = list(logs)
        # capacity-bounded fold groups (a round's distinct aggregates can
        # exceed the slab; each group admits/evicts then folds)
        try:
            for lo in range(0, len(ids), self.capacity):
                group = ids[lo: lo + self.capacity]
                await self._fold_group(group, logs, part_of, gens)
        except Exception:
            # a mid-round failure leaves the groups committed SO FAR folded
            # past the round's (un-advanced) watermarks — the retry would
            # refold their events (double-fold). Re-anchor every polled
            # partition through the re-grant path: purge + watermark 0 + gen
            # bump, so the next rounds refold each partition from scratch
            # (the golden-tested never-double-fold route).
            for p in batches:
                if (p in self._watermarks
                        and self._anchor_gen.get(p, 0) == gens.get(p, 0)):
                    self._purge_partition(p)
                    self._watermarks[p] = 0
                    self._anchor_gen[p] = self._anchor_gen.get(p, 0) + 1
            # a donated dispatch that failed AFTER consuming its inputs
            # leaves no slab to serve from — rebuild it empty and re-anchor
            # EVERY tracked partition for refold (the never-double-fold route)
            self._recover_if_slab_deleted()
            raise
        committed: Dict[int, int] = {}
        for p, recs in batches.items():
            # skip partitions revoked OR re-anchored (revoke→re-grant) while
            # the round flew: overwriting a re-grant's 0-anchor would skip
            # the whole-partition refold
            if (p in self._watermarks
                    and self._anchor_gen.get(p, 0) == gens[p]):
                self._watermarks[p] = recs[-1].offset + 1
                committed[p] = recs[-1].offset + 1
        if (self._views is not None and committed
                and self._views.active_or_pending):
            # the views' leg of the round rides the same decoded logs, under
            # the same gen fence the slab commit just passed — one columnar
            # encode per committed partition, shared by every view. Off-loop:
            # the view scans are device dispatches the command path must not
            # share the loop with. fold_round never raises (a failing view
            # degrades alone); the plane's watermark advance above stands.
            await loop.run_in_executor(
                None, self._views.fold_round, logs, part_of, committed)
        elapsed = time.perf_counter() - t0
        self.stats["rounds"] += 1
        self.stats["folded_events"] += n_events
        if self.metrics is not None:
            self.metrics.resident_fold_round_timer.record_ms(elapsed * 1000.0)
        if self.profiler is not None:
            # the incremental-fold stage of the per-stage replay profile:
            # encode (host pack) reported separately, the umbrella `refresh`
            # covers encode+h2d+dispatch of the round (the h2d rides the
            # dispatch on this path — nothing is transferred ahead of it)
            self.profiler.record("encode", enc_s, kind="refresh")
            # the umbrella span carries its measured device legs so the
            # command anatomy decomposes it instead of binning the whole
            # round into `other` (the stage spans map by name; an umbrella
            # maps by attributes — anatomy claims one or the other)
            self.profiler.record(
                "refresh", elapsed, events=n_events, aggregates=len(ids),
                **{"leg.decode-ms": round(feed_s * 1000.0, 3),
                   "leg.dispatch-ms": round(
                       self._round_acc["dispatch_s"] * 1000.0, 3)})
        self._observe_round(n_events, feed_s, enc_s)
        self._record_gauges()
        if (self._faults is not None
                and self._faults.corrupt_point("corrupt.slab-row")):
            # corruption-to-page e2e: rot one live row AFTER the round
            # committed — the log stays right, the slab lies
            corrupted = self._corrupt_resident_row()
            if corrupted is not None and self.flight is not None:
                self.flight.record("fault.corrupt", site="corrupt.slab-row",
                                   aggregate=corrupted)
        return True

    def _slab_deleted(self) -> bool:
        if self._slab is None:
            return False
        leaf = next(iter(self._slab.values()))
        deleted = getattr(leaf, "is_deleted", None)
        return bool(deleted()) if callable(deleted) else False

    def _recover_if_slab_deleted(self) -> None:
        """Last-ditch donation recovery: a refresh dispatch that raised after
        donation consumed the slab left neither the old columns nor a result
        to rebind. Every resident/spilled row's provenance is the log, so the
        plane rebuilds EMPTY and re-anchors every tracked partition at 0 —
        the refresh loop refolds them from scratch exactly like a re-grant,
        which can never double-fold. No-op while the slab is live (the
        common failure path: the error fired before the dispatch consumed)."""
        if not self._slab_deleted():
            return
        for p in list(self._watermarks):
            self._purge_partition(p)
            self._watermarks[p] = 0
            self._anchor_gen[p] = self._anchor_gen.get(p, 0) + 1
        # defensive sweep: every row was consumed with the slab, so nothing
        # host-side may keep claiming residency or spill coverage
        self._dir.clear()
        self._spill.clear()
        self._lru.clear()
        self._agg_part.clear()
        self._free = list(range(self.capacity))
        self._slab = None
        self._ords = None
        self._ensure_device_state()
        logger.warning(
            "resident slab was consumed by a failed donated refresh "
            "dispatch; rebuilt empty and re-anchored %d partition(s) for "
            "refold", len(self._watermarks))

    def _observe_round(self, n_events: int, feed_s: float,
                       enc_s: float) -> None:
        """Device-observatory round close: the padding-waste gauges off the
        round's slot accounting and the ledger's ``round`` event. Always on —
        these are the instruments ROADMAP item 2's bucketing work is judged
        against, and a waste spike you only see under DEBUG never pages."""
        acc = self._round_acc
        dispatched, occupied = acc["dispatched"], acc["occupied"]
        waste = waste_ratio(dispatched, occupied)
        dispatch_us = acc["dispatch_s"] * 1e6
        deal = self._meshp.last_deal if self._meshp is not None else None
        lane_slots = acc["lane_slots"]
        if self.metrics is not None:
            m = self.metrics
            m.resident_round_events.record(n_events)
            m.resident_padding_waste_ratio.record(waste)
            m.resident_dispatch_occupancy.record(
                occupied / dispatched if dispatched else 0.0)
            m.resident_events_per_dispatch_us.record(
                n_events / dispatch_us if dispatch_us > 0 else 0.0)
            m.resident_shard_skew.record(shard_skew(deal))
            m.resident_bucket_dispatches.record(acc["programs"])
            m.resident_bucket_fill_ratio.record(
                acc["lanes"] / lane_slots if lane_slots else 0.0)
        if self.ledger is not None:
            causes, self._round_causes = self._round_causes, {}
            self.ledger.record_round(
                events=n_events, lanes=acc["lanes"], windows=acc["windows"],
                dispatched=dispatched, occupied=occupied,
                batch=acc["batch"], width=acc["width"],
                feed_us=feed_s * 1e6, encode_us=enc_s * 1e6,
                dispatch_us=dispatch_us, deal_sizes=deal,
                causes=causes or None, evictions=acc["evictions"],
                buckets=acc["buckets"] or None,
                bucket_table=len(self.bucket_table))

    def _decode_batches(self, batches: Dict[int, list]):
        """Executor half of a refresh round: deserialize + encode every
        record, grouping events per aggregate. Pure w.r.t. plane state —
        poison candidates are RETURNED (``{agg: partition}``) and applied on
        the loop, so the reader lane never observes a half-applied poison.

        With a batch deserializer wired (the native feed), the whole
        round's payloads decode in ONE call per partition; a batch that
        fails (a poisoned payload hiding inside) falls back to the
        per-event path, which locates and poisons the offender exactly as
        the pre-batch feed did."""
        logs: Dict[str, list] = {}
        part_of: Dict[str, int] = {}
        n_events = 0
        poisons: Dict[str, int] = {}
        poisoned = self._poisoned
        batch_decode = self.deserialize_events
        for p, recs in batches.items():
            pend = []
            for r in recs:
                key = r.key
                if (key is None or r.value is None or key in poisoned
                        or key in poisons):
                    continue
                pend.append((key, r.value))
            if not pend:
                continue
            events = None
            if batch_decode is not None:
                try:
                    events = batch_decode([v for _k, v in pend])
                    if len(events) != len(pend):  # pragma: no cover — a
                        events = None  # misbehaving custom batch decoder
                except Exception:  # noqa: BLE001 — per-event path poisons
                    events = None
            if events is not None:
                encode = self.encode_event
                schema_for = self.spec.registry.schema_for_cls
                for (key, _raw), ev in zip(pend, events):
                    if key in poisons:
                        continue
                    try:
                        if encode is not None:
                            ev = encode(ev)
                        schema_for(type(ev))
                    except Exception:  # noqa: BLE001 — per-agg degradation
                        poisons[key] = p
                        logs.pop(key, None)
                        continue
                    logs.setdefault(key, []).append(ev)
                    part_of[key] = p
                    n_events += 1
                continue
            for key, raw in pend:
                if key in poisons:
                    continue
                try:
                    ev = self._encode_event(raw)
                except Exception:  # noqa: BLE001 — per-aggregate degradation
                    poisons[key] = p
                    logs.pop(key, None)
                    continue
                logs.setdefault(key, []).append(ev)
                part_of[key] = p
                n_events += 1
        return logs, part_of, n_events, poisons

    def _encode_event(self, raw: bytes) -> Any:
        """Deserialize + producer-encode one record and check its type rides
        the replay schema; raises when it can't (callers poison the
        aggregate). Pure w.r.t. plane state — safe in the executor."""
        ev = self.deserialize_event(raw)
        if self.encode_event is not None:
            ev = self.encode_event(ev)
        self.spec.registry.schema_for_cls(type(ev))
        return ev

    def _encode_checked(self, agg_id: str, raw: bytes,
                        partition: int) -> Any:
        """:meth:`_encode_event`, or None when the aggregate cannot ride the
        tensor path. Events outside the replay schema (or failing the
        producer's encode) poison their aggregate: the plane stops tracking
        it — reads fall back to the host KV store, whose scalar fold handles
        every event type — instead of wedging the refresh loop."""
        if agg_id in self._poisoned:
            return None
        try:
            return self._encode_event(raw)
        except Exception:  # noqa: BLE001 — per-aggregate degradation
            self._poison(agg_id, partition)
            return None

    def _poison(self, agg_id: str, partition: int) -> None:
        self._poisoned[agg_id] = partition
        slot = self._dir.pop(agg_id, None)
        if slot is not None:
            self._free.append(slot)
        self._spill.pop(agg_id, None)
        self._lru.pop(agg_id, None)
        self._agg_part.pop(agg_id, None)
        if not self._warned_poison:
            self._warned_poison = True
            logger.warning(
                "aggregate %s emitted an event type outside the replay "
                "schema; it (and any later such aggregate) is served from "
                "the host store only", agg_id)

    def _encode_pack_group(self, event_logs: List[list]):
        """Executor half of one fold group: ragged encode + wire pack of
        every refresh plan. Pure — touches no plane state.

        Returns ``(b, plans)``. Each plan is one fused program dispatch
        shape: ``("win", sel, lanes_b, width, wins)`` for the jit rectangle
        fold (``wins = [(packed, side, counts), ...]`` chained windows) or
        ``("rag", sel, lanes_b, width, (packed_flat, sides, starts, wins))``
        for the ragged Pallas tile (``wins = [(t_base, counts), ...]``).
        ``sel`` indexes the plan's lanes back into the group.

        Dense dispatch is ONE plan covering the whole group at the
        ``_pow8(b) × _pow2(max_len)`` rectangle. Bucketed dispatch deals
        lanes into pow2 LENGTH buckets first, so a steady ragged round (many
        1–5-event lanes under one long tail) stops paying the long lane's
        width across every short lane — each occupied bucket dispatches its
        own ``_pow2(lanes, 8) × bucket_width`` grid and the union of scatters
        still lands on disjoint slots (every lane is in exactly one bucket),
        which is what keeps the fold byte-identical to the dense path."""
        b = len(event_logs)
        if self._refresh_dispatch == "dense":
            enc = encode_events(self.spec.registry, event_logs)
            # window width adapts to the batch's tail length (bucketed pow2
            # under the configured cap): a steady incremental round folds 1–5
            # events per aggregate, and scanning the full 512-step cold-start
            # window for it would make every refresh ~100x more device work
            # than its events
            width = min(self._window, _pow2(enc.max_len))
            sel = np.arange(b, dtype=np.int64)
            return b, [("win", sel, _pow8(b), width,
                        self._pack_windows(enc, _pow8(b), width))]
        lens = np.fromiter((len(ev) for ev in event_logs), dtype=np.int64,
                           count=b)
        deal: Dict[int, list] = {}
        for i in range(b):
            wb = min(self._window, _pow2(max(int(lens[i]), 1), 2))
            deal.setdefault(wb, []).append(i)
        plans = []
        for wb in sorted(deal):
            sel = np.asarray(deal[wb], dtype=np.int64)
            enc = encode_events(self.spec.registry,
                                [event_logs[i] for i in sel])
            lanes_b = _pow2(len(sel))
            if self._ragged and not self._mesh_local:
                plans.append(("rag", sel, lanes_b, wb,
                              self._pack_ragged(enc, lanes_b, wb)))
            else:
                plans.append(("win", sel, lanes_b, wb,
                              self._pack_windows(enc, lanes_b, wb)))
        return b, plans

    def _pack_windows(self, enc, lanes_b: int, width: int):
        """Chained dense windows of one plan: ``[(packed, side, counts)]``."""
        wins = []
        for s in range(0, enc.max_len, width):
            e = min(s + width, enc.max_len)
            packed, side = self._wire.pack_window(
                enc.type_ids, enc.cols, s, e, width, lanes_b)
            counts = np.zeros((lanes_b,), dtype=np.int32)
            counts[:enc.batch_size] = np.clip(enc.lengths - s, 0, width)
            wins.append((packed, side, counts))
        return wins

    def _pack_ragged(self, enc, lanes_b: int, width: int):
        """Flat-pack one bucket for the ragged Pallas tile: the bucket's
        events concatenate lane-contiguous into ONE packed buffer of
        ``_pow2(total)`` rows (pad rows carry type −1, which packs to the
        pad sentinel and folds as carry-through), with per-lane start
        offsets; chained windows shift the starts instead of re-packing."""
        nb, t = enc.batch_size, enc.max_len
        total = int(enc.lengths.sum())
        rows_b = _pow2(max(total, 1))
        mask = np.arange(t, dtype=np.int64)[None, :] < enc.lengths[:, None]
        flat_tids = np.full((rows_b,), -1, dtype=enc.type_ids.dtype)
        flat_tids[:total] = enc.type_ids[mask]
        flat_cols = {}
        for name, col in enc.cols.items():
            buf = np.zeros((rows_b,), dtype=col.dtype)
            buf[:total] = col[mask]
            flat_cols[name] = buf
        packed, sides = self._wire.pack_flat(flat_tids, flat_cols)
        starts = np.zeros((lanes_b,), dtype=np.int32)
        starts[1:nb] = np.cumsum(enc.lengths[:-1], dtype=np.int64)[:nb - 1]
        wins = []
        for s in range(0, t, width):
            counts = np.zeros((lanes_b,), dtype=np.int32)
            counts[:nb] = np.clip(enc.lengths - s, 0, width)
            wins.append((s, counts))
        return packed, sides, starts, wins

    async def _fold_group(self, group: List[str], logs: Dict[str, list],
                          part_of: Dict[str, int],
                          gens: Dict[int, int]) -> None:
        """Admit + fold one ≤capacity group of aggregates' new events.

        Encode+pack AND the window dispatches run in the executor (an XLA
        dispatch/compile releases the GIL; keeping it off the loop keeps the
        command path's latency flat while the plane folds). Correctness
        across the awaits rests on DEFERRED COMMIT: slots are reserved but
        the directory, spill and watermarks only change after the fold
        lands — a concurrent read of an admitting aggregate is served from
        its (exact, pre-batch) spill row or falls back, never from a
        half-admitted slab row. A rebalance racing the fold is detected at
        commit (the partition left ``_watermarks``, or its anchor generation
        moved — a revoke→re-grant pair both purges AND re-anchors, so the
        stale fold must not land) and its aggregates' reservations are
        rolled back."""
        b, plans = await asyncio.get_running_loop().run_in_executor(
            None, self._encode_pack_group, [logs[a] for a in group])

        # -- sync: evict + reserve slots + per-lane admission rows ----------
        # reservation stays GROUP-level (one evict pass, one slot per lane);
        # each plan below slices its lanes' rows out of these flat arrays
        admit_ids = [a for a in group if a not in self._dir]
        short = len(admit_ids) - len(self._free)
        if short > 0:
            self._evict(short, protect=set(group))
        init = self.spec.init_state_tree()
        new_slots: Dict[str, int] = {}
        slot_of = np.empty((b,), dtype=np.int32)
        admit_lane = np.zeros((b,), dtype=bool)
        admit_ord_of = np.zeros((b,), dtype=np.int32)
        admit_val_of = {f.name: np.full((b,), init[f.name], dtype=f.dtype)
                        for f in self._fields}
        for i, agg in enumerate(group):
            s = self._dir.get(agg)
            if s is not None:
                slot_of[i] = s
                continue
            slot = self._free.pop()
            new_slots[agg] = slot
            slot_of[i] = slot
            admit_lane[i] = True
            spilled = self._spill.get(agg)  # peek — popped at commit
            if spilled is not None:
                row, ordinal = spilled
                admit_ord_of[i] = ordinal
                for k in admit_val_of:
                    admit_val_of[k][i] = row[k]

        # -- dispatch off-loop (reads keep serving from the pinned slab) ----
        # every lane is in exactly one plan, so each plan's admissions are
        # the group's admits restricted to its lanes and the plans' scatters
        # hit disjoint slots — dispatch order cannot change the fold
        slab, ords = self._slab, self._ords
        loop = asyncio.get_running_loop()
        acc = self._round_acc
        acc["lanes"] += b
        for plan in plans:
            slab, ords = await self._dispatch_plan(
                loop, plan, slab, ords, slot_of, admit_lane, admit_ord_of,
                admit_val_of, init)

        # -- sync commit: publish the folded slab + directory ---------------
        self._slab, self._ords = slab, ords
        for agg in group:
            p = part_of[agg]
            if (p not in self._watermarks      # revoked while the fold flew
                    or self._anchor_gen.get(p, 0) != gens.get(p, 0)):
                # ...or re-anchored (revoke→re-grant): either way this fold
                # used the OLD anchor's carry/events — roll the agg back
                slot = new_slots.pop(agg, None)
                if slot is not None:
                    self._free.append(slot)
                continue
            slot = new_slots.get(agg)
            if slot is not None:
                self._dir[agg] = slot
                self._spill.pop(agg, None)
            elif agg not in self._dir:
                continue  # purged mid-flight; stays purged
            self._agg_part[agg] = p
            self._touch(agg)

    async def _dispatch_plan(self, loop, plan, slab, ords,
                             slot_of: np.ndarray, admit_lane: np.ndarray,
                             admit_ord_of: np.ndarray,
                             admit_val_of: Dict[str, np.ndarray], init):
        """Dispatch one refresh plan's chained windows. Pads the plan's
        admission/lane arrays to its ``lanes_b`` bucket (so every window of a
        bucket shares ONE compiled signature — shape churn is what turns
        steady folds into compile storms), runs each window in the executor,
        and — when donation is on — republishes ``self._slab`` after every
        dispatch so readers re-pin live buffers (the consumed predecessor
        would raise on them; directory/spill commit stays deferred, so
        mid-round rows are folds of committed per-lane prefixes — valid
        bounded-stale states under the plane's consistency model)."""
        mode, sel, lanes_b, width, payload = plan
        nb = len(sel)
        adm = sel[admit_lane[sel]]
        admit_idx = np.full((lanes_b,), self.capacity, dtype=np.int32)
        admit_idx[:len(adm)] = slot_of[adm]
        admit_ord = np.zeros((lanes_b,), dtype=np.int32)
        admit_ord[:len(adm)] = admit_ord_of[adm]
        admit_vals = {f.name: np.full((lanes_b,), init[f.name], dtype=f.dtype)
                      for f in self._fields}
        for k in admit_vals:
            admit_vals[k][:len(adm)] = admit_val_of[k][adm]
        lane_slots = np.full((lanes_b,), self.capacity, dtype=np.int32)
        lane_slots[:nb] = slot_of[sel]

        if mode == "rag":
            packed_flat, sides_flat, starts, wins = payload
            rows_b = packed_flat.shape[0]
            sig = ("refresh-ragged", lanes_b, width, rows_b)
            prog = self._ragged_program(lanes_b, width, rows_b)
        else:
            wins = payload
            sig = ("refresh", lanes_b, width)
            prog = (self._meshp.refresh if self._mesh_local
                    else self._refresh_prog)
        fresh = sig not in self._signatures
        self._signatures.add(sig)
        acc = self._round_acc
        acc["batch"] = lanes_b
        acc["width"] = width
        acc["programs"] += 1
        acc["lane_slots"] += lanes_b
        occupied = 0
        faults = self._faults
        donate = self._donate_refresh
        first = True
        noop_ord = np.zeros((lanes_b,), dtype=np.int32)
        noop_idx = np.full((lanes_b,), self.capacity, dtype=np.int32)
        noop_vals = None  # built once on the first later window
        for win in wins:
            if first:
                ai, av, ao = admit_idx, admit_vals, admit_ord
                first = False
            else:  # later windows: no-op admissions (all-scratch; the jitted
                # program never mutates its inputs, so one dict serves all)
                if noop_vals is None:
                    noop_vals = {
                        f.name: np.full((lanes_b,), init[f.name],
                                        dtype=f.dtype) for f in self._fields}
                ai, av, ao = noop_idx, noop_vals, noop_ord
            if mode == "rag":
                t_base, counts = win
                run = functools.partial(
                    prog, slab, ords, ai, av, ao, lane_slots, counts,
                    packed_flat, sides_flat,
                    (starts + t_base).astype(np.int32))
            else:
                packed, side, counts = win
                run = functools.partial(prog, slab, ords, ai, av,
                                        ao, lane_slots, counts, packed, side)
            if faults is not None:
                # the stall-anatomy e2e's site, INSIDE the executor thunk so
                # an armed delay lands in the dispatch stage's measured time
                run = functools.partial(
                    (lambda f, thunk: (f.point("resident.refresh.dispatch"),
                                       thunk())[1]), faults, run)
            d0 = time.perf_counter()
            if self.profiler is None:
                slab, ords = await loop.run_in_executor(None, run)
            else:
                with self.profiler.stage("compile" if fresh else "dispatch",
                                         width=width, batch=lanes_b):
                    slab, ords = await loop.run_in_executor(None, run)
                fresh = False
            if donate:
                self._slab, self._ords = slab, ords
            # padding-waste accounting: the program always runs the full
            # lanes_b × width slot grid; counts carries the occupied slots
            acc["windows"] += 1
            acc["dispatched"] += lanes_b * width
            acc["occupied"] += int(counts.sum())
            occupied += int(counts.sum())
            acc["dispatch_s"] += time.perf_counter() - d0
        acc["buckets"].append({
            "width": width, "lanes_b": lanes_b, "lanes": nb,
            "windows": len(wins), "dispatched": lanes_b * width * len(wins),
            "occupied": occupied, "ragged": mode == "rag" or None})
        return slab, ords

    def _ragged_program(self, lanes_b: int, width: int, rows_b: int):
        """The fused ragged refresh program (admission scatter → Pallas
        ragged tile walking the flat packed buffer by per-lane offsets →
        scatter back), cached per (lanes_b, width, rows_b) shape and donated
        like the rectangle jit."""
        key = (lanes_b, width, rows_b)
        prog = self._ragged_progs.get(key)
        if prog is not None:
            return prog
        import jax

        from surge_tpu.replay.pallas_fold import make_ragged_fold

        wire = self._wire
        tile = make_ragged_fold(self.spec, wire, width, lanes_b, rows_b, 1)

        def refresh_ragged(slab, ords, admit_idx, admit_vals, admit_ord,
                           lane_slots, counts, packed, sides, starts):
            slab = {k: v.at[admit_idx].set(admit_vals[k])
                    for k, v in slab.items()}
            ords = ords.at[admit_idx].set(admit_ord)
            carry = {k: v[lane_slots] for k, v in slab.items()}
            words = wire.expand_flat(packed)
            out = tile(carry, words, sides, starts, counts, ords[lane_slots])
            slab = {k: v.at[lane_slots].set(out[k]) for k, v in slab.items()}
            ords = ords.at[lane_slots].add(counts)
            return slab, ords

        prog = jax.jit(refresh_ragged,
                       donate_argnums=(0, 1) if self._donate_refresh else ())
        self._ragged_progs[key] = prog
        return prog

    def _touch(self, agg_id: str) -> None:
        self._tick += 1
        self._lru[agg_id] = self._tick

    def _evict(self, n: int, protect: set) -> None:
        """Pull the n least-recently-touched unprotected rows to the host
        spill and free their slots (the one small d2h the plane ever does
        outside reads; a spilled row re-admits at its exact fold point)."""
        victims = sorted((a for a in self._dir if a not in protect),
                         key=lambda a: self._lru.get(a, 0))[:n]
        if len(victims) < n:
            raise RuntimeError(
                f"resident slab cannot hold the refresh batch: need {n} more "
                f"slots, only {len(victims)} evictable "
                f"(capacity {self.capacity})")
        idx = np.fromiter((self._dir[v] for v in victims), dtype=np.int32,
                          count=len(victims))
        rows, ords = self._pull_positions(self._slab, idx, ords=self._ords)
        for j, v in enumerate(victims):
            self._spill[v] = ({k: rows[k][j] for k in rows}, int(ords[j]))
            self._free.append(self._dir.pop(v))
            self._lru.pop(v, None)
        self.stats["evictions"] += len(victims)
        self._round_acc["evictions"] += len(victims)
        if self.metrics is not None:
            self.metrics.resident_evictions.record(len(victims))
        if self.flight is not None:
            self.flight.record("resident.evict", count=len(victims),
                               resident=len(self._dir),
                               spilled=len(self._spill))
        if self.ledger is not None:
            self.ledger.record_evict(len(victims), resident=len(self._dir),
                                     cause="capacity")

    # -- pulls / decode -----------------------------------------------------------------

    def _pull_positions(self, slab, positions: np.ndarray, ords=None):
        """Wide (u32) gather of ``positions`` rows + one fetch; returns
        ``({field: np[k]}, ordinals np[k])`` decoded to schema dtypes."""
        if ords is None:
            import jax.numpy as jnp

            ords = jnp.zeros((int(np.max(positions, initial=0)) + 1,),
                             dtype=jnp.int32)
        k = len(positions)
        k_b = _pow2(max(k, 1))
        idx = np.zeros((k_b,), dtype=np.int32)
        idx[:k] = positions
        mat, o = self._gather_wide(slab, ords, idx)
        mat = np.asarray(mat)  # the fetch barrier
        o = np.asarray(o)
        return self._decode_wide(mat, k), o[:k]

    def _decode_wide(self, mat: np.ndarray, k: int) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        row = 0
        for f, w in zip(self._fields, self._wide_words):
            dev = self._dev_dts[f.name]
            dt = self._dtypes[f.name]  # widen back to the schema dtype
            raw = mat[row: row + w, :k]
            row += w
            if np.issubdtype(dev, np.floating) and dev.itemsize < 4:
                out[f.name] = raw[0].view(np.float32).astype(dt)
            elif dev == np.bool_ or dev.itemsize < 4:
                out[f.name] = raw[0].astype(dt)
            elif w > 1:  # w u32 word-rows -> (k, w) contiguous -> one column
                out[f.name] = np.ascontiguousarray(raw.T).view(dev)[:, 0]
            else:
                out[f.name] = raw[0].view(dev).astype(dt)
        return out

    def _decode_narrow(self, buf: np.ndarray, k: int, k_b: int
                       ) -> Optional[Dict[str, np.ndarray]]:
        """Decode the u16 gather buffer; None when a column overflowed (the
        caller refetches wide — exactness never depends on the guess)."""
        nf = len(self._fields)
        if not buf[nf * k_b:].all():
            return None
        out: Dict[str, np.ndarray] = {}
        for i, f in enumerate(self._fields):
            dt = self._dtypes[f.name]
            raw = buf[i * k_b: i * k_b + k]
            if dt == np.bool_:
                out[f.name] = raw.astype(dt)
            elif np.issubdtype(dt, np.signedinteger):
                out[f.name] = raw.view(np.int16).astype(dt)
            else:
                out[f.name] = raw.astype(dt)
        return out

    # -- read path ----------------------------------------------------------------------

    def lag_records(self) -> int:
        """Σ over assigned partitions of (end offset − fold watermark)."""
        return sum(self.partition_lag(p) for p in self.partitions)

    def partition_lag(self, p: int) -> int:
        return max(self.log.end_offset(self.events_topic, p)
                   - self._watermarks.get(p, 0), 0)

    def _ends_sync(self, parts: Sequence[int]) -> Dict[int, int]:
        return {p: self.log.end_offset(self.events_topic, p) for p in parts}

    async def _ends_for(self, parts: Sequence[int]) -> Dict[int, int]:
        """Live end-offset view for a read's freshness check. Local logs
        answer from memory/a stat; a remote (broker) log turns each call
        into a blocking RPC, so there the view rides the executor — the
        read path shares its event loop with the command path."""
        parts = [p for p in parts if p in self._watermarks]
        if not parts:
            return {}
        if self._remote_log:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._ends_sync, parts)
        return self._ends_sync(parts)

    def _fresh_enough(self, p: Optional[int], require_current: bool,
                      ends: Optional[Mapping[int, int]] = None) -> bool:
        if p is None or p not in self._watermarks:
            return False
        bound = 0 if require_current else self.max_lag
        if ends is not None:
            end = ends.get(p)
            if end is None:
                return False
            return max(end - self._watermarks.get(p, 0), 0) <= bound
        return self.partition_lag(p) <= bound

    #: fallback cause -> the EngineMetrics counter carrying its split
    _FALLBACK_CAUSE_SENSORS = {
        "lag-exceeded": "resident_fallbacks_lag",
        "lane-error": "resident_fallbacks_lane_error",
        "unschema-poison": "resident_fallbacks_poison",
        "untracked": "resident_fallbacks_untracked",
    }

    def _record_fallback(self, n: int = 1, cause: str = "untracked") -> None:
        """One or more reads fell back to the host store, and WHY:
        ``lag-exceeded`` (the partition's fold watermark is too stale for the
        read's bound), ``lane-error`` (the gather batch failed on device or
        in decode), ``unschema-poison`` (the aggregate emitted an event
        outside the replay schema and is host-served for good), ``untracked``
        (not resident/spilled, revoked, or the plane is stopped/unseeded).
        The flat total keeps its name; the splits ride
        ``surge.replay.resident.fallback-reads.<cause>``."""
        self.stats["fallbacks"] += n
        self.fallback_causes[cause] = self.fallback_causes.get(cause, 0) + n
        self._round_causes[cause] = self._round_causes.get(cause, 0) + n
        if self.metrics is not None:
            self.metrics.resident_fallbacks.record(n)
            getattr(self.metrics,
                    self._FALLBACK_CAUSE_SENSORS[cause]).record(n)

    async def read_state(self, aggregate_id: str, *,
                         require_current: bool = False
                         ) -> Tuple[bool, Any]:
        """Read one aggregate's state: ``(hit, state)``. A miss means the
        caller must fall back to the host KV store — not resident, revoked,
        poisoned, or the partition's fold watermark is too stale.

        ``require_current=True`` demands lag 0 on the aggregate's partition —
        the entity-init contract (processing a command on bounded-stale state
        would fork the aggregate); the default tolerates
        ``surge.replay.resident.max-lag-records`` (read-side projections)."""
        if self._stopped or not self._seeded:
            self._record_fallback()
            return (False, None)
        p = self._agg_part.get(aggregate_id)
        if p is None or p not in self._watermarks:
            self._record_fallback(cause="unschema-poison"
                                  if aggregate_id in self._poisoned
                                  else "untracked")
            return (False, None)
        ends = await self._ends_for((p,))
        if not self._fresh_enough(p, require_current, ends):
            self._record_fallback(cause="lag-exceeded")
            return (False, None)
        spilled = self._spill.get(aggregate_id)
        if spilled is not None:
            row, _ord = spilled
            return (True, self._state_of(aggregate_id,
                                         {k: np.asarray(v)
                                          for k, v in row.items()}, 0))
        if aggregate_id not in self._dir:
            self._record_fallback()
            return (False, None)
        fut = asyncio.get_running_loop().create_future()
        if not self._pending:
            self._pending_t0 = time.perf_counter()
        self._pending.append((aggregate_id, fut))
        self._touch(aggregate_id)
        self._kick_drain()
        return await fut

    async def read_bytes(self, aggregate_id: str, *,
                         require_current: bool = False
                         ) -> Tuple[bool, Optional[bytes]]:
        """:meth:`read_state` + the restore serialize chain — byte-identical
        to what the host KV store holds for the same fold point."""
        hit, state = await self.read_state(aggregate_id,
                                           require_current=require_current)
        if not hit:
            return (False, None)
        return (True, self.serialize_state(aggregate_id, state))

    async def read_many(self, aggregate_ids: Sequence[str], *,
                        require_current: bool = False) -> Dict[str, Any]:
        """Bulk read: ``{aggregate_id: state}`` for every id the plane can
        serve; misses (not tracked, stale, revoked, poisoned) are OMITTED —
        the caller overlays the host store. The whole call rides the gather
        lane as ONE queued item: a single future, one device gather shared
        with every concurrent reader, and a batch-materialized decode — the
        per-id asyncio machinery of :meth:`read_state` is paid once per call,
        which is what makes read-side projections cheaper than per-key host
        lookups at high concurrency."""
        if self._stopped or not self._seeded:
            self._record_fallback(len(aggregate_ids))
            return {}
        # freshness varies only by PARTITION: resolve each assigned
        # partition's lag once per call, not once per id. When EVERY assigned
        # partition is fresh (the steady state), the per-id loop disappears
        # entirely — untracked ids miss in the drain and fall back there,
        # exactly as a per-id check would have concluded.
        ends = await self._ends_for(self.partitions)
        if all(self._fresh_enough(p, require_current, ends)
               for p in self.partitions):
            ok: Sequence[str] = tuple(aggregate_ids)
        else:
            fresh: Dict[Optional[int], bool] = {None: False}
            ok_list: List[str] = []
            stale = 0
            part = self._agg_part
            for agg in aggregate_ids:
                p = part.get(agg)
                f = fresh.get(p)
                if f is None:
                    f = fresh[p] = self._fresh_enough(p, require_current,
                                                      ends)
                if f:
                    ok_list.append(agg)
                else:
                    stale += 1
            if stale:
                self._record_fallback(stale, cause="lag-exceeded")
            ok = ok_list
        if not ok:
            return {}
        fut = asyncio.get_running_loop().create_future()
        if not self._pending:
            self._pending_t0 = time.perf_counter()
        self._pending.append((ok, fut))
        self._kick_drain()
        return await fut

    async def project(self, aggregate_ids: Sequence[str], *,
                      require_current: bool = False) -> Dict[str, Any]:
        """Batched read-side projection — alias of :meth:`read_many`."""
        return await self.read_many(aggregate_ids,
                                    require_current=require_current)

    def _kick_drain(self) -> None:
        if not self._draining:
            self._draining = True
            # retained + reaped: if the drain task were GC'd mid-flight,
            # _draining would stay True forever and the gather lane would
            # wedge; an escaping failure logs instead of rotting
            spawn_reaped(self._drain_tasks, self._drain_reads(),
                         "resident gather-lane drain")

    async def _drain_reads(self) -> None:
        """The gather lane: coalesce every queued read — single ``read_state``
        futures and whole ``read_many`` groups alike — into one device gather
        + a single fetch-barriered pull (u16 wire when the schema allows)."""
        loop = asyncio.get_running_loop()
        try:
            while self._pending:
                batch, self._pending = self._pending, []
                # coalesce wait: first enqueue of this batch → drain start
                # (the gather-coalesce leg of the read's device anatomy)
                t0, self._pending_t0 = self._pending_t0, None
                wait_s = max(time.perf_counter() - t0, 0.0) if t0 else 0.0
                try:
                    await self._drain_batch(loop, batch, wait_s)
                except Exception:  # noqa: BLE001 — the plane is an optimization:
                    # a device/decode failure must fail the batch over to the
                    # host KV store, never strand its futures (an entity init
                    # awaiting one would hang forever, commands queuing behind
                    # it — the exact case the host fallback exists for)
                    logger.exception(
                        "resident gather batch failed; failing %d read(s) "
                        "over to the host store", len(batch))
                    try:
                        self.on_signal("surge.replay.resident.gather-error",
                                       "error")
                    except Exception:  # noqa: BLE001
                        logger.exception("on_signal failed")
                    n = 0
                    for target, fut in batch:
                        if not fut.done():
                            n += 1
                            fut.set_result((False, None)
                                           if isinstance(target, str) else {})
                    if n:
                        self._record_fallback(n, cause="lane-error")
        finally:
            self._draining = False

    async def _drain_batch(self, loop, batch, wait_s: float = 0.0) -> None:
        # snapshot slots atomically on the loop; ids evicted since
        # enqueue are served from their (exact) spill rows instead.
        # refs per id: gather position, ("spill", row) or None=miss;
        # refs is None for the common all-resident call, whose gather
        # rows are the contiguous range [start, start+len(ids)) —
        # results then assemble via one C-speed dict(zip(...))
        calls = []
        gather_ids: List[str] = []
        slots: List[int] = []
        dir_get, spill_get = self._dir.get, self._spill.get
        for target, fut in batch:
            if fut.done():
                continue
            single = isinstance(target, str)
            ids = (target,) if single else target
            start = len(slots)
            refs: Optional[List[Any]] = None
            looked = [dir_get(a) for a in ids]
            if None not in looked:  # all resident: pure C-speed path
                slots.extend(looked)
                gather_ids.extend(ids)
            else:
                refs = []
                for agg, slot in zip(ids, looked):
                    if slot is not None:
                        refs.append(len(slots))
                        slots.append(slot)
                        gather_ids.append(agg)
                    else:
                        spilled = spill_get(agg)
                        refs.append(("spill", spilled[0])
                                    if spilled is not None else None)
            calls.append((fut, single, ids, refs, start))
        states: list = []
        if slots:
            k = len(slots)
            k_b = _pow2(k)
            # pad with the first LIVE slot, not the scratch row: the
            # u16 fit flags scan every gathered value, and scratch
            # garbage would force the wide refetch on every read
            idx = np.full((k_b,), slots[0], dtype=np.int32)
            idx[:k] = slots
            off_loop = self._fetch_off_loop
            rows: Optional[Dict[str, np.ndarray]] = None
            # device-leg clocks for the observatory: dispatch (gather program
            # call), fetch-barrier (the d2h asarray), decode (buffer → rows →
            # domain states) — a u16 overflow refetch accumulates both passes
            disp_s = fetch_s = dec_s = 0.0
            # a DONATED refresh window may consume the pinned slab between
            # the dispatch below and its fetch (the fold runs in the
            # executor concurrently) — the deleted-buffer error re-pins the
            # republished slab and retries; a persistent failure falls
            # through to the gather lane's host failover
            for attempt in range(3):
                # pin: a fold may replace self._slab/_ords mid-drain
                slab, s_ords = self._slab, self._ords
                rows = None
                try:
                    t = time.perf_counter()
                    if self._gather_narrow is not None:
                        buf = self._gather_narrow(slab, idx)  # dispatch
                        disp_s += time.perf_counter() - t
                        t = time.perf_counter()
                        host = (await loop.run_in_executor(
                            None, np.asarray, buf)
                            if off_loop else np.asarray(buf))
                        fetch_s += time.perf_counter() - t
                        t = time.perf_counter()
                        rows = self._decode_narrow(host, k, k_b)
                        dec_s += time.perf_counter() - t
                    if rows is None:  # wide schema, or a u16 overflow refetch
                        t = time.perf_counter()
                        mat, _ = self._gather_wide(slab, s_ords, idx)
                        disp_s += time.perf_counter() - t
                        t = time.perf_counter()
                        host = (await loop.run_in_executor(
                            None, np.asarray, mat)
                            if off_loop else np.asarray(mat))
                        fetch_s += time.perf_counter() - t
                        t = time.perf_counter()
                        rows = self._decode_wide(host, k)
                        dec_s += time.perf_counter() - t
                    break
                except RuntimeError as exc:
                    if attempt == 2 or "delet" not in str(exc).lower():
                        raise
                    await asyncio.sleep(0.001)
            t = time.perf_counter()
            states = self._states_of_batch(gather_ids, rows, k)
            dec_s += time.perf_counter() - t
            # one batched LRU touch for every gathered hit (read_many
            # skips per-id touching on its fast path)
            self._tick += 1
            self._lru.update(dict.fromkeys(gather_ids, self._tick))
            self.stats["gathers"] += 1
            self.stats["gathered_rows"] += k
            if self.metrics is not None:
                self.metrics.resident_gather_batch.record(k)
            if self.ledger is not None:
                self.ledger.record_gather(
                    reads=len(calls), rows=k, wait_us=wait_s * 1e6,
                    dispatch_us=disp_s * 1e6, fetch_us=fetch_s * 1e6,
                    decode_us=dec_s * 1e6)
            if self.tracer is not None:
                self._emit_gather_span(wait_s, disp_s, fetch_s, dec_s, k)
        for fut, single, ids, refs, start in calls:
            if fut.done():
                continue
            try:
                if refs is None:  # all resident, contiguous rows
                    if single:
                        fut.set_result((True, states[start]))
                    else:
                        fut.set_result(dict(zip(
                            ids, states[start:start + len(ids)])))
                    continue
                out: Dict[str, Any] = {}
                misses = poisons = 0
                for agg, ref in zip(ids, refs):
                    if ref is None:
                        if agg in self._poisoned:
                            poisons += 1
                        else:
                            misses += 1
                    elif isinstance(ref, int):
                        out[agg] = states[ref]
                    else:  # exact-fold-point spill row
                        out[agg] = self._state_of(
                            agg, {k: np.asarray(v)
                                  for k, v in ref[1].items()}, 0)
                if misses:
                    self._record_fallback(misses)
                if poisons:
                    self._record_fallback(poisons, cause="unschema-poison")
                if single:
                    agg = ids[0]
                    fut.set_result((agg in out, out.get(agg)))
                else:
                    fut.set_result(out)
            except Exception as exc:  # noqa: BLE001 — decode bug
                if not fut.done():
                    fut.set_exception(exc)

    def _emit_gather_span(self, wait_s: float, disp_s: float, fetch_s: float,
                          dec_s: float, rows: int) -> None:
        """One retro-dated ``resident.gather`` span per drained batch, its
        device legs as ``leg.*-ms`` attributes — the read-side fold anatomy.
        BOTH clocks are retro-dated to the measured interval (the profiler's
        span discipline): the tail sampler's keep decision and the anatomy
        placement read the mono pair first, so a wall-only retro-date would
        make a stalled 2 s gather look like a 0 ms span."""
        total = wait_s + disp_s + fetch_s + dec_s
        span = self.tracer.start_span("resident.gather")
        span.start_time = time.time() - total
        span.start_mono = time.monotonic() - total
        try:
            span.set_attribute("leg.coalesce-ms", round(wait_s * 1000.0, 3))
            span.set_attribute("leg.dispatch-ms", round(disp_s * 1000.0, 3))
            span.set_attribute("leg.fetch-ms", round(fetch_s * 1000.0, 3))
            span.set_attribute("leg.decode-ms", round(dec_s * 1000.0, 3))
            span.set_attribute("rows", rows)
        finally:
            span.finish()  # unconditional: a leaked span pins its trace

    def _state_of(self, aggregate_id: str, record: Mapping[str, Any],
                  _j: int) -> Any:
        """Tensor row → domain state, through the exact restore chain
        (from_record → aggregate-id reattach → decode_state)."""
        from surge_tpu.store.restore import _with_aggregate_id

        state = self.spec.registry.state.from_record(record)
        state = _with_aggregate_id(state, aggregate_id)
        if self.decode_state is not None:
            state = self.decode_state(aggregate_id, state)
        return state

    # -- introspection ------------------------------------------------------------------

    def occupancy(self) -> int:
        return len(self._dir)

    def resident_ids(self) -> List[str]:
        return sorted(self._dir)

    def _record_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.resident_occupancy.record(len(self._dir))
        # gauge lag from the last poll's end offsets — a live end_offset per
        # partition here would put the FileLog's stat() back on the loop
        ends = self._last_ends
        self.metrics.resident_fold_lag.record(sum(
            max(ends.get(p, 0) - self._watermarks.get(p, 0), 0)
            for p in self.partitions))

    def snapshot_states(self) -> Dict[str, Any]:
        """Host snapshot of every tracked aggregate's state (resident + spill)
        — the golden-test surface; one wide gather for the resident rows."""
        out: Dict[str, Any] = {}
        ids = list(self._dir)
        if ids:
            idx = np.fromiter((self._dir[a] for a in ids), dtype=np.int32,
                              count=len(ids))
            rows, _ = self._pull_positions(self._slab, idx, ords=self._ords)
            for j, agg in enumerate(ids):
                out[agg] = self._state_of(
                    agg, {k: rows[k][j] for k in rows}, j)
        for agg, (row, _ord) in self._spill.items():
            out[agg] = self._state_of(
                agg, {k: np.asarray(v) for k, v in row.items()}, 0)
        return out
