"""Sequence-parallel replay: one aggregate's LONG log sharded across devices.

The reference's long-sequence analog is a long per-aggregate event log
(SURVEY.md §5.7) — it replays one sequentially. Entity parallelism
(`resident_mesh`) cannot help when one log dwarfs the batch: a fold is a
sequential dependence chain. This module is the event-sourcing form of
sequence/context parallelism (the ring-attention role for this framework):
models whose fold is **associative** declare

- ``lift(event_fields) -> summary``  — per-event state-transform summary,
- ``combine(s1, s2) -> summary``     — associative (NOT necessarily
  commutative) composition of transforms,
- ``apply(state, summary) -> state`` — apply a composed transform,
- ``identity``                        — the no-op summary (padding lifts here),

and the engine shards the TIME axis over the mesh: each device lifts and
scan-combines its slice of the log into one summary per lane, a single
ordered ``all_gather`` moves the (tiny) per-device summaries everywhere, and
each device composes them in device order — O(T/D) sequential work instead of
O(T), with one collective of size D×B summaries riding ICI. The classic
parallel event-sourcing trick (monoid fold / parallel prefix), here as an
SPMD program.

Not every model qualifies (general ``handle_event`` is opaque); the batched
entity-parallel fold remains the default. Counter-like additive models, and
any model whose transforms close under composition, do.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping

import numpy as np

Summary = Dict[str, Any]


@dataclass(frozen=True)
class AssociativeFold:
    """An associative decomposition of a model's event fold."""

    lift: Callable[[Mapping[str, Any]], Summary]
    combine: Callable[[Summary, Summary], Summary]
    apply: Callable[[Dict[str, Any], Summary], Dict[str, Any]]
    identity: Summary


def replay_time_sharded(afold: AssociativeFold, spec, events: Mapping[str, Any],
                        mesh, *, mesh_axis: str = "data",
                        init_carry: Mapping[str, Any] | None = None
                        ) -> dict[str, np.ndarray]:
    """Fold time-major event columns ``{col: [T, B]}`` (type_id -1 = padding)
    with the time axis sharded over ``mesh_axis``. Returns state columns
    ``{field: [B]}`` identical to the sequential fold.

    ``T`` is padded up to a multiple of the device count; padding slots lift
    to ``identity`` (callers' ``lift`` must honor ``type_id == -1``).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = int(np.prod(mesh.devices.shape))
    t = next(iter(events.values())).shape[0]
    b = next(iter(events.values())).shape[1]
    # bucket the per-device slice length to a power of two so variable-length
    # chunks of one long log reuse a program per bucket (padding lifts to the
    # identity summary, costing only combine steps)
    t_local = 8
    while t_local * n_dev < max(t, 1):
        t_local *= 2
    t_pad = t_local * n_dev
    padded: dict[str, Any] = {}
    for name, col in events.items():
        col = np.asarray(col)
        if t_pad != t:
            fill = -1 if name == "type_id" else 0
            col = np.concatenate(
                [col, np.full((t_pad - t, b), fill, dtype=col.dtype)], axis=0)
        padded[name] = col

    init = {f.name: np.broadcast_to(
        np.asarray(spec.init_state_tree()[f.name]), (b,)).copy()
        for f in spec.registry.state.fields}
    if init_carry is not None:
        for k, v in init_carry.items():
            init[k] = np.asarray(v).copy()

    program = _program(afold, mesh, mesh_axis, b,
                       tuple(sorted((k, v.shape, str(v.dtype))
                                    for k, v in padded.items())),
                       tuple(sorted(init)))
    p_ev = P(mesh_axis, None)
    ev_dev = {k: jax.device_put(v, NamedSharding(mesh, p_ev))
              for k, v in padded.items()}
    init_dev = {k: jax.device_put(v[None], NamedSharding(mesh, P(None, None)))
                for k, v in init.items()}
    out = program(ev_dev, init_dev)
    return {k: np.asarray(v)[0] for k, v in out.items()}


#: compiled time-sharded programs, keyed on (fold, mesh, axis, shapes) — a
#: chunked/resumed replay of one long log reuses one program per shape bucket
_PROGRAMS: dict = {}


def _program(afold: AssociativeFold, mesh, mesh_axis: str, b: int,
             ev_shapes: tuple, init_names: tuple):
    # keyed on the fold OBJECT's identity (its dict members are unhashable);
    # the cache entry pins the fold, so a freed object's id can never alias a
    # live entry. Callers should build one AssociativeFold per model.
    key = (id(afold), mesh, mesh_axis, b, ev_shapes, init_names)
    hit = _PROGRAMS.get(key)
    if hit is not None:
        return hit[1]
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n_dev = int(np.prod(mesh.devices.shape))

    def local(events_local, init_state):
        # events_local: {col: [T/D, B]} time block; scan-combine the lifted
        # summaries of the local slice (order-preserving)
        def body(acc, ev_t):
            return afold.combine(acc, afold.lift(ev_t)), None

        ident = {k: jnp.broadcast_to(jnp.asarray(v), (b,))
                 for k, v in afold.identity.items()}
        local_sum, _ = jax.lax.scan(body, ident, events_local)
        # one ordered collective: every device sees all D summaries [D, B]
        allsum = {k: jax.lax.all_gather(v, mesh_axis)
                  for k, v in local_sum.items()}

        def compose(acc, d):
            return afold.combine(acc, {k: v[d] for k, v in allsum.items()}), None

        total, _ = jax.lax.scan(compose, ident, jnp.arange(n_dev))
        out = afold.apply({k: v[0] for k, v in init_state.items()}, total)
        return {k: v[None] for k, v in out.items()}

    p_ev = P(mesh_axis, None)
    ev_names = tuple(k for k, _, _ in ev_shapes)
    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=({k: p_ev for k in ev_names},
                  {k: P(None, None) for k in init_names}),
        out_specs={k: P(None, None) for k in init_names},
        check_vma=False)
    jitted = jax.jit(mapped)
    _PROGRAMS[key] = (afold, jitted)
    return jitted
