"""Sequence-parallel replay: one aggregate's LONG log sharded across devices.

The reference's long-sequence analog is a long per-aggregate event log
(SURVEY.md §5.7) — it replays one sequentially. Entity parallelism
(`resident_mesh`) cannot help when one log dwarfs the batch: a fold is a
sequential dependence chain. This module is the event-sourcing form of
sequence/context parallelism (the ring-attention role for this framework):
models whose fold is **associative** declare

- ``lift(event_fields) -> summary``  — per-event state-transform summary,
- ``combine(s1, s2) -> summary``     — associative (NOT necessarily
  commutative) composition of transforms,
- ``apply(state, summary) -> state`` — apply a composed transform,
- ``identity``                        — the no-op summary (padding lifts here),

and the engine shards the TIME axis over the mesh: each device lifts and
scan-combines its slice of the log into one summary per lane, a single
ordered ``all_gather`` moves the (tiny) per-device summaries everywhere, and
each device composes them in device order — O(T/D) sequential work instead of
O(T), with one collective of size D×B summaries riding ICI. The classic
parallel event-sourcing trick (monoid fold / parallel prefix), here as an
SPMD program.

Not every model qualifies (general ``handle_event`` is opaque); the batched
entity-parallel fold remains the default. Counter-like additive models, and
any model whose transforms close under composition, do.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping

import numpy as np

Summary = Dict[str, Any]


@dataclass(frozen=True)
class AssociativeFold:
    """An associative decomposition of a model's event fold."""

    lift: Callable[[Mapping[str, Any]], Summary]
    combine: Callable[[Summary, Summary], Summary]
    apply: Callable[[Dict[str, Any], Summary], Dict[str, Any]]
    identity: Summary


def check_associative_fold(afold: AssociativeFold, spec, *, lanes: int = 4,
                           length: int = 48, trials: int = 3, seed: int = 0,
                           atol: float = 1e-5,
                           column_sampler: Callable | None = None) -> None:
    """Property-check a decomposition against the spec's scalar step fold on
    randomized event streams (``type_id = -1`` padding included) and reject a
    wrong one LOUDLY (VERDICT r4 weak #5 — a bad user-supplied ``combine``
    must never silently corrupt states).

    Laws checked, per trial:

    1. identity:       ``combine(e, x) == x == combine(x, e)`` and
                       ``apply(s, e) == s``
    2. homomorphism:   ``apply(s, fold_left(combine, lifts)) == step-fold(s)``
                       (the scalar ground truth from ``make_step_fn``)
    3. associativity:  regrouping the combine tree at random cut points — the
                       exact transformation the time-sharded program performs —
                       changes nothing
    4. padding:        an all-padding stream leaves the state untouched

    ``column_sampler(name, dtype, shape, rng)`` overrides the default field
    generator (small ints; quarters for float columns, which keeps float
    monoid reassociation exact).
    """
    import jax

    from surge_tpu.replay.engine import make_step_fn

    rng = np.random.default_rng(seed)
    num_types = spec.registry.num_event_types
    step = jax.vmap(make_step_fn(spec), in_axes=(0, 0))  # lane-wise
    field_specs = [(f.name, np.dtype(f.dtype))
                   for f in spec.registry.union_columns()
                   if f.name != "type_id"]

    def sample(name, dtype, shape):
        if column_sampler is not None:
            return np.asarray(column_sampler(name, dtype, shape, rng),
                              dtype=dtype)
        if np.issubdtype(dtype, np.floating):
            return (rng.integers(0, 16, size=shape) * 0.25).astype(dtype)
        if dtype == np.bool_:
            return rng.integers(0, 2, size=shape).astype(dtype)
        return rng.integers(0, 4, size=shape).astype(dtype)

    def fail(law: str, field: str, got, want) -> None:
        raise ValueError(
            f"AssociativeFold violates the {law} law on field {field!r}: "
            f"got {np.asarray(got)!r}, expected {np.asarray(want)!r} — "
            "the decomposition would silently corrupt sequence-parallel "
            "replays; fix lift/combine/apply or use the entity-parallel path")

    def eq(law: str, a: Mapping[str, Any], b: Mapping[str, Any]) -> None:
        for k in b:
            av, bv = np.asarray(a[k]), np.asarray(b[k])
            if av.dtype == np.bool_ or np.issubdtype(av.dtype, np.integer):
                if not np.array_equal(av, bv):
                    fail(law, k, av, bv)
            elif not np.allclose(av, bv, atol=atol, rtol=1e-5):
                fail(law, k, av, bv)

    ident = {k: np.broadcast_to(np.asarray(v), (lanes,))
             for k, v in afold.identity.items()}
    for _ in range(trials):
        cols = {"type_id": rng.integers(-1, num_types,
                                        size=(length, lanes)).astype(np.int32)}
        for name, dtype in field_specs:
            cols[name] = sample(name, dtype, (length, lanes))
        state0 = {f.name: sample(f.name, np.dtype(f.dtype), (lanes,))
                  for f in spec.registry.state.fields}

        # scalar ground truth: the spec's per-event step, lane-wise
        truth = {k: v.copy() for k, v in state0.items()}
        for t in range(length):
            out = step({k: v for k, v in truth.items()},
                       {k: v[t] for k, v in cols.items()})
            truth = {k: np.asarray(v) for k, v in out.items()}

        lifts = [{k: np.asarray(v) for k, v in
                  afold.lift({c: cols[c][t] for c in cols}).items()}
                 for t in range(length)]
        # 1. identity laws (on a representative lifted summary)
        eq("identity (left)", afold.combine(ident, lifts[0]), lifts[0])
        eq("identity (right)", afold.combine(lifts[0], ident), lifts[0])
        eq("identity (apply)", afold.apply(dict(state0), ident), state0)
        # 2. homomorphism vs the scalar fold
        acc = ident
        for s in lifts:
            acc = afold.combine(acc, s)
        eq("homomorphism (apply∘fold(lift) == step-fold)",
           afold.apply(dict(state0), acc), truth)
        # 3. associativity: random regrouping (what the mesh program does)
        cuts = sorted(rng.choice(range(1, length), size=3, replace=False))
        acc2 = ident
        for lo, hi in zip([0, *cuts], [*cuts, length]):
            seg = ident
            for s in lifts[lo:hi]:
                seg = afold.combine(seg, s)
            acc2 = afold.combine(acc2, seg)
        eq("associativity (regrouped combine)",
           afold.apply(dict(state0), acc2), truth)
        # 4. padding lifts to a no-op
        pad = dict(cols)
        pad["type_id"] = np.full_like(cols["type_id"], -1)
        pacc = ident
        for t in range(length):
            pacc = afold.combine(pacc, afold.lift(
                {c: pad[c][t] for c in pad}))
        eq("padding (type_id=-1 is identity)",
           afold.apply(dict(state0), pacc), state0)


def replay_time_sharded(afold: AssociativeFold, spec, events: Mapping[str, Any],
                        mesh, *, mesh_axis: str = "data",
                        init_carry: Mapping[str, Any] | None = None,
                        validate: bool = True) -> dict[str, np.ndarray]:
    """Fold time-major event columns ``{col: [T, B]}`` (type_id -1 = padding)
    with the time axis sharded over ``mesh_axis``. Returns state columns
    ``{field: [B]}`` identical to the sequential fold.

    ``T`` is padded up to a multiple of the device count; padding slots lift
    to ``identity`` (callers' ``lift`` must honor ``type_id == -1``).

    The first use of each fold (structural key) property-checks it against the
    spec's scalar step fold — a wrong ``combine`` raises instead of silently
    corrupting states; ``validate=False`` opts out (e.g. a fold whose columns
    the default sampler cannot generate — pair it with an explicit
    :func:`check_associative_fold` call).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if validate:
        ensure_validated(afold, spec)

    n_dev = int(np.prod(mesh.devices.shape))
    t = next(iter(events.values())).shape[0]
    b = next(iter(events.values())).shape[1]
    # bucket the per-device slice length to a power of two so variable-length
    # chunks of one long log reuse a program per bucket (padding lifts to the
    # identity summary, costing only combine steps)
    t_local = 8
    while t_local * n_dev < max(t, 1):
        t_local *= 2
    t_pad = t_local * n_dev
    padded: dict[str, Any] = {}
    for name, col in events.items():
        col = np.asarray(col)
        if t_pad != t:
            fill = -1 if name == "type_id" else 0
            col = np.concatenate(
                [col, np.full((t_pad - t, b), fill, dtype=col.dtype)], axis=0)
        padded[name] = col

    init = {f.name: np.broadcast_to(
        np.asarray(spec.init_state_tree()[f.name]), (b,)).copy()
        for f in spec.registry.state.fields}
    if init_carry is not None:
        for k, v in init_carry.items():
            init[k] = np.asarray(v).copy()

    program = _program(afold, mesh, mesh_axis, b,
                       tuple(sorted((k, v.shape, str(v.dtype))
                                    for k, v in padded.items())),
                       tuple(sorted(init)))
    p_ev = P(mesh_axis, None)
    ev_dev = {k: jax.device_put(v, NamedSharding(mesh, p_ev))
              for k, v in padded.items()}
    init_dev = {k: jax.device_put(v[None], NamedSharding(mesh, P(None, None)))
                for k, v in init.items()}
    out = program(ev_dev, init_dev)
    return {k: np.asarray(v)[0] for k, v in out.items()}


#: compiled time-sharded programs, keyed on (fold structure, mesh, axis,
#: shapes) — a chunked/resumed replay of one long log reuses one program per
#: shape bucket, and two structurally-equal folds (e.g. a factory called per
#: restore chunk) share programs instead of recompiling
_PROGRAMS: dict = {}

#: structural fold keys that already passed check_associative_fold
_VALIDATED: set = set()


def ensure_validated(afold: AssociativeFold, spec) -> None:
    """Law-check ``afold`` against ``spec`` once per structural (fold, spec)
    pair — keyed on the PAIR because the laws tie a decomposition to one
    spec's handlers; the same fold against a different spec must be
    re-checked, not skipped. Shared by the time-sharded replay and the
    engine's assoc tile backend."""
    vkey = (fold_key(afold), _spec_key(spec))
    if vkey not in _VALIDATED:
        check_associative_fold(afold, spec)
        _VALIDATED.add(vkey)


def _hash_or_id(v):
    try:
        hash(v)
        return v
    except TypeError:
        return ("id", id(v))


def _callable_key(fn) -> tuple:
    """Structural identity of a fold callable: its code object plus EVERY
    captured input that parameterizes it — closure cells, default args, and a
    bound method's receiver (two folds differing only in a default-arg capture
    or in ``self`` must NOT collide). Hashables key by value, the rest by
    object id — those ids stay valid because the program cache pins the whole
    fold."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return ("obj", id(fn))
    cells = tuple(_hash_or_id(c.cell_contents)
                  for c in (getattr(fn, "__closure__", None) or ()))
    defaults = tuple(_hash_or_id(d)
                     for d in (getattr(fn, "__defaults__", None) or ()))
    kwdefaults = tuple(sorted(
        (k, _hash_or_id(v))
        for k, v in (getattr(fn, "__kwdefaults__", None) or {}).items()))
    receiver = getattr(fn, "__self__", None)
    return ("code", code, cells, defaults, kwdefaults,
            ("id", id(receiver)) if receiver is not None else None)


def _spec_key(spec) -> tuple:
    """Structural identity of a ReplaySpec for the validation cache: schema
    shape plus the handler callables' structural keys (handlers carry the
    semantics the conformance laws are checked against)."""
    num_types = spec.registry.num_event_types
    return (num_types,
            tuple((f.name, str(f.dtype))
                  for f in spec.registry.state.fields),
            tuple(_callable_key(h)
                  for h in spec.handlers.ordered(num_types)))


def fold_key(afold: AssociativeFold) -> tuple:
    """Hashable structural key: two folds made by the same factory with equal
    captures compare equal (VERDICT r4 weak #5 — id() keying compiled twice
    and relied on caller discipline)."""
    ident = tuple(sorted(
        (k, np.asarray(v).dtype.str, np.asarray(v).item()
         if np.ndim(v) == 0 else tuple(np.asarray(v).ravel().tolist()))
        for k, v in afold.identity.items()))
    return (_callable_key(afold.lift), _callable_key(afold.combine),
            _callable_key(afold.apply), ident)


def _program(afold: AssociativeFold, mesh, mesh_axis: str, b: int,
             ev_shapes: tuple, init_names: tuple):
    # the cache entry pins the fold object, so any id()-keyed closure cells in
    # the structural key can never alias a freed object's id
    key = (fold_key(afold), mesh, mesh_axis, b, ev_shapes, init_names)
    hit = _PROGRAMS.get(key)
    if hit is not None:
        return hit[1]
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n_dev = int(np.prod(mesh.devices.shape))

    def local(events_local, init_state):
        # events_local: {col: [T/D, B]} time block; scan-combine the lifted
        # summaries of the local slice (order-preserving)
        def body(acc, ev_t):
            return afold.combine(acc, afold.lift(ev_t)), None

        ident = {k: jnp.broadcast_to(jnp.asarray(v), (b,))
                 for k, v in afold.identity.items()}
        local_sum, _ = jax.lax.scan(body, ident, events_local)
        # one ordered collective: every device sees all D summaries [D, B]
        allsum = {k: jax.lax.all_gather(v, mesh_axis)
                  for k, v in local_sum.items()}

        def compose(acc, d):
            return afold.combine(acc, {k: v[d] for k, v in allsum.items()}), None

        total, _ = jax.lax.scan(compose, ident, jnp.arange(n_dev))
        out = afold.apply({k: v[0] for k, v in init_state.items()}, total)
        return {k: v[None] for k, v in out.items()}

    p_ev = P(mesh_axis, None)
    ev_names = tuple(k for k, _, _ in ev_shapes)
    from surge_tpu.replay.jax_compat import shard_map as _shard_map

    mapped = _shard_map(
        local, mesh=mesh,
        in_specs=({k: p_ev for k in ev_names},
                  {k: P(None, None) for k in init_names}),
        out_specs={k: P(None, None) for k in init_names},
        check_vma=False)
    jitted = jax.jit(mapped)
    _PROGRAMS[key] = (afold, jitted)
    return jitted
