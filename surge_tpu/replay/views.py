"""Incremental materialized views + changefeeds off the resident refresh feed.

The reference's entire read side is a Kafka STREAMS job: state materializes
incrementally into a KTable and downstream consumers ride the changelog
(PAPER.md, AggregateStateStoreKafkaStreams). PR 15 built only the batch half —
one-shot ``query()`` scans over committed columnar segments. This module is
the streaming half (ROADMAP item 1): named views registered through
``SurgeEngine.register_view()`` are OWNED by the resident plane — every
refresh round folds the committed tail into each view's grouped-aggregate
slab, so a view over millions of aggregates answers in one host merge of
device-computed partials instead of a whole-segment rescan, and subscribers
ride a push-based per-round delta changefeed instead of polling.

Design:

- **Views are scan queries, kept warm.** A :class:`ViewDef` wraps the exact
  :class:`~surge_tpu.replay.query.ScanQuery` the batch engine runs
  (count/sum/min/max, grouped by aggregate id or — ``group_by`` — by an event
  column, conjunctive AND OR predicates), plus an optional served ``top_k``.
  The view's per-round fold dispatches the SAME cached device program
  ``scan_chunks`` uses (mesh-sharded when the plane is), so batch scan and
  incremental view can never drift: the golden bar is byte-equality between a
  view and a from-scratch ``query()`` scan at the same watermark.
- **Per-partition raw partials.** View state is kept per PARTITION as the raw
  sentinel-carrying merge partials the batch engine's cross-chunk merge uses
  (count/sum add, min/max combine; zero-match normalization only at serve
  time). Partition separability is what lets views survive the plane's
  re-anchor paths for free: a revoke, a mid-round failure, a kill-failover
  re-grant — anything that re-anchors partition ``p`` at offset 0 simply
  drops ``p``'s partial, and the refresh loop's refold rebuilds it. Per-view
  fold watermarks advance only with the plane's own gen-fenced commits, so a
  view can never double-fold an event the slab didn't.
- **One encode per round.** The refresh round's decoded logs are split by
  partition and columnar-encoded ONCE; every registered view scans the same
  chunk (sharing the round's single h2d of it), riding ``plane_mesh``
  sharding on multi-device exactly like a batch scan.
- **Changefeed.** Every fold round bumps the view ``version`` (the resume
  watermark) and appends the changed rows to a bounded delta ring.
  ``SubscribeView`` streams these entries; a resume from version ``V``
  replays the ring when it still covers ``V`` (no gap, no dup) and otherwise
  answers with ONE reconciling snapshot (``reset``) the client replaces its
  state with — the same contract a fresh subscription and a failover
  re-anchor use. Applying entries in order always reconstructs the snapshot.

Exactness caveat: integer columns merge associatively, so incremental ==
batch bit-for-bit; float sums are order-sensitive and may differ in the last
ulp between fold orders (docs/replay.md "Materialized views").
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from surge_tpu.codec.tensor import encode_events_columnar
from surge_tpu.config import Config, default_config
from surge_tpu.replay.query import (QueryEngine, ScanQuery, _normalize_zero_match,
                                    _sentinel)

__all__ = ["ViewDef", "MaterializedViews", "ViewSubscription", "select_top_k"]


@dataclass(frozen=True)
class ViewDef:
    """One registered view: a scan query kept incrementally materialized.

    ``top_k`` (with ``top_k_by``, default the first non-count aggregate or
    ``count``) limits what the view SERVES — ranked descending, ties broken
    by ascending key — while the full group set stays materialized, so the
    ranking is exact, never approximate."""

    name: str
    query: ScanQuery
    top_k: Optional[int] = None
    top_k_by: Optional[str] = None

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("view needs a non-empty name")
        if not self.query.aggregates:
            raise ValueError(f"view {self.name!r} needs at least one aggregate")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"view {self.name!r}: top_k must be >= 1")
        outputs = ["count"] + [a.name for a in self.query.aggregates
                               if a.op != "count"]
        if self.top_k_by is not None and self.top_k_by not in outputs:
            raise ValueError(
                f"view {self.name!r}: top_k_by {self.top_k_by!r} is not an "
                f"output column (has {outputs})")

    @property
    def rank_by(self) -> str:
        if self.top_k_by is not None:
            return self.top_k_by
        for a in self.query.aggregates:
            if a.op != "count":
                return a.name
        return "count"

    def as_json(self) -> dict:
        out: dict = {"name": self.name, "query": self.query.as_json()}
        if self.top_k is not None:
            out["top_k"] = self.top_k
        if self.top_k_by is not None:
            out["top_k_by"] = self.top_k_by
        return out

    @classmethod
    def from_json(cls, d: Mapping[str, Any]) -> "ViewDef":
        return cls(name=d["name"], query=ScanQuery.from_json(d["query"]),
                   top_k=d.get("top_k"), top_k_by=d.get("top_k_by"))


def select_top_k(keys: Sequence[str], columns: Mapping[str, np.ndarray],
                 k: int, by: str) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """The served top-k selection, shared with the golden tests so a top-k
    view and a client-side cut of a batch scan rank identically: descending
    on ``by``, ties broken by ascending key."""
    order = sorted(range(len(keys)),
                   key=lambda j: (-float(columns[by][j]), keys[j]))[:k]
    idx = np.asarray(order, dtype=np.int64)
    return ([keys[j] for j in order],
            {name: col[idx] for name, col in columns.items()})


@dataclass
class _Accum:
    """One partition's raw merge partials: unique keys (first-seen order) and
    sentinel-carrying aggregate columns — droppable as a unit when the
    partition re-anchors."""

    keys: List[str] = field(default_factory=list)
    index: Dict[str, int] = field(default_factory=dict)
    cols: Dict[str, np.ndarray] = field(default_factory=dict)


class _View:
    """Runtime state of one registered view."""

    def __init__(self, vdef: ViewDef) -> None:
        self.vdef = vdef
        self.active = False          # pending until seeded or backfilled
        self.version = 0             # fold rounds applied — the resume watermark
        self.watermarks: Dict[int, int] = {}
        self.parts: Dict[int, _Accum] = {}
        self.ring: deque = deque()   # delta entries, bounded by changefeed-rounds
        self.ring_floor = 0          # deltas at/below this version are gone
        self.error: Optional[str] = None
        self.folded_events = 0


class ViewSubscription:
    """One live changefeed subscriber: an asyncio queue the fold thread
    publishes into via ``call_soon_threadsafe`` (folds run in the refresh
    executor; subscribers live on the event loop)."""

    def __init__(self, view: str, loop: asyncio.AbstractEventLoop) -> None:
        self.view = view
        self.queue: asyncio.Queue = asyncio.Queue()
        self._loop = loop
        self.closed = False

    def _publish(self, entry: dict) -> None:
        if self.closed:
            return
        try:
            self._loop.call_soon_threadsafe(self.queue.put_nowait, entry)
        except RuntimeError:  # loop shut down mid-publish
            self.closed = True

    async def get(self) -> dict:
        return await self.queue.get()

    def __aiter__(self) -> "ViewSubscription":
        return self

    async def __anext__(self) -> dict:
        return await self.queue.get()


class MaterializedViews:
    """The view subsystem: registered view defs, per-partition partials, the
    per-round fold, and the changefeed hub. Owned by the engine, driven by
    the resident plane's refresh loop (fold/drop run in the refresh executor;
    registration, snapshots and subscriptions run on the event loop — one
    lock guards all state)."""

    def __init__(self, spec, *, config: Config | None = None, mesh=None,
                 metrics=None, ledger=None, flight=None) -> None:
        self.spec = spec
        self.config = config or default_config()
        self.metrics = metrics
        self.ledger = ledger
        self.flight = flight
        # the views' scans ride the SAME engine class (and program cache
        # discipline) as batch query() — mesh-sharded when the plane is
        self._qeng = QueryEngine(spec, config=self.config, mesh=mesh)
        self._union_cols = {f.name for f in spec.registry.union_columns()}
        #: per-view delta-ring capacity: resumes within this many fold rounds
        #: replay exact deltas; older resumes get a reconciling snapshot
        self._ring_cap = max(self.config.get_int(
            "surge.replay.views.changefeed-rounds", 256), 1)
        #: per-view distinct-group cap — a group_by over an unbounded-
        #: cardinality column must degrade the one view, not the plane
        self._max_groups = self.config.get_int(
            "surge.replay.views.max-groups", 1 << 20)
        self._lock = threading.Lock()
        self._views: Dict[str, _View] = {}
        self._subs: Dict[str, List[ViewSubscription]] = {}
        self.stats = {"fold_rounds": 0, "delta_rows": 0, "resets": 0,
                      "snapshots": 0}

    # -- registration -------------------------------------------------------------------

    def register(self, vdef: ViewDef, *, active: bool) -> None:
        """Install a view. ``active=True`` means its partials start empty and
        the NEXT fold covers it from the start (pre-seed registration);
        ``active=False`` parks it pending until the plane backfills the
        already-folded prefix between refresh rounds."""
        for c in vdef.query.columns_needed():
            if c not in self._union_cols:
                raise ValueError(
                    f"view {vdef.name!r} references unknown event column "
                    f"{c!r} (has {sorted(self._union_cols)})")
        if vdef.query.event_types is not None:
            self._qeng.resolve_type_ids(vdef.query.event_types)  # validates
        with self._lock:
            if vdef.name in self._views:
                raise ValueError(f"view {vdef.name!r} already registered")
            v = _View(vdef)
            v.active = active
            self._views[vdef.name] = v
        if self.flight is not None:
            self.flight.record("views.register", view=vdef.name,
                               active=active)

    def unregister(self, name: str) -> bool:
        with self._lock:
            v = self._views.pop(name, None)
            subs = self._subs.pop(name, [])
        for s in subs:
            s._publish({"view": name, "closed": "unregistered"})
            s.closed = True
        self._record_subscriber_gauge()
        return v is not None

    @property
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    @property
    def active_or_pending(self) -> int:
        with self._lock:
            return len(self._views)

    @property
    def has_pending(self) -> bool:
        with self._lock:
            return any(not v.active for v in self._views.values())

    # -- the per-round fold (refresh executor) ------------------------------------------

    def _round_chunks(self, logs: Mapping[str, list],
                      part_of: Mapping[str, int],
                      committed: Mapping[int, int]) -> Dict[int, Any]:
        """Split one round's decoded logs by partition and columnar-encode
        each slice ONCE — every view scans the same chunk (one h2d per
        partition per round, shared across views)."""
        by_part: Dict[int, Tuple[List[str], List[list]]] = {}
        for agg, events in logs.items():
            p = part_of.get(agg)
            if p in committed and events:
                ids, evs = by_part.setdefault(p, ([], []))
                ids.append(agg)
                evs.append(events)
        chunks: Dict[int, Any] = {}
        for p, (ids, evs) in by_part.items():
            colev = encode_events_columnar(self.spec.registry, evs)
            colev.aggregate_ids = ids
            chunks[p] = colev
        return chunks

    def fold_round(self, logs: Mapping[str, list],
                   part_of: Mapping[str, int],
                   committed: Mapping[int, int],
                   activate_pending: bool = False) -> None:
        """Fold one committed refresh round into every active view: scan the
        round's per-partition chunk per view, merge into that partition's
        partials, advance fold watermarks, bump versions, publish deltas.
        Runs in the refresh executor; never raises — a failing view degrades
        to an error state served as such, the plane keeps folding."""
        t0 = time.perf_counter()
        with self._lock:
            if activate_pending:
                for v in self._views.values():
                    v.active = True
            views = [v for v in self._views.values()
                     if v.active and v.error is None]
        if not views:
            return
        chunks = self._round_chunks(logs, part_of, committed)
        delta_rows = 0
        with self._lock:
            for v in views:
                delta_rows += self._fold_view_locked(v, chunks, committed)
        elapsed = time.perf_counter() - t0
        self.stats["fold_rounds"] += 1
        self.stats["delta_rows"] += delta_rows
        if self.metrics is not None:
            self.metrics.views_fold_timer.record_ms(elapsed * 1000.0)
            if delta_rows:
                self.metrics.views_delta_rows.record(delta_rows)
        if self.ledger is not None:
            self.ledger.record_view_round(
                views=len(views), rows=delta_rows,
                events=sum(c.num_events for c in chunks.values()),
                fold_us=elapsed * 1e6)

    def fold_view_backfill(self, name: str, logs: Mapping[str, list],
                           part_of: Mapping[str, int],
                           committed: Mapping[int, int]) -> None:
        """Activate ONE pending view by folding the already-committed prefix
        the plane re-read for it (register-while-running). Its version starts
        at 1 with a reset entry, so an early subscriber reconciles."""
        chunks = self._round_chunks(logs, part_of, committed)
        with self._lock:
            v = self._views.get(name)
            if v is None or v.active:
                return
            v.active = True
            self._fold_view_locked(v, chunks, committed, reset=True)

    def _fold_view_locked(self, v: _View, chunks: Mapping[int, Any],
                          committed: Mapping[int, int],
                          reset: bool = False) -> int:
        changed: set = set()
        for p, colev in chunks.items():
            try:
                ids_c, raw = self._qeng._raw_scan(colev, v.vdef.query)
            except Exception as exc:  # noqa: BLE001 — per-view degradation
                self._fail_view_locked(v, f"fold failed: {exc}")
                return 0
            if ids_c is None:
                ids_c = list(colev.aggregate_ids)
            acc = v.parts.get(p)
            if acc is None:
                acc = v.parts[p] = _Accum()
            self._merge_raw_locked(v, acc, ids_c, raw)
            if v.error is not None:  # group cap tripped mid-merge
                return 0
            for key, c in zip(ids_c, raw["count"].tolist()):
                if c:
                    changed.add(key)
            v.folded_events += colev.num_events
        v.watermarks.update(committed)
        v.version += 1
        if reset:
            entry = self._reset_entry_locked(v)
            self.stats["resets"] += 1
        elif changed:
            keys = sorted(changed)
            entry = {"view": v.vdef.name, "version": v.version,
                     "reset": False,
                     "watermarks": {str(p): w
                                    for p, w in sorted(v.watermarks.items())},
                     "rows": self._rows_locked(v, keys)}
        else:
            return 0
        self._push_delta_locked(v, entry)
        return len(entry["rows"])

    def _merge_raw_locked(self, v: _View, acc: _Accum, ids_c: List[str],
                          raw: Mapping[str, np.ndarray]) -> None:
        """Merge one chunk's RAW scan output into a partition accumulator —
        the same count/sum-add, min/max-combine arithmetic as the batch
        engine's cross-chunk merge, kept un-normalized so later rounds keep
        combining."""
        fresh = [k for k in ids_c if k not in acc.index]
        if fresh:
            if len(acc.keys) + len(fresh) > self._max_groups:
                self._fail_view_locked(
                    v, f"group cap exceeded "
                       f"(surge.replay.views.max-groups={self._max_groups})")
                return
            grow = len(fresh)
            for name, col in acc.cols.items():
                op = self._op_of(v.vdef.query, name)
                init = (0 if op in ("count", "sum")
                        else _sentinel(op, np.dtype(col.dtype)))
                acc.cols[name] = np.concatenate(
                    [col, np.full((grow,), init, dtype=col.dtype)])
            for k in fresh:
                acc.index[k] = len(acc.keys)
                acc.keys.append(k)
        b = len(acc.keys)
        idxs = np.fromiter((acc.index[k] for k in ids_c), dtype=np.int64,
                           count=len(ids_c))
        for name, col in raw.items():
            have = acc.cols.get(name)
            op = self._op_of(v.vdef.query, name)
            if have is None:
                init = (0 if op in ("count", "sum")
                        else _sentinel(op, np.dtype(col.dtype)))
                have = acc.cols[name] = np.full((b,), init, dtype=col.dtype)
            if op in ("count", "sum"):
                np.add.at(have, idxs, col)
            elif op == "min":
                np.minimum.at(have, idxs, col)
            else:
                np.maximum.at(have, idxs, col)

    @staticmethod
    def _op_of(query: ScanQuery, name: str) -> str:
        if name == "count":
            return "count"
        for a in query.aggregates:
            if a.op != "count" and a.name == name:
                return a.op
        raise KeyError(name)

    def _fail_view_locked(self, v: _View, reason: str) -> None:
        v.error = reason
        for s in self._subs.get(v.vdef.name, []):
            s._publish({"view": v.vdef.name, "error": reason,
                        "version": v.version})
        if self.flight is not None:
            self.flight.record("views.error", view=v.vdef.name,
                               reason=reason)

    # -- re-anchor (shared with every plane purge path) ---------------------------------

    def drop_partition(self, p: int) -> None:
        """Partition ``p`` re-anchored (revoke, re-grant, mid-round failure,
        failover): drop every view's partial for it and emit a reset entry —
        subscribers replace their state, and the refresh refold rebuilds the
        partial through normal rounds."""
        with self._lock:
            for v in self._views.values():
                had = v.parts.pop(p, None) is not None
                wm = v.watermarks.pop(p, None) is not None
                if not (had or wm) or not v.active or v.error is not None:
                    continue
                v.version += 1
                self.stats["resets"] += 1
                self._push_delta_locked(v, self._reset_entry_locked(v))

    def _reset_entry_locked(self, v: _View) -> dict:
        keys, cols = self._combined_locked(v)
        return {"view": v.vdef.name, "version": v.version, "reset": True,
                "watermarks": {str(p): w
                               for p, w in sorted(v.watermarks.items())},
                "rows": self._rows_of(v, keys, cols)}

    # -- serving ------------------------------------------------------------------------

    def _combined_locked(self, v: _View, only: Optional[List[str]] = None
                         ) -> Tuple[List[str], Dict[str, np.ndarray]]:
        """Merge the per-partition raw partials into normalized output
        columns over sorted keys (serve order is key-sorted: incremental and
        batch paths discover keys in different orders, the sort is the
        canonical one byte-equality is defined on)."""
        if only is not None:
            keys = only
        else:
            union: set = set()
            for acc in v.parts.values():
                union.update(acc.keys)
            keys = sorted(union)
        index = {k: i for i, k in enumerate(keys)}
        b = len(keys)
        agg_specs = [(a.op, a.name) for a in v.vdef.query.aggregates
                     if a.op != "count"]
        cols: Dict[str, np.ndarray] = {"count": np.zeros((b,), np.int32)}
        for p in sorted(v.parts):
            acc = v.parts[p]
            pairs = [(j, index[k]) for j, k in enumerate(acc.keys)
                     if k in index]
            if not pairs:
                continue
            js = np.asarray([j for j, _ in pairs], dtype=np.int64)
            ks = np.asarray([i for _, i in pairs], dtype=np.int64)
            cols["count"][ks] += acc.cols["count"][js]
            for op, name in agg_specs:
                src = acc.cols.get(name)
                if src is None:
                    continue
                have = cols.get(name)
                if have is None:
                    init = (0 if op == "sum"
                            else _sentinel(op, np.dtype(src.dtype)))
                    have = cols[name] = np.full((b,), init, dtype=src.dtype)
                if op == "sum":
                    have[ks] += src[js]
                elif op == "min":
                    np.minimum.at(have, ks, src[js])
                else:
                    np.maximum.at(have, ks, src[js])
        for _op, name in agg_specs:
            if name not in cols:  # empty view: no chunk ever carried dtypes
                cols[name] = np.zeros((b,), dtype=np.int32)
        return keys, _normalize_zero_match(cols, v.vdef.query)

    def _rows_of(self, v: _View, keys: List[str],
                 cols: Mapping[str, np.ndarray]) -> List[dict]:
        names = list(cols)
        lists = [cols[n].tolist() for n in names]
        return [{"key": k, **{n: lists[i][j] for i, n in enumerate(names)}}
                for j, k in enumerate(keys)]

    def _rows_locked(self, v: _View, keys: List[str]) -> List[dict]:
        keys2, cols = self._combined_locked(v, only=keys)
        return self._rows_of(v, keys2, cols)

    def snapshot(self, name: str) -> dict:
        """The served view: normalized columns over sorted keys (top-k cut
        applied), version + fold watermarks. This is the ``QueryView`` RPC
        payload and the golden-test surface."""
        with self._lock:
            v = self._views.get(name)
            if v is None:
                raise KeyError(f"unknown view {name!r}")
            if v.error is not None:
                return {"view": name, "error": v.error, "version": v.version}
            keys, cols = self._combined_locked(v)
            if v.vdef.top_k is not None:
                keys, cols = select_top_k(keys, cols, v.vdef.top_k,
                                          v.vdef.rank_by)
            self.stats["snapshots"] += 1
            return {"view": name, "version": v.version,
                    "active": v.active,
                    "watermarks": {str(p): w
                                   for p, w in sorted(v.watermarks.items())},
                    "keys": keys,
                    "columns": {n: c for n, c in cols.items()},
                    "rows": self._rows_of(v, keys, cols)}

    def summary(self) -> List[dict]:
        """Operator view (``chaos.py views`` / surgetop): one row per view."""
        with self._lock:
            out = []
            for name in sorted(self._views):
                v = self._views[name]
                groups = len({k for acc in v.parts.values()
                              for k in acc.keys})
                out.append({
                    "view": name, "active": v.active, "version": v.version,
                    "groups": groups, "folded_events": v.folded_events,
                    "watermarks": {str(p): w for p, w
                                   in sorted(v.watermarks.items())},
                    "subscribers": len(self._subs.get(name, [])),
                    "error": v.error,
                    "query": v.vdef.query.as_json(),
                })
            return out

    # -- changefeed ---------------------------------------------------------------------

    def _push_delta_locked(self, v: _View, entry: dict) -> None:
        self.ring_append(v, entry)
        for s in self._subs.get(v.vdef.name, []):
            s._publish(entry)

    def ring_append(self, v: _View, entry: dict) -> None:
        v.ring.append(entry)
        while len(v.ring) > self._ring_cap:
            evicted = v.ring.popleft()
            v.ring_floor = max(v.ring_floor, evicted["version"])

    def subscribe(self, name: str, from_version: Optional[int] = None, *,
                  loop: Optional[asyncio.AbstractEventLoop] = None
                  ) -> ViewSubscription:
        """Open a changefeed. ``from_version=None`` → initial reconciling
        snapshot then live deltas. With a resume watermark: the missed
        deltas replay from the ring when it still covers them (exactly, no
        gap no dup); a gap beyond the ring — or a version from before a
        failover reset — gets ONE reconciling snapshot instead, and the gap
        width lands on ``surge.replay.views.resume-gap-rounds``. Pass
        ``loop`` when calling from an executor thread (the engine hops the
        lock acquisition off the event loop — a fold may hold it through a
        device scan)."""
        if loop is None:
            loop = asyncio.get_running_loop()
        with self._lock:
            v = self._views.get(name)
            if v is None:
                raise KeyError(f"unknown view {name!r}")
            sub = ViewSubscription(name, loop)
            if from_version is None:
                sub.queue.put_nowait(self._reset_entry_locked(v))
            elif (from_version < v.ring_floor or from_version > v.version):
                gap = max(v.version - from_version, 1)
                if self.metrics is not None:
                    self.metrics.views_resume_gap_rounds.record(gap)
                if self.flight is not None:
                    self.flight.record("views.resume-gap", view=name,
                                       from_version=from_version,
                                       gap_rounds=gap)
                sub.queue.put_nowait(self._reset_entry_locked(v))
            else:
                for entry in v.ring:
                    if entry["version"] > from_version:
                        sub.queue.put_nowait(entry)
            self._subs.setdefault(name, []).append(sub)
        self._record_subscriber_gauge()
        return sub

    def unsubscribe(self, sub: ViewSubscription) -> None:
        sub.closed = True
        with self._lock:
            subs = self._subs.get(sub.view)
            if subs and sub in subs:
                subs.remove(sub)
        self._record_subscriber_gauge()

    def subscriber_count(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._subs.values())

    def _record_subscriber_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.views_subscribers.record(self.subscriber_count())

    def close(self) -> None:
        """Engine stop: end every subscription."""
        with self._lock:
            subs = [s for lst in self._subs.values() for s in lst]
            self._subs.clear()
        for s in subs:
            s._publish({"view": s.view, "closed": "engine-stopped"})
            s.closed = True
        self._record_subscriber_gauge()
