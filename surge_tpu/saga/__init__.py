"""Saga / process-manager orchestration across aggregates (ROADMAP 5(b)).

Saga state is itself an aggregate — ``make_saga_logic()`` builds a normal
engine family, so sagas inherit replay, resident-plane recovery, quorum
failover and flight observability for free.  The :class:`SagaManager`
drives every in-flight saga to a terminal state with deterministic
saga-scoped request ids, making retries after timeout/crash/failover ride
the existing dedup window exactly-once.  See docs/operations.md
("Running sagas") and docs/event-engine.md.
"""

from surge_tpu.saga.definition import (
    SagaDefinition,
    SagaStep,
    definition_index,
)
from surge_tpu.saga.manager import (
    SagaManager,
    compensation_request_id,
    step_request_id,
)
from surge_tpu.saga.model import (
    COMPENSATED,
    COMPENSATING,
    COMPLETED,
    DEAD_LETTER,
    MAX_STEPS,
    RUNNING,
    STATUS_NAMES,
    TERMINAL,
    SagaModel,
    SagaState,
    StartSaga,
    make_registry,
    make_replay_spec,
    make_saga_logic,
)

__all__ = [
    "SagaDefinition",
    "SagaStep",
    "SagaManager",
    "SagaModel",
    "SagaState",
    "StartSaga",
    "make_saga_logic",
    "make_registry",
    "make_replay_spec",
    "definition_index",
    "step_request_id",
    "compensation_request_id",
    "MAX_STEPS",
    "RUNNING",
    "COMPENSATING",
    "COMPLETED",
    "COMPENSATED",
    "DEAD_LETTER",
    "STATUS_NAMES",
    "TERMINAL",
]
