"""SagaDefinition — the ordered-step DSL a process manager executes.

A definition is pure code, registered with the :class:`~surge_tpu.saga.
manager.SagaManager` under a stable ``def_id`` (persisted in the saga
aggregate's state, so a restarted manager re-binds replayed sagas to their
definitions). Each step names the participant engine it targets and builds
its forward and compensation commands from ``(saga_id, SagaState)`` alone —
no captured per-saga context is allowed to matter, because after a crash
the ONLY inputs available are the saga id and the replayed state (the four
float context slots ``c0..c3`` plus whatever the id itself encodes).

::

    transfer = SagaDefinition(
        name="transfer", def_id=1,
        steps=(
            SagaStep("credit-src", participant="counter",
                     target=lambda sid, s: f"acct-{sid.split(':')[1]}",
                     command=lambda tid, s: counter.Increment(tid),
                     compensation=lambda tid, s: counter.Decrement(tid)),
            SagaStep("credit-dst", participant="counter",
                     target=lambda sid, s: f"acct-{sid.split(':')[2]}",
                     command=lambda tid, s: counter.Increment(tid),
                     compensation=lambda tid, s: counter.Decrement(tid)),
        ))

A step without a ``compensation`` is skipped during the reverse walk (its
effect is considered intrinsically safe to keep). Per-step retry/timeout
overrides fall back to the ``surge.saga.*`` config keys
(docs/operations.md "Running sagas").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from surge_tpu.saga.model import MAX_STEPS

#: (target_aggregate_id, saga_state) -> command object
CommandFactory = Callable[[str, Any], Any]
#: (saga_id, saga_state) -> target aggregate id
TargetFactory = Callable[[str, Any], str]


@dataclass(frozen=True)
class SagaStep:
    """One ordered unit of work: a typed command against a target aggregate
    plus the command that undoes it."""

    name: str
    participant: str
    target: TargetFactory
    command: CommandFactory
    compensation: Optional[CommandFactory] = None
    #: per-step overrides; None falls back to surge.saga.* config
    max_attempts: Optional[int] = None
    timeout_ms: Optional[float] = None
    backoff_ms: Optional[float] = None


@dataclass(frozen=True)
class SagaDefinition:
    """An ordered, immutable step list under a stable numeric id."""

    name: str
    def_id: int
    steps: Tuple[SagaStep, ...] = field(default_factory=tuple)

    def __post_init__(self) -> None:
        object.__setattr__(self, "steps", tuple(self.steps))
        if not self.steps:
            raise ValueError(f"saga {self.name!r} has no steps")
        if len(self.steps) > MAX_STEPS:
            raise ValueError(
                f"saga {self.name!r} has {len(self.steps)} steps "
                f"(max {MAX_STEPS}: progress bitmasks are int32 columns)")
        names = [s.name for s in self.steps]
        if len(set(names)) != len(names):
            raise ValueError(f"saga {self.name!r} has duplicate step names")
        if self.def_id <= 0:
            raise ValueError("def_id must be a positive, stable integer")

    @property
    def num_steps(self) -> int:
        return len(self.steps)


def definition_index(definitions) -> Dict[int, SagaDefinition]:
    """def_id -> definition, rejecting collisions (ids are persisted state)."""
    index: Dict[int, SagaDefinition] = {}
    for d in definitions:
        if d.def_id in index and index[d.def_id] is not d:
            raise ValueError(
                f"def_id {d.def_id} registered twice "
                f"({index[d.def_id].name!r} and {d.name!r})")
        index[d.def_id] = d
    return index
