"""SagaManager — the supervised process manager driving sagas to a terminal.

The manager holds NO durable state of its own.  Every transition a saga
makes is an event on the saga aggregate (surge_tpu.saga.model), so a
restarted manager rebuilds its whole world by scanning the saga engine's
state store: any non-terminal row gets a fresh driver task that re-derives
the next action purely from replayed state.  There is no side journal to
fsync, no checkpoint to age out, nothing to reconcile against the log —
the log IS the journal.

Exactly-once across retries, restarts and broker failover comes from
deterministic saga-scoped request ids:

* forward step ``n``      → ``saga:{saga_id}:{n}:fwd``
* compensation of ``n``   → ``saga:{saga_id}:{n}:comp``
* the start command       → ``saga:{saga_id}:start``
* progress records        → ``saga:{saga_id}:{n}:rec-c`` / ``rec-f`` /
  ``comp-rec`` / ``dead``

A timed-out or crash-interrupted dispatch is re-sent VERBATIM under the
same rid; the partition publisher's completed/in-flight dedup window (and
the entity-level short-circuit in front of ``process_command``) turns the
duplicate into the original outcome instead of a second fold.  The fault
plane's ``crash.saga.record.step-committed`` site fires in the torn spot —
after the participant committed but before the saga recorded it — and the
kill-failover soak proves the resumed manager closes that gap without
double-applying the step.

Reconciliation invariant (the soak verdict): every terminal saga satisfies
*all steps committed* XOR *all committed steps compensated* — COMPLETED
rows carry the full bitmask and no compensations, COMPENSATED rows carry
``compensated == committed``, and DEAD_LETTER is the only state allowed to
hold an unbalanced ledger (it is the acknowledged, operator-visible loss).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

from surge_tpu.common import Ack, Controllable, cancel_safe_wait_for
from surge_tpu.config import Config, default_config
from surge_tpu.engine.entity import CommandFailure, CommandRejected, CommandSuccess
from surge_tpu.saga.definition import SagaDefinition, definition_index
from surge_tpu.saga.model import (
    COMPENSATING,
    COMPLETED,
    DEAD_LETTER,
    RUNNING,
    STATUS_NAMES,
    TERMINAL,
    RecordDeadLetter,
    RecordStepCommitted,
    RecordStepCompensated,
    RecordStepFailed,
    SagaState,
    StartSaga,
)
from surge_tpu.testing.faults import SimulatedCrash

log = logging.getLogger("surge.saga")

#: attempts the manager makes to land a progress record on the saga
#: aggregate before parking the driver for a poll interval and re-deriving
#: (records ride the same rid-dedup window, so re-deriving is always safe)
_RECORD_ATTEMPTS = 8


def step_request_id(saga_id: str, step: int) -> str:
    """The deterministic rid a forward dispatch of ``step`` rides."""
    return f"saga:{saga_id}:{step}:fwd"


def compensation_request_id(saga_id: str, step: int) -> str:
    """The deterministic rid the compensation of ``step`` rides."""
    return f"saga:{saga_id}:{step}:comp"


class SagaManager(Controllable):
    """Drives every in-flight saga of one engine to a terminal state.

    Parameters
    ----------
    engine:
        The saga-family engine (``make_saga_logic()``) whose aggregates
        hold the saga state machines.
    definitions:
        Iterable of :class:`SagaDefinition`; ``def_id`` collisions raise.
    participants:
        participant name → engine-like (anything with ``aggregate_for``);
        step targets resolve through this map.
    faults:
        Optional :class:`~surge_tpu.testing.faults.FaultPlane` for the
        ``saga.*`` delay/error sites and ``crash.saga.*`` crash points.
        Falls back to the saga engine log's armed plane when present.
    on_signal:
        ``(name, level)`` health-bus adapter; a fired crash point emits
        ``saga-manager.crash.fatal`` here so the supervisor restarts the
        manager (the restart IS the recovery path under test).
    """

    def __init__(self, engine: Any, definitions: Iterable[SagaDefinition],
                 participants: Dict[str, Any], *,
                 config: Config | None = None, metrics: Any = None,
                 flight: Any = None, faults: Any = None,
                 on_signal: Optional[Callable[[str, str], None]] = None) -> None:
        self.engine = engine
        self.definitions = definition_index(definitions)
        self._by_name: Dict[str, SagaDefinition] = {
            d.name: d for d in self.definitions.values()}
        if len(self._by_name) != len(self.definitions):
            raise ValueError("saga definition names must be unique")
        self.participants = dict(participants)
        self.config = config or getattr(engine, "config", None) or default_config()
        self.metrics = metrics if metrics is not None else getattr(
            engine, "metrics", None)
        self.flight = flight if flight is not None else getattr(
            engine, "flight", None)
        self.faults = faults
        self.on_signal = on_signal
        cfg = self.config
        self._step_timeout_s = float(cfg.get("surge.saga.step-timeout-ms")) / 1000.0
        self._step_attempts = int(cfg.get("surge.saga.step-max-attempts"))
        self._backoff_s = float(cfg.get("surge.saga.step-backoff-ms")) / 1000.0
        self._comp_attempts = int(cfg.get("surge.saga.compensation-max-attempts"))
        self._poll_s = float(cfg.get("surge.saga.poll-interval-ms")) / 1000.0
        self._gate = asyncio.Semaphore(int(cfg.get("surge.saga.max-concurrent")))
        self._drivers: Dict[str, asyncio.Task] = {}
        self._refs: Dict[str, Any] = {}
        self._counted: set = set()
        self._running = False
        self.crashed: Optional[str] = None

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> Ack:
        self._running = True
        self.crashed = None
        resumed = self.resume_in_flight()
        self._record_flight("saga.manager.start", resumed=resumed)
        self._gauge_active()
        return Ack()

    async def stop(self) -> Ack:
        self._running = False
        drivers, self._drivers = self._drivers, {}
        for task in drivers.values():
            task.cancel()
        for task in drivers.values():
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._refs.clear()
        self._record_flight("saga.manager.stop")
        self._gauge_active()
        return Ack()

    def resume_in_flight(self) -> int:
        """Scan the saga state store and (re)spawn a driver for every
        non-terminal saga.  This is the whole recovery story: no side
        journal, just the replayed aggregate rows."""
        n = 0
        for saga_id, state in self._all_states():
            if state.status in TERMINAL:
                self._counted.add(saga_id)
                continue
            self._spawn(saga_id)
            n += 1
        return n

    def kick(self, saga_id: str) -> None:
        """Ensure a driver is running for ``saga_id`` (idempotent).

        A liveness-only helper: the soak's settle loop kicks any saga whose
        driver died with the broker it was mid-call against.  Safety never
        depends on it — a double-spawned driver's commands collapse into
        the same deterministic rids."""
        if self._running:
            self._spawn(saga_id)

    def health_check(self):
        from surge_tpu.health import HealthCheck

        status = "down" if self.crashed else ("up" if self._running else "down")
        return HealthCheck(name="saga-manager", status=status)

    # ------------------------------------------------------------ public API

    async def start_saga(self, saga_id: str, definition: str,
                         ctx: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0),
                         ) -> Dict[str, Any]:
        """Start (idempotently) a saga under ``saga_id``.

        The start command rides the deterministic ``saga:{id}:start`` rid,
        and an already-started saga answers with a rejection the caller
        treats as success — so admin-plane retries and double-submits from
        a failed-over client collapse into one StartSaga event.
        """
        d = self._by_name.get(definition)
        if d is None:
            raise KeyError(f"unknown saga definition {definition!r}")
        c = tuple(ctx) + (0.0,) * (4 - len(ctx))
        cmd = StartSaga(aggregate_id=saga_id, def_id=d.def_id,
                        num_steps=d.num_steps,
                        c0=float(c[0]), c1=float(c[1]),
                        c2=float(c[2]), c3=float(c[3]))
        res = await self._send(self.engine, saga_id, cmd,
                               f"saga:{saga_id}:start", self._step_timeout_s)
        if isinstance(res, CommandFailure):
            raise RuntimeError(f"start_saga({saga_id}) failed: {res.error!r}")
        if isinstance(res, CommandSuccess):
            self._record_flight("saga.start", saga_id=saga_id,
                                definition=definition, steps=d.num_steps)
        self._spawn(saga_id)
        return await self.status(saga_id)

    async def status(self, saga_id: str) -> Dict[str, Any]:
        """One saga's ledger, readable by an operator."""
        state = await self._load(saga_id)
        if state is None:
            return {"saga_id": saga_id, "status": "unknown"}
        d = self.definitions.get(state.def_id)
        return {
            "saga_id": saga_id,
            "status": STATUS_NAMES[state.status],
            "definition": d.name if d is not None else f"def:{state.def_id}",
            "step": state.step,
            "num_steps": state.num_steps,
            "committed": [i for i in range(state.num_steps)
                          if state.committed >> i & 1],
            "compensated": [i for i in range(state.num_steps)
                            if state.compensated >> i & 1],
            "attempts": state.attempts,
            "ctx": [state.c0, state.c1, state.c2, state.c3],
            "driver": saga_id in self._drivers,
        }

    def summary(self) -> Dict[str, Any]:
        """Fleet-shaped counts + the reconciliation verdict."""
        verdict = self.reconcile()
        verdict["drivers"] = len(self._drivers)
        verdict["running"] = self._running
        return verdict

    def reconcile(self) -> Dict[str, Any]:
        """The ledger-reconciliation invariant over EVERY saga row.

        A terminal saga must satisfy *all steps committed* XOR *all
        committed steps compensated*; DEAD_LETTER is the only acknowledged
        exception.  Violations here are exactly the soak's
        "half-compensated" count — the verdict must come back empty.
        """
        counts = {name: 0 for name in STATUS_NAMES.values()}
        violations = []
        total = 0
        for saga_id, st in self._all_states():
            total += 1
            counts[STATUS_NAMES[st.status]] += 1
            full = (1 << st.num_steps) - 1
            if st.status == COMPLETED:
                if st.committed != full:
                    violations.append({"saga_id": saga_id,
                                       "kind": "completed-missing-steps",
                                       "committed": st.committed, "full": full})
                if st.compensated:
                    violations.append({"saga_id": saga_id,
                                       "kind": "completed-but-compensated",
                                       "compensated": st.compensated})
            elif st.status not in (RUNNING, COMPENSATING, DEAD_LETTER):
                # COMPENSATED: every committed step must be undone
                if st.compensated != st.committed:
                    violations.append({"saga_id": saga_id,
                                       "kind": "half-compensated",
                                       "committed": st.committed,
                                       "compensated": st.compensated})
        return {"ok": not violations, "total": total, "counts": counts,
                "violations": violations,
                "in_flight": counts["running"] + counts["compensating"],
                "dead_letter": counts["dead-letter"]}

    # ---------------------------------------------------------- driver loop

    def _spawn(self, saga_id: str) -> None:
        existing = self._drivers.get(saga_id)
        if existing is not None and not existing.done():
            return
        task = asyncio.get_running_loop().create_task(
            self._drive(saga_id), name=f"saga-driver-{saga_id}")
        self._drivers[saga_id] = task
        task.add_done_callback(lambda t, sid=saga_id: self._reap(sid, t))
        self._gauge_active()

    def _reap(self, saga_id: str, task: asyncio.Task) -> None:
        if self._drivers.get(saga_id) is task:
            del self._drivers[saga_id]
        self._refs.pop(saga_id, None)
        if not task.cancelled():
            exc = task.exception()
            if exc is not None and not isinstance(exc, SimulatedCrash):
                log.warning("saga driver %s died: %r", saga_id, exc)
        self._gauge_active()

    async def _drive(self, saga_id: str) -> None:
        misses = 0
        try:
            while self._running:
                state = await self._load(saga_id)
                if state is None:
                    # started but the fold hasn't landed yet (or unknown id)
                    misses += 1
                    if misses > 100:
                        log.warning("saga %s never materialized; driver exiting",
                                    saga_id)
                        return
                    await asyncio.sleep(self._poll_s)
                    continue
                misses = 0
                if state.status in TERMINAL:
                    self._finish(saga_id, state)
                    return
                d = self.definitions.get(state.def_id)
                if d is None:
                    log.warning("saga %s references unknown def_id %d; parked",
                                saga_id, state.def_id)
                    self._record_flight("saga.parked", saga_id=saga_id,
                                        def_id=state.def_id)
                    return
                if state.status == RUNNING:
                    ok = await self._forward(saga_id, state, d)
                else:
                    ok = await self._compensate(saga_id, state, d)
                if not ok:
                    await asyncio.sleep(self._poll_s)
        except asyncio.CancelledError:
            raise
        except SimulatedCrash as exc:
            # The torn spot under test: the participant committed (or the
            # record landed) and the manager died before the next action.
            # Surface a fatal signal; the health supervisor restarts the
            # manager, whose resume scan re-derives this saga's next move
            # under the SAME rids — the dedup window makes it exactly-once.
            self.crashed = str(exc)
            self._record_flight("saga.manager.crash", saga_id=saga_id,
                                point=str(exc))
            if self.on_signal is not None:
                self.on_signal("saga-manager.crash.fatal", "fatal")
            raise

    async def _forward(self, saga_id: str, state: SagaState,
                       d: SagaDefinition) -> bool:
        step_i = state.step
        sdef = d.steps[step_i]
        participant = self.participants.get(sdef.participant)
        if participant is None:
            log.warning("saga %s step %d names unknown participant %r",
                        saga_id, step_i, sdef.participant)
            return await self._record(
                saga_id, RecordStepFailed(saga_id, step_i, 0),
                f"saga:{saga_id}:{step_i}:rec-f")
        target = sdef.target(saga_id, state)
        cmd = sdef.command(target, state)
        rid = step_request_id(saga_id, step_i)
        max_attempts = sdef.max_attempts or self._step_attempts
        timeout_s = (sdef.timeout_ms / 1000.0 if sdef.timeout_ms
                     else self._step_timeout_s)
        backoff_s = (sdef.backoff_ms / 1000.0 if sdef.backoff_ms
                     else self._backoff_s)
        attempts = 0
        while attempts < max_attempts:
            attempts += 1
            self._point("saga.step.dispatch")
            t0 = time.monotonic()
            async with self._gate:
                res = await self._send(participant, target, cmd, rid, timeout_s)
            self._time_step((time.monotonic() - t0) * 1000.0)
            if isinstance(res, CommandSuccess):
                self._record_flight("saga.step.commit", saga_id=saga_id,
                                    step=step_i, name=sdef.name,
                                    target=target, attempt=attempts)
                # the torn spot: participant committed, saga not yet told
                self._crash("saga.record.step-committed")
                return await self._record(
                    saga_id, RecordStepCommitted(saga_id, step_i),
                    f"saga:{saga_id}:{step_i}:rec-c")
            if isinstance(res, CommandRejected):
                # business no — never retried, flips the saga to compensation
                self._record_flight("saga.step.reject", saga_id=saga_id,
                                    step=step_i, name=sdef.name,
                                    reason=repr(res.reason))
                return await self._record(
                    saga_id, RecordStepFailed(saga_id, step_i, attempts),
                    f"saga:{saga_id}:{step_i}:rec-f")
            # CommandFailure: timeout / publish / routing — the SAME rid
            # rides the retry, so a command that actually landed dedups
            self._record_flight("saga.step.retry", saga_id=saga_id,
                                step=step_i, attempt=attempts,
                                error=repr(getattr(res, "error", res)))
            if attempts < max_attempts:
                await asyncio.sleep(backoff_s * (2 ** (attempts - 1)))
        self._record_flight("saga.step.exhausted", saga_id=saga_id,
                            step=step_i, attempts=attempts)
        return await self._record(
            saga_id, RecordStepFailed(saga_id, step_i, attempts),
            f"saga:{saga_id}:{step_i}:rec-f")

    async def _compensate(self, saga_id: str, state: SagaState,
                          d: SagaDefinition) -> bool:
        pending = state.committed & ~state.compensated
        if pending == 0:
            # the fold flips status when the masks meet; re-read
            return True
        step_i = pending.bit_length() - 1  # reverse order: highest first
        sdef = d.steps[step_i]
        rec = RecordStepCompensated(saga_id, step_i)
        rec_rid = f"saga:{saga_id}:{step_i}:comp-rec"
        if sdef.compensation is None:
            # intrinsically safe to keep — recorded as compensated so the
            # ledger balances without issuing a command
            self._record_flight("saga.comp.skip", saga_id=saga_id,
                                step=step_i, name=sdef.name)
            return await self._record(saga_id, rec, rec_rid)
        participant = self.participants.get(sdef.participant)
        if participant is None:
            return await self._record(
                saga_id, RecordDeadLetter(saga_id, step_i),
                f"saga:{saga_id}:{step_i}:dead")
        target = sdef.target(saga_id, state)
        cmd = sdef.compensation(target, state)
        rid = compensation_request_id(saga_id, step_i)
        timeout_s = (sdef.timeout_ms / 1000.0 if sdef.timeout_ms
                     else self._step_timeout_s)
        backoff_s = (sdef.backoff_ms / 1000.0 if sdef.backoff_ms
                     else self._backoff_s)
        attempts = 0
        while attempts < self._comp_attempts:
            attempts += 1
            self._point("saga.compensation.dispatch")
            t0 = time.monotonic()
            async with self._gate:
                res = await self._send(participant, target, cmd, rid, timeout_s)
            self._time_step((time.monotonic() - t0) * 1000.0)
            if isinstance(res, CommandSuccess):
                self._record_flight("saga.comp.commit", saga_id=saga_id,
                                    step=step_i, name=sdef.name,
                                    target=target, attempt=attempts)
                self._crash("saga.record.step-compensated")
                return await self._record(saga_id, rec, rec_rid)
            if isinstance(res, CommandRejected):
                # the participant refuses to undo — retrying cannot help;
                # park the saga in the operator-visible dead letter
                self._record_flight("saga.comp.reject", saga_id=saga_id,
                                    step=step_i, reason=repr(res.reason))
                return await self._record(
                    saga_id, RecordDeadLetter(saga_id, step_i),
                    f"saga:{saga_id}:{step_i}:dead")
            self._record_flight("saga.comp.retry", saga_id=saga_id,
                                step=step_i, attempt=attempts,
                                error=repr(getattr(res, "error", res)))
            if attempts < self._comp_attempts:
                await asyncio.sleep(backoff_s * (2 ** (attempts - 1)))
        self._record_flight("saga.comp.exhausted", saga_id=saga_id,
                            step=step_i, attempts=attempts)
        return await self._record(
            saga_id, RecordDeadLetter(saga_id, step_i),
            f"saga:{saga_id}:{step_i}:dead")

    # ------------------------------------------------------------- plumbing

    async def _record(self, saga_id: str, cmd: Any, rid: str) -> bool:
        """Land a progress record on the saga aggregate.

        A rejection means the record is already folded (the Record*
        commands are idempotent-by-rejection) — both outcomes hand control
        back to the driver loop, which re-reads state and re-derives."""
        for attempt in range(_RECORD_ATTEMPTS):
            res = await self._send(self.engine, saga_id, cmd, rid,
                                   self._step_timeout_s)
            if isinstance(res, (CommandSuccess, CommandRejected)):
                return True
            await asyncio.sleep(self._poll_s * (attempt + 1))
        log.warning("saga %s could not land %s after %d attempts",
                    saga_id, type(cmd).__name__, _RECORD_ATTEMPTS)
        return False

    async def _send(self, engine: Any, aggregate_id: str, cmd: Any,
                    rid: str, timeout_s: float) -> Any:
        ref = engine.aggregate_for(aggregate_id)
        try:
            return await cancel_safe_wait_for(
                ref.send_command(cmd, request_id=rid), timeout_s)
        except asyncio.TimeoutError as exc:
            return CommandFailure(exc)
        except (asyncio.CancelledError, SimulatedCrash):
            raise
        except Exception as exc:  # noqa: BLE001 — routing errors are retryable
            return CommandFailure(exc)

    async def _load(self, saga_id: str) -> Optional[SagaState]:
        ref = self._refs.get(saga_id)
        if ref is None:
            ref = self._refs[saga_id] = self.engine.aggregate_for(saga_id)
        try:
            return await ref.get_state()
        except Exception:  # noqa: BLE001 — transient; the driver re-polls
            return None

    def _all_states(self) -> Iterator[Tuple[str, SagaState]]:
        indexer = getattr(self.engine, "indexer", None)
        if indexer is None:
            return
        state_format = self.engine.logic.state_format
        for key, data in indexer.store.all_items():
            try:
                st = state_format.read_state(data)
            except Exception:  # noqa: BLE001 — foreign rows are skipped
                continue
            if isinstance(st, SagaState):
                yield key, st

    def _finish(self, saga_id: str, state: SagaState) -> None:
        if saga_id in self._counted:
            return
        self._counted.add(saga_id)
        self._record_flight("saga.terminal", saga_id=saga_id,
                            status=STATUS_NAMES[state.status],
                            committed=state.committed,
                            compensated=state.compensated)
        m = self.metrics
        if m is None:
            return
        if state.status == COMPLETED:
            m.saga_completed.record(1)
        elif state.status == DEAD_LETTER:
            m.saga_dead_letter.record(1)
        else:
            m.saga_compensated.record(1)

    def _plane(self) -> Any:
        if self.faults is not None:
            return self.faults
        return getattr(getattr(self.engine, "log", None), "faults", None)

    def _point(self, site: str) -> None:
        plane = self._plane()
        if plane is not None:
            plane.point(site)

    def _crash(self, name: str) -> None:
        plane = self._plane()
        if plane is not None:
            plane.crash_point(name)

    def _gauge_active(self) -> None:
        if self.metrics is not None:
            self.metrics.saga_active.record(float(len(self._drivers)))

    def _time_step(self, ms: float) -> None:
        if self.metrics is not None:
            self.metrics.saga_step_timer.record_ms(ms)

    def _record_flight(self, etype: str, **fields: Any) -> None:
        if self.flight is not None:
            self.flight.record(etype, **fields)
