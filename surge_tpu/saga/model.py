"""The saga bounded context — saga state IS an aggregate.

The process-manager's whole durability story is that a saga's progress lives
in an ordinary aggregate family: every transition is an event published
through the transactional publisher, state replays through the TPU replay
plane (scratch replay and the resident plane's incremental fold are
byte-identical — tests/test_saga_replay.py), and recovery after a manager
restart is nothing but reading the replayed state back (no side journal).

The state is deliberately ALL-NUMERIC so the family stays on the tensor
path: step progress is a pair of bitmasks (``committed`` / ``compensated``,
capped at :data:`MAX_STEPS` steps), the definition is referenced by its
registered ``def_id``, and the only free-form payload is four float32
context slots (``c0..c3``) the definition's command factories interpret.
Anything stringly (target aggregate ids, poison markers) must be derived
from the saga id + context by the :class:`~surge_tpu.saga.definition.
SagaDefinition`'s callables — which is exactly what makes resumption pure:
the next action is a function of replayed state alone.

Status machine::

    RUNNING --step n committed--> RUNNING (step=n+1)   [all committed -> COMPLETED]
    RUNNING --step n failed-----> COMPENSATING         [nothing committed -> COMPENSATED]
    COMPENSATING --comp n-------> COMPENSATING         [all committed compensated -> COMPENSATED]
    COMPENSATING --comp exhausted-> DEAD_LETTER

``COMPLETED`` / ``COMPENSATED`` / ``DEAD_LETTER`` are terminal. The
ledger-reconciliation invariant (cluster/soak.py saga arm, chaos.py sagas):
every terminal saga has either ALL steps committed and none compensated, or
ALL committed steps compensated — dead-lettered sagas are the operator's
queue and are reported separately, never silently counted as reconciled.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from surge_tpu.codec.schema import SchemaRegistry
from surge_tpu.engine.model import RejectedCommand, ReplayHandlers, ReplaySpec
from surge_tpu.serialization import (JsonCommandFormatting, JsonEventFormatting,
                                     JsonFormatting)

#: step-index cap: progress bitmasks live in one int32 state column
MAX_STEPS = 30

#: status enum (int32 state column; 0 must be RUNNING so the replay plane's
#: zero-initialized row folds correctly from the SagaStarted event)
RUNNING, COMPENSATING, COMPLETED, COMPENSATED, DEAD_LETTER = 0, 1, 2, 3, 4

STATUS_NAMES = {RUNNING: "running", COMPENSATING: "compensating",
                COMPLETED: "completed", COMPENSATED: "compensated",
                DEAD_LETTER: "dead-letter"}

TERMINAL = frozenset((COMPLETED, COMPENSATED, DEAD_LETTER))


# --- domain types -------------------------------------------------------------------


@dataclass(frozen=True)
class SagaState:
    aggregate_id: str
    def_id: int
    num_steps: int
    status: int
    step: int          # next forward step index while RUNNING
    committed: int     # bitmask of committed forward steps
    compensated: int   # bitmask of compensated steps
    attempts: int      # attempts burned on the failing step (observability)
    c0: float
    c1: float
    c2: float
    c3: float
    version: int


@dataclass(frozen=True)
class StartSaga:
    aggregate_id: str
    def_id: int
    num_steps: int
    c0: float = 0.0
    c1: float = 0.0
    c2: float = 0.0
    c3: float = 0.0


@dataclass(frozen=True)
class RecordStepCommitted:
    aggregate_id: str
    step: int


@dataclass(frozen=True)
class RecordStepFailed:
    aggregate_id: str
    step: int
    attempts: int


@dataclass(frozen=True)
class RecordStepCompensated:
    aggregate_id: str
    step: int


@dataclass(frozen=True)
class RecordDeadLetter:
    aggregate_id: str
    step: int


@dataclass(frozen=True)
class SagaStarted:
    aggregate_id: str
    def_id: int
    num_steps: int
    c0: float
    c1: float
    c2: float
    c3: float
    sequence_number: int


@dataclass(frozen=True)
class SagaStepCommitted:
    aggregate_id: str
    step: int
    sequence_number: int


@dataclass(frozen=True)
class SagaStepFailed:
    aggregate_id: str
    step: int
    attempts: int
    sequence_number: int


@dataclass(frozen=True)
class SagaStepCompensated:
    aggregate_id: str
    step: int
    sequence_number: int


@dataclass(frozen=True)
class SagaDeadLettered:
    aggregate_id: str
    step: int
    sequence_number: int


def _full_mask(num_steps: int) -> int:
    return (1 << num_steps) - 1


# --- scalar model --------------------------------------------------------------------


class SagaModel:
    """Command/fold model for the saga aggregate family.

    Every Record* command is IDEMPOTENT-BY-REJECTION: re-recording an
    already-recorded transition rejects instead of emitting a duplicate
    event, so the manager's deterministic re-delivery after a crash can
    treat ``CommandRejected`` on a record as "already done, move on"."""

    def initial_state(self, aggregate_id: str) -> Optional[SagaState]:
        return None

    def process_command(self, state: Optional[SagaState], command) -> Sequence[object]:
        seq = (state.version if state else 0) + 1
        if isinstance(command, StartSaga):
            if state is not None:
                raise RejectedCommand("saga already started")
            if not 1 <= command.num_steps <= MAX_STEPS:
                raise RejectedCommand(
                    f"num_steps must be 1..{MAX_STEPS}, got {command.num_steps}")
            return [SagaStarted(command.aggregate_id, command.def_id,
                                command.num_steps, command.c0, command.c1,
                                command.c2, command.c3, seq)]
        if state is None:
            raise RejectedCommand("saga not started")
        if isinstance(command, RecordStepCommitted):
            if state.status != RUNNING:
                raise RejectedCommand(
                    f"saga is {STATUS_NAMES[state.status]}, not running")
            if command.step != state.step or state.committed & (1 << command.step):
                raise RejectedCommand(
                    f"step {command.step} is not the pending step "
                    f"(pending={state.step})")
            return [SagaStepCommitted(command.aggregate_id, command.step, seq)]
        if isinstance(command, RecordStepFailed):
            if state.status != RUNNING:
                raise RejectedCommand(
                    f"saga is {STATUS_NAMES[state.status]}, not running")
            if command.step != state.step:
                raise RejectedCommand(
                    f"step {command.step} is not the pending step "
                    f"(pending={state.step})")
            return [SagaStepFailed(command.aggregate_id, command.step,
                                   command.attempts, seq)]
        if isinstance(command, RecordStepCompensated):
            if state.status != COMPENSATING:
                raise RejectedCommand(
                    f"saga is {STATUS_NAMES[state.status]}, not compensating")
            bit = 1 << command.step
            if not state.committed & bit:
                raise RejectedCommand(f"step {command.step} never committed")
            if state.compensated & bit:
                raise RejectedCommand(f"step {command.step} already compensated")
            return [SagaStepCompensated(command.aggregate_id, command.step, seq)]
        if isinstance(command, RecordDeadLetter):
            if state.status in TERMINAL:
                raise RejectedCommand(
                    f"saga is already terminal ({STATUS_NAMES[state.status]})")
            return [SagaDeadLettered(command.aggregate_id, command.step, seq)]
        raise RejectedCommand(f"unknown command {command!r}")

    def handle_event(self, state: Optional[SagaState], event) -> Optional[SagaState]:
        if isinstance(event, SagaStarted):
            return SagaState(event.aggregate_id, event.def_id, event.num_steps,
                             RUNNING, 0, 0, 0, 0, event.c0, event.c1,
                             event.c2, event.c3, event.sequence_number)
        if state is None:
            return None  # orphan record event: nothing to fold onto
        if isinstance(event, SagaStepCommitted):
            committed = state.committed | (1 << event.step)
            done = committed == _full_mask(state.num_steps)
            return SagaState(state.aggregate_id, state.def_id, state.num_steps,
                             COMPLETED if done else RUNNING,
                             event.step + 1, committed, state.compensated, 0,
                             state.c0, state.c1, state.c2, state.c3,
                             event.sequence_number)
        if isinstance(event, SagaStepFailed):
            nothing_committed = state.committed == 0
            return SagaState(state.aggregate_id, state.def_id, state.num_steps,
                             COMPENSATED if nothing_committed else COMPENSATING,
                             state.step, state.committed, state.compensated,
                             event.attempts, state.c0, state.c1, state.c2,
                             state.c3, event.sequence_number)
        if isinstance(event, SagaStepCompensated):
            compensated = state.compensated | (1 << event.step)
            done = compensated == state.committed
            return SagaState(state.aggregate_id, state.def_id, state.num_steps,
                             COMPENSATED if done else COMPENSATING,
                             state.step, state.committed, compensated,
                             state.attempts, state.c0, state.c1, state.c2,
                             state.c3, event.sequence_number)
        if isinstance(event, SagaDeadLettered):
            return SagaState(state.aggregate_id, state.def_id, state.num_steps,
                             DEAD_LETTER, state.step, state.committed,
                             state.compensated, state.attempts, state.c0,
                             state.c1, state.c2, state.c3,
                             event.sequence_number)
        return state

    # -- TPU replay contract ----------------------------------------------------------
    def replay_spec(self) -> ReplaySpec:
        return make_replay_spec()


# --- tensor schemas + JAX fold -------------------------------------------------------

STARTED, STEP_COMMITTED, STEP_FAILED, STEP_COMPENSATED, DEAD_LETTERED = \
    0, 1, 2, 3, 4


def make_registry() -> SchemaRegistry:
    reg = SchemaRegistry()
    reg.register_event(SagaStarted, type_id=STARTED, exclude=("aggregate_id",))
    reg.register_event(SagaStepCommitted, type_id=STEP_COMMITTED,
                       exclude=("aggregate_id",), bits={"step": 5})
    reg.register_event(SagaStepFailed, type_id=STEP_FAILED,
                       exclude=("aggregate_id",), bits={"step": 5})
    reg.register_event(SagaStepCompensated, type_id=STEP_COMPENSATED,
                       exclude=("aggregate_id",), bits={"step": 5})
    reg.register_event(SagaDeadLettered, type_id=DEAD_LETTERED,
                       exclude=("aggregate_id",), bits={"step": 5})
    reg.register_state(SagaState, exclude=("aggregate_id",))
    return reg


def make_replay_spec() -> ReplaySpec:
    """The saga fold in batched tensor form — every branch of
    ``handle_event`` as masked int32 arithmetic (bitmask progress makes the
    status transitions pure compares, no data-dependent control flow)."""
    import jax.numpy as jnp

    def _shift(step):
        return jnp.left_shift(jnp.int32(1), step.astype(jnp.int32))

    def started(s, f):
        return {"def_id": f["def_id"], "num_steps": f["num_steps"],
                "status": jnp.full_like(f["num_steps"], RUNNING),
                "step": jnp.zeros_like(f["num_steps"]),
                "committed": jnp.zeros_like(f["num_steps"]),
                "compensated": jnp.zeros_like(f["num_steps"]),
                "attempts": jnp.zeros_like(f["num_steps"]),
                "c0": f["c0"], "c1": f["c1"], "c2": f["c2"], "c3": f["c3"],
                "version": f["sequence_number"]}

    def step_committed(s, f):
        committed = s["committed"] | _shift(f["step"])
        full = jnp.left_shift(jnp.int32(1), s["num_steps"]) - 1
        done = committed == full
        return {"committed": committed,
                "status": jnp.where(done, COMPLETED, RUNNING)
                    .astype(s["status"].dtype),
                "step": (f["step"] + 1).astype(s["step"].dtype),
                "attempts": jnp.zeros_like(s["attempts"]),
                "version": f["sequence_number"]}

    def step_failed(s, f):
        nothing = s["committed"] == 0
        return {"status": jnp.where(nothing, COMPENSATED, COMPENSATING)
                    .astype(s["status"].dtype),
                "attempts": f["attempts"].astype(s["attempts"].dtype),
                "version": f["sequence_number"]}

    def step_compensated(s, f):
        compensated = s["compensated"] | _shift(f["step"])
        done = compensated == s["committed"]
        return {"compensated": compensated,
                "status": jnp.where(done, COMPENSATED, COMPENSATING)
                    .astype(s["status"].dtype),
                "version": f["sequence_number"]}

    def dead_lettered(s, f):
        return {"status": jnp.full_like(s["status"], DEAD_LETTER),
                "version": f["sequence_number"]}

    return ReplaySpec(
        registry=make_registry(),
        handlers=ReplayHandlers({STARTED: started,
                                 STEP_COMMITTED: step_committed,
                                 STEP_FAILED: step_failed,
                                 STEP_COMPENSATED: step_compensated,
                                 DEAD_LETTERED: dead_lettered}),
        init_record={"def_id": 0, "num_steps": 0, "status": RUNNING,
                     "step": 0, "committed": 0, "compensated": 0,
                     "attempts": 0, "c0": 0.0, "c1": 0.0, "c2": 0.0,
                     "c3": 0.0, "version": 0},
    )


# --- byte formats --------------------------------------------------------------------

_EVENT_TYPES = {c.__name__: c for c in (SagaStarted, SagaStepCommitted,
                                        SagaStepFailed, SagaStepCompensated,
                                        SagaDeadLettered)}
_COMMAND_TYPES = {c.__name__: c for c in (StartSaga, RecordStepCommitted,
                                          RecordStepFailed,
                                          RecordStepCompensated,
                                          RecordDeadLetter)}


def _to_tagged_dict(obj) -> dict:
    d = {k: getattr(obj, k) for k in obj.__dataclass_fields__}
    d["_type"] = type(obj).__name__
    return d


def _from_tagged_dict(type_map: dict, d: dict):
    d = dict(d)
    return type_map[d.pop("_type")](**d)


def event_formatting() -> JsonEventFormatting:
    return JsonEventFormatting(
        to_dict=_to_tagged_dict,
        from_dict=lambda d: _from_tagged_dict(_EVENT_TYPES, d),
        key_of=lambda e: e.aggregate_id)


def command_formatting() -> JsonCommandFormatting:
    return JsonCommandFormatting(
        to_dict=_to_tagged_dict,
        from_dict=lambda d: _from_tagged_dict(_COMMAND_TYPES, d))


def state_formatting() -> JsonFormatting:
    return JsonFormatting(
        to_dict=lambda s: {k: getattr(s, k) for k in s.__dataclass_fields__},
        from_dict=lambda d: SagaState(**d))


def make_saga_logic(aggregate_name: str = "saga"):
    """The saga family's :class:`SurgeCommandBusinessLogic` bundle — hand it
    to ``create_engine`` to host saga state like any other aggregate."""
    from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic

    return SurgeCommandBusinessLogic(
        aggregate_name=aggregate_name, model=SagaModel(),
        state_format=state_formatting(), event_format=event_formatting(),
        command_format=command_formatting())
