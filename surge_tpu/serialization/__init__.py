"""L0 serialization contracts.

Equivalent of the reference's ``modules/serialization``:
- ``SerializedMessage`` (key/value/headers) — serialization/src/main/scala/surge/core/SerializedMessage.scala:6
- ``SerializedAggregate`` — serialization/src/main/scala/surge/core/SerializedAggregate.scala:7
- ``SurgeAggregateReadFormatting`` / ``SurgeAggregateWriteFormatting`` /
  ``SurgeEventWriteFormatting`` — surge/core/SurgeFormatting.scala:5-17

These are pure byte-level contracts between user domain types and the log. The TPU build
adds a parallel *tensor* contract in ``surge_tpu.codec`` (event→tensor codec) so the same
domain events have both a byte form (log/durability path) and a tensor form (replay path).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, Mapping, Protocol, Sequence, TypeVar

State = TypeVar("State")
Event = TypeVar("Event")


@dataclass(frozen=True)
class SerializedMessage:
    """A serialized event destined for the events topic.

    Mirrors surge.core.SerializedMessage (key, value, headers) — SerializedMessage.scala:6.
    """

    key: str
    value: bytes
    headers: Mapping[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class SerializedAggregate:
    """A serialized aggregate state snapshot destined for the compacted state topic.

    Mirrors surge.core.SerializedAggregate — SerializedAggregate.scala:7. ``value=None``
    encodes deletion (tombstone on the compacted topic).
    """

    value: bytes | None
    headers: Mapping[str, str] = field(default_factory=dict)


class AggregateWriteFormatting(Protocol[State]):
    """surge.core.SurgeAggregateWriteFormatting — SurgeFormatting.scala:9-11."""

    def write_state(self, state: State | None) -> SerializedAggregate: ...


class AggregateReadFormatting(Protocol[State]):
    """surge.core.SurgeAggregateReadFormatting — SurgeFormatting.scala:5-7."""

    def read_state(self, data: bytes) -> State | None: ...


class EventWriteFormatting(Protocol[Event]):
    """surge.core.SurgeEventWriteFormatting — SurgeFormatting.scala:13-15."""

    def write_event(self, event: Event) -> SerializedMessage: ...


class EventReadFormatting(Protocol[Event]):
    """Inverse of EventWriteFormatting; needed by the replay path (the reference reads
    events back only through Kafka Streams restore; our TPU replay decodes them)."""

    def read_event(self, msg: SerializedMessage) -> Event: ...


# --- JSON convenience formatters (play-json Format equivalents used throughout the
#     reference's tests, e.g. TestBoundedContext.scala:84-110) ---


@dataclass
class JsonFormatting(Generic[State]):
    """Round-trips dataclass-like objects via user-provided to/from dict functions."""

    to_dict: Callable[[Any], dict]
    from_dict: Callable[[dict], Any]

    def write_state(self, state: Any | None) -> SerializedAggregate:
        if state is None:
            return SerializedAggregate(value=None)
        return SerializedAggregate(value=json.dumps(self.to_dict(state)).encode())

    def read_state(self, data: bytes) -> Any | None:
        if not data:
            return None
        return self.from_dict(json.loads(data.decode()))


@dataclass
class JsonCommandFormatting:
    """Command ⇄ bytes codec for cross-node delivery (the Jackson-CBOR envelope
    serialization role of the reference's remoting, core reference.conf:1-11)."""

    to_dict: Callable[[Any], dict]
    from_dict: Callable[[dict], Any]

    def write_command(self, command: Any) -> bytes:
        return json.dumps(self.to_dict(command)).encode()

    def read_command(self, data: bytes) -> Any:
        return self.from_dict(json.loads(data.decode()))


@dataclass
class JsonEventFormatting(Generic[Event]):
    """Event JSON formatter; key is the aggregate id extracted by ``key_of``."""

    to_dict: Callable[[Any], dict]
    from_dict: Callable[[dict], Any]
    key_of: Callable[[Any], str]

    def write_event(self, event: Any) -> SerializedMessage:
        return SerializedMessage(key=self.key_of(event), value=json.dumps(self.to_dict(event)).encode())

    def read_event(self, msg: SerializedMessage) -> Any:
        return self.from_dict(json.loads(msg.value.decode()))

    def read_events_batch(self, values: Sequence[bytes]) -> list:
        """Decode a whole batch of event payloads in ONE C-level JSON parse:
        the payloads join into a single JSON array, so the per-call
        ``json.loads`` overhead (scanner setup, unicode round trip) is paid
        once per BATCH instead of once per event. The resident plane's
        refresh feed rides this (ISSUE 12: the sustained-fold host leg);
        semantically identical to ``read_event`` per value — a malformed
        payload raises, and the caller degrades to the per-event path to
        find (and poison) the offender."""
        if not values:
            return []
        doc = json.loads(b"[" + b",".join(values) + b"]")
        from_dict = self.from_dict
        return [from_dict(d) for d in doc]


__all__ = [
    "SerializedMessage",
    "SerializedAggregate",
    "AggregateReadFormatting",
    "AggregateWriteFormatting",
    "EventWriteFormatting",
    "EventReadFormatting",
    "JsonFormatting",
    "JsonCommandFormatting",
    "JsonEventFormatting",
]
