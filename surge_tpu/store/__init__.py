"""Materialized aggregate-state store — the KTable equivalent.

Reference: the embedded Kafka Streams KTable over the compacted state topic
(modules/common/src/main/scala/surge/kafka/streams/AggregateStateStoreKafkaStreams.scala:53-178,
SurgeStateStoreConsumer.scala:57-76 — "the entire KTable is just a compacted-topic →
key-value-store index"). Here the index is an explicit asyncio consumer task
(:class:`StateStoreIndexer`) over a pluggable :class:`KeyValueStore`, with
``(partition, offset)`` watermarks answering the publisher's lag queries, plus a **bulk
restore** path that rebuilds the whole store by folding the events topic through the TPU
replay engine (``surge.replay.backend=tpu``) or the scalar fold (``cpu``) — the
north-star workload (SURVEY.md §3.3, BASELINE.md).
"""

from surge_tpu.store.kv import InMemoryKeyValueStore, KeyValueStore
from surge_tpu.store.indexer import StateStoreIndexer
from surge_tpu.store.checkpoint import Checkpoint, CheckpointStore, CheckpointWriter
from surge_tpu.store.restore import (
    RestoreResult,
    restore_from_events,
    restore_from_segment,
    restore_from_state_topic,
)

__all__ = [
    "Checkpoint",
    "CheckpointStore",
    "CheckpointWriter",
    "InMemoryKeyValueStore",
    "KeyValueStore",
    "StateStoreIndexer",
    "RestoreResult",
    "restore_from_events",
    "restore_from_segment",
    "restore_from_state_topic",
]
