"""Aggregate-state checkpoints — the bounded-cold-start half of the compaction PR.

``restore_from_events`` folds the events topic from offset 0: O(total history) per
cold start. A **checkpoint** is an atomic snapshot of every aggregate's folded state
together with the exact per-partition event-offset watermarks the fold had consumed —
so a cold start becomes *load checkpoint, TPU-fold only the tail* (the
checkpoint/resume contract ROADMAP and SURVEY.md §5.4 promise: the tensor carry
resumes from ``ReplayEngine.carry_from_states``).

Three pieces:

- :class:`Checkpoint` — the value: ``seq``, events ``watermarks`` (partition → next
  offset), and ``states`` (aggregate id → ``serialize_state`` bytes; ``None`` marks an
  aggregate whose fold produced ``None`` — it must still resume from ``None``, not
  from the model's initial state).
- :class:`CheckpointStore` — durable directory of ``ckpt-<seq>.ck`` files. Writes are
  crash-atomic (tmp write → fsync → rename → directory fsync) and pruned to the
  newest N; a torn or unreadable newest file falls back to the previous one. The
  payload reuses the segment block codec (surge_tpu.log.segment): states ride as
  key/value records — tombstone framing for ``None`` states — CRC-checked and
  native-compressed when the codec is built.
- :class:`CheckpointWriter` — the incremental materializer: a supervised background
  task that tails the events topic with the scalar (cpu) fold, advancing its own
  state map from the previous checkpoint instead of re-folding history, and writes a
  checkpoint on a publisher-style cadence (interval + min-events gate). Consistency
  is by construction: the watermark is captured before each advance and every state
  in the file is the fold of exactly the events below it.
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from surge_tpu.common import Ack, BackgroundTask, Controllable, logger
from surge_tpu.config import Config, default_config
from surge_tpu.log import segment as seg
from surge_tpu.log.file import _fsync_dir
from surge_tpu.log.transport import LogRecord, page_keyed_records

__all__ = ["Checkpoint", "CheckpointStore", "CheckpointWriter",
           "encode_partition_slice", "decode_partition_slice"]

_MAGIC = b"SCKP"
_HEADER = struct.Struct("<4sI")  # magic | header_json_len

_SLICE_MAGIC = b"SSLC"


def encode_partition_slice(records: Sequence[LogRecord], topic: str,
                           partition: int,
                           base: Optional[int] = None) -> bytes:
    """One self-describing wire slice of a log partition, built from the
    checkpoint file's atomic per-partition blocks (the segment block codec:
    CRC-checked, native-compressed when built). Records keep their
    leader-assigned offsets and timestamps — a standby ingesting a slice
    converges verbatim with its source — and are split into contiguous-offset
    runs, one block each, exactly like FileLog's verbatim append (a block's
    decode assigns ``base+i``, so it must never span a compaction hole).
    This is the bulk lane of standby catch-up and live partition handoff:
    block-encoded pages instead of per-record protobuf messages. ``base`` is
    the offset the slice was READ FROM: when it is below the first record's
    offset, the head hole is a compaction gap the source vouches for — an
    installer may ingest past it, where an unexplained head gap must be
    refused (missing records, not compacted ones)."""
    runs: List[List[LogRecord]] = []
    for r in records:
        if runs and r.offset == runs[-1][-1].offset + 1:
            runs[-1].append(r)
        else:
            runs.append([r])
    blocks = [seg.encode_block(run, run[0].offset) for run in runs]
    first = records[0].offset if records else 0
    header = json.dumps({
        "version": 1, "topic": topic, "partition": partition,
        "count": len(records), "blocks": len(blocks),
        "from": first, "base": first if base is None else int(base),
        "end": records[-1].offset + 1 if records else 0,
    }).encode()
    return b"".join([_HEADER.pack(_SLICE_MAGIC, len(header)), header] + blocks)


def decode_partition_slice(data: bytes):
    """(header dict, records) from :func:`encode_partition_slice` bytes; the
    block CRCs make a torn/garbled slice fail loudly instead of ingesting a
    corrupt prefix."""
    magic, hlen = _HEADER.unpack_from(data, 0)
    if magic != _SLICE_MAGIC:
        raise ValueError("not a partition slice")
    header = json.loads(data[_HEADER.size: _HEADER.size + hlen])
    records: List[LogRecord] = []
    pos = _HEADER.size + hlen
    blocks = 0
    while pos < len(data):
        recs, pos = seg.decode_block(data, pos, header["topic"],
                                     int(header["partition"]))
        records.extend(recs)
        blocks += 1
    if len(records) != int(header["count"]) or blocks != int(header["blocks"]):
        raise ValueError(
            f"truncated partition slice ({len(records)} != {header['count']} "
            "records)")
    return header, records


@dataclass(frozen=True)
class Checkpoint:
    """One consistent (states, watermarks) snapshot of an events topic's fold.

    ``partitions`` records each aggregate's source partition so a
    partition-scoped restore (multi-node cold start: 1/N of the work) can take
    only the snapshots it owns and never write unowned aggregates into the
    local store."""

    seq: int
    topic: str
    created_at: float
    watermarks: Dict[int, int] = field(default_factory=dict)
    states: Dict[str, Optional[bytes]] = field(default_factory=dict)
    partitions: Dict[str, int] = field(default_factory=dict)

    @property
    def num_aggregates(self) -> int:
        return len(self.states)

    def events_covered(self) -> int:
        return sum(self.watermarks.values())

    def partition_of(self, agg_id: str) -> int:
        return self.partitions.get(agg_id, 0)


class CheckpointStore:
    """Durable checkpoint directory with atomic writes and keep-N pruning."""

    def __init__(self, path: str, keep: int = 2, fsync: bool = True) -> None:
        self.path = path
        self.keep = max(int(keep), 1)
        self._fsync = fsync
        os.makedirs(path, exist_ok=True)

    def _file(self, seq: int) -> str:
        return os.path.join(self.path, f"ckpt-{seq:012d}.ck")

    def sequences(self) -> List[int]:
        out = []
        for name in os.listdir(self.path):
            if name.startswith("ckpt-") and name.endswith(".ck"):
                try:
                    out.append(int(name[5:-3]))
                except ValueError:
                    continue
        return sorted(out)

    def write(self, ckpt: Checkpoint) -> str:
        """Atomically publish ``ckpt`` and prune old generations."""
        path = self._file(ckpt.seq)
        tmp = path + ".tmp"
        # states ride the segment block codec: key/value records with
        # tombstone framing for folded-to-None aggregates, grouped into one
        # block run per source partition (the codec stamps a whole block with
        # one partition) so scoped multi-node restores can take only the
        # partitions they own. Key order within a partition keeps a
        # checkpoint's bytes deterministic for its contents.
        by_part: Dict[int, list] = {}
        for k in sorted(ckpt.states):
            by_part.setdefault(ckpt.partition_of(k), []).append(k)
        block_partitions: List[int] = []
        blocks: List[bytes] = []
        chunk = 65536  # bound the per-block buffer for huge stores
        base = 0
        for p in sorted(by_part):
            keys = by_part[p]
            for i in range(0, len(keys), chunk):
                records = [LogRecord(topic=ckpt.topic, key=k,
                                     value=ckpt.states[k], partition=p)
                           for k in keys[i:i + chunk]]
                blocks.append(seg.encode_block(records, base))
                block_partitions.append(p)
                base += len(records)
        header = json.dumps({
            "version": 1, "seq": ckpt.seq, "topic": ckpt.topic,
            "created_at": ckpt.created_at,
            "watermarks": {str(p): off for p, off in ckpt.watermarks.items()},
            "count": len(ckpt.states),
            "block_partitions": block_partitions,
        }).encode()
        with open(tmp, "wb") as f:
            f.write(_HEADER.pack(_MAGIC, len(header)))
            f.write(header)
            for block in blocks:
                f.write(block)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self._fsync:
            _fsync_dir(self.path)
        self.prune()
        return path

    def prune(self) -> None:
        for old in self.sequences()[: -self.keep]:
            try:
                os.unlink(self._file(old))
            except OSError:
                pass

    def load(self, seq: int) -> Checkpoint:
        path = self._file(seq)
        with open(path, "rb") as f:
            data = f.read()
        magic, hlen = _HEADER.unpack_from(data, 0)
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a checkpoint file")
        header = json.loads(data[_HEADER.size: _HEADER.size + hlen])
        states: Dict[str, Optional[bytes]] = {}
        partitions: Dict[str, int] = {}
        block_parts = list(header.get("block_partitions", []))
        pos = _HEADER.size + hlen
        bi = 0
        while pos < len(data):
            p = int(block_parts[bi]) if bi < len(block_parts) else 0
            records, pos = seg.decode_block(data, pos, header["topic"], p)
            for r in records:
                states[r.key] = r.value
                partitions[r.key] = p
            bi += 1
        if len(states) != header["count"] or bi != len(block_parts):
            raise ValueError(f"{path}: truncated checkpoint "
                             f"({len(states)} != {header['count']} states)")
        return Checkpoint(
            seq=int(header["seq"]), topic=header["topic"],
            created_at=float(header["created_at"]),
            watermarks={int(p): int(off)
                        for p, off in header["watermarks"].items()},
            states=states, partitions=partitions)

    def latest(self) -> Optional[Checkpoint]:
        """Newest loadable checkpoint; a torn/corrupt newer file (crash during
        an unsynced write) falls back to its predecessor, never errors out the
        cold start."""
        for s in reversed(self.sequences()):
            try:
                return self.load(s)
            except Exception as exc:  # noqa: BLE001 — fall back, loudly
                logger.warning("checkpoint %d unreadable (%s: %s); trying "
                               "predecessor", s, type(exc).__name__, exc)
        return None


class CheckpointWriter(Controllable):
    """Incremental checkpoint materializer for one events topic.

    Config knobs (docs/compaction.md):

    - ``surge.store.checkpoint.interval-ms`` — write cadence (publisher-style
      timed tick; a tick with nothing newly folded writes nothing).
    - ``surge.store.checkpoint.min-events`` — don't write until at least this
      many events were folded since the last checkpoint.
    - ``surge.store.checkpoint.keep`` — generations retained on disk.
    """

    health_name = "checkpoint-writer"

    def __init__(self, log, events_topic: str, model, store: CheckpointStore,
                 *, serialize_state: Callable[[str, Any], bytes],
                 deserialize_event: Callable[[bytes], Any],
                 deserialize_state: Callable[[bytes], Any] | None = None,
                 partitions: Optional[Sequence[int]] = None,
                 config: Config | None = None, metrics=None,
                 on_signal: Callable[[str, str], None] | None = None) -> None:
        self.log = log
        self.events_topic = events_topic
        self.model = model
        self.store = store
        self.serialize_state = serialize_state
        self.deserialize_event = deserialize_event
        self.deserialize_state = deserialize_state
        self.partitions = (sorted(partitions) if partitions is not None
                           else None)
        self.config = config or default_config()
        self.metrics = metrics
        self.on_signal = on_signal or (lambda name, level: None)
        self._interval_s = self.config.get_seconds(
            "surge.store.checkpoint.interval-ms", 30_000)
        self._min_events = self.config.get_int(
            "surge.store.checkpoint.min-events", 1)
        self._states: Dict[str, Any] = {}
        self._partitions_of: Dict[str, int] = {}
        self._watermarks: Dict[int, int] = {}
        self._seq = 0
        self._last_written_at: Optional[float] = None
        self._events_since_write = 0
        self._resumed = False
        # write_now runs on executor threads from BOTH the background loop and
        # the admin WriteCheckpoint RPC: without mutual exclusion two advances
        # would fold the same tail twice into the shared state map
        self._write_lock = threading.Lock()
        self._task = BackgroundTask(self._loop, "checkpoint-writer")

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> Ack:
        self._task.start()
        return Ack()

    async def stop(self) -> Ack:
        await self._task.stop()
        return Ack()

    @property
    def running(self) -> bool:
        return self._task.running

    # -- materialization ----------------------------------------------------------------

    def _parts(self) -> List[int]:
        return (self.partitions if self.partitions is not None
                else list(range(self.log.num_partitions(self.events_topic))))

    def _resume(self) -> None:
        """Continue from the newest durable checkpoint instead of re-folding
        history. Without a state deserializer the writer starts from scratch —
        correct, just a one-time O(history) first advance."""
        self._resumed = True
        ckpt = self.store.latest()
        if ckpt is None:
            return
        self._seq = ckpt.seq
        self._last_written_at = ckpt.created_at
        if self.deserialize_state is None:
            logger.warning(
                "checkpoint writer for %s: no state deserializer — cannot "
                "resume from seq %d, re-folding from offset 0",
                self.events_topic, ckpt.seq)
            return
        self._watermarks = dict(ckpt.watermarks)
        self._partitions_of = dict(ckpt.partitions)
        for agg_id, raw in ckpt.states.items():
            self._states[agg_id] = (None if raw is None
                                    else self.deserialize_state(raw))

    def advance(self) -> int:
        """Fold every event between the last-consumed watermarks and the
        current end offsets into the state map; returns events folded. The
        watermark for each partition is captured before its scan, so the map
        is always the fold of exactly ``self._watermarks``."""
        if not self._resumed:
            self._resume()
        folded = 0
        initial = getattr(self.model, "initial_state", None)
        handle = getattr(self.model, "handle_event", None)
        from surge_tpu.engine.model import fold_events

        for p in self._parts():
            start = self._watermarks.get(p, 0)
            end = self.log.end_offset(self.events_topic, p)
            if end <= start:
                continue
            for rec in page_keyed_records(self.log, self.events_topic, p,
                                          start=start, upto=end):
                agg_id = rec.key
                self._partitions_of[agg_id] = p
                if agg_id not in self._states:
                    self._states[agg_id] = (initial(agg_id)
                                            if initial is not None else None)
                event = self.deserialize_event(rec.value)
                if handle is not None:
                    self._states[agg_id] = handle(self._states[agg_id], event)
                else:
                    self._states[agg_id] = fold_events(
                        self.model, self._states[agg_id], [event])
                folded += 1
            self._watermarks[p] = end
        self._events_since_write += folded
        return folded

    def build(self) -> Checkpoint:
        from surge_tpu.store.restore import _with_aggregate_id

        states: Dict[str, Optional[bytes]] = {}
        for agg_id, state in self._states.items():
            if state is None:
                states[agg_id] = None
            else:
                states[agg_id] = self.serialize_state(
                    agg_id, _with_aggregate_id(state, agg_id))
        return Checkpoint(seq=self._seq + 1, topic=self.events_topic,
                          created_at=time.time(),
                          watermarks=dict(self._watermarks), states=states,
                          partitions=dict(self._partitions_of))

    def write_now(self) -> Checkpoint:
        """Advance to the current end offsets and publish a checkpoint
        unconditionally (admin RPC / shutdown hook). Blocking — callers on the
        event loop run it in an executor; serialized against the background
        loop's own writes."""
        t0 = time.perf_counter()
        with self._write_lock:
            folded = self.advance()
            ckpt = self.build()
            self.store.write(ckpt)
            self._seq = ckpt.seq
            self._last_written_at = ckpt.created_at
            self._events_since_write = 0
        if self.metrics is not None:
            self.metrics.checkpoint_writes.record()
            self.metrics.checkpoint_events_folded.record(folded)
            self.metrics.checkpoint_timer.record_ms(
                (time.perf_counter() - t0) * 1000.0)
        logger.info("checkpoint %d for %s: %d aggregates, %d events covered "
                    "(%d newly folded)", ckpt.seq, self.events_topic,
                    ckpt.num_aggregates, ckpt.events_covered(), folded)
        return ckpt

    def lag(self) -> int:
        """Events committed past the last checkpoint's watermarks."""
        return sum(
            max(self.log.end_offset(self.events_topic, p)
                - self._watermarks.get(p, 0), 0)
            for p in self._parts()) + self._events_since_write

    # -- loop ---------------------------------------------------------------------------

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self._interval_s)
            try:
                if self.metrics is not None:
                    self.metrics.checkpoint_lag_events.record(self.lag())
                    if self._last_written_at is not None:
                        self.metrics.checkpoint_age.record(
                            time.time() - self._last_written_at)
                if self.lag() >= self._min_events:
                    await loop.run_in_executor(None, self.write_now)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep the cadence alive
                logger.exception("checkpoint write failed; retrying in %.1fs",
                                 self._interval_s)
                try:
                    self.on_signal("surge.store.checkpoint-error", "error")
                except Exception:  # noqa: BLE001
                    logger.exception("on_signal failed")
