"""State-topic indexer task: compacted topic → KV store, with watermarks.

The asyncio re-expression of the embedded Kafka Streams KTable job
(KafkaStreamManagerActor.scala:20-190 + SurgeStateStoreConsumer.scala:57-76): consume the
state topic read_committed, upsert the latest snapshot per aggregate id into the KV
store, and expose

- ``get_aggregate_bytes(id)`` — the aggregate cold-start read path
  (AggregateStateStoreKafkaStreams.scala:126-140),
- ``indexed_watermark(topic, partition)`` — the lag signal the publisher's
  ``is_aggregate_state_current`` gating consumes (KafkaProducerActorImpl.scala:701-708),
- ``wipe-state-on-start`` (common reference.conf:8-12) and bulk-restore priming
  (watermark fast-forward after a TPU rebuild).

On-change listeners fire on every RUNNING transition / assignment change — the
``KafkaStreamsUpdatePartitionsOnStateChangeListener`` analog that keeps the partition
tracker current (SURVEY.md §3.5).
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional, Sequence

from surge_tpu.common import (Ack, BackgroundTask, Controllable,
                              cancel_safe_wait_for, logger, spawn_reaped)
from surge_tpu.config import Config, default_config
from surge_tpu.log.transport import LogRecord
from surge_tpu.store.kv import KeyValueStore, create_store


class StateStoreIndexer(Controllable):
    """Materializes one state topic's assigned partitions into a KV store."""

    def __init__(self, log, state_topic: str,
                 partitions: Optional[Sequence[int]] = None,
                 store: Optional[KeyValueStore] = None,
                 config: Config | None = None,
                 on_signal: Callable[[str, str], None] | None = None) -> None:
        self.log = log
        self.state_topic = state_topic
        self.config = config or default_config()
        self.store = store if store is not None else create_store(
            self.config.get_str("surge.state-store.backend", "memory"))
        self.partitions: List[int] = sorted(
            partitions if partitions is not None else range(log.num_partitions(state_topic)))
        self.on_signal = on_signal or (lambda name, level: None)
        self._watermarks: Dict[int, int] = {p: 0 for p in self.partitions}
        self._max_poll = self.config.get_int("surge.state-store.restore-max-poll-records", 500)
        self._poll_timeout = max(
            self.config.get_seconds("surge.state-store.commit-interval-ms", 3000), 0.001)
        self._tasks: Dict[int, BackgroundTask] = {}
        # partition -> in-flight stop() of its previous loop (set_partitions
        # revoke); a re-grant chains its new loop behind this so two loops never
        # tail one partition concurrently
        self._stopping: Dict[int, asyncio.Task] = {}
        self._chains: set = set()  # stop→restart chains in flight (reaped)
        self._running = False
        self._state_listeners: List[Callable[[str], None]] = []

    # -- lifecycle (Controllable) -------------------------------------------------------

    async def start(self) -> Ack:
        if self.config.get_bool("surge.state-store.wipe-state-on-start"):
            logger.info("wipe-state-on-start: clearing %s store", self.state_topic)
            self.store.clear()
            self._watermarks = {p: 0 for p in self.partitions}
        self._tasks = {
            p: BackgroundTask(self._make_partition_loop(p),
                              f"indexer-{self.state_topic}-{p}")
            for p in self.partitions
        }
        for t in self._tasks.values():
            t.start()
        self._running = True
        self._notify_state("running")
        return Ack()

    async def stop(self) -> Ack:
        self._running = False
        for t in self._tasks.values():
            await t.stop()
        self._tasks = {}
        # drain in-flight revoke stops so shutdown never orphans a pending task
        for t in list(self._stopping.values()):
            try:
                await t
            except Exception:  # noqa: BLE001 — stop is best-effort
                pass
        self._stopping = {}
        self._notify_state("stopped")
        return Ack()

    def set_partitions(self, partitions: Sequence[int]) -> None:
        """Retarget which partitions this indexer tails (rebalance: the Kafka
        Streams task-migration analog, SURVEY.md §3.5). Added partitions start
        tailing from their last-known watermark (0 if never tailed); removed
        partitions stop tailing but their already-indexed keys stay in the store
        — routing ownership means this node is no longer asked for them. A
        partition re-granted while its old loop is still stopping gets its new
        loop chained behind the stop, so one partition never has two tailers."""
        new = sorted(set(partitions))
        if new == self.partitions:
            return
        added = [p for p in new if p not in self._tasks]
        removed = [p for p in self.partitions if p not in new]
        self.partitions = new
        for p in new:
            self._watermarks.setdefault(p, 0)
        if not self._running:
            return
        for p in removed:
            task = self._tasks.pop(p, None)
            if task is not None:
                stopper = asyncio.ensure_future(task.stop())
                self._stopping[p] = stopper
                stopper.add_done_callback(
                    lambda t, p=p: self._stopping.pop(p, None)
                    if self._stopping.get(p) is t else None)
        for p in added:
            self._start_partition_loop(p)

    def _start_partition_loop(self, p: int) -> None:
        pending = self._stopping.get(p)
        if pending is not None and not pending.done():
            async def chain() -> None:
                try:
                    await pending
                except Exception:  # noqa: BLE001
                    pass
                # re-check: assignment may have changed again while waiting
                if self._running and p in self.partitions and p not in self._tasks:
                    t = BackgroundTask(self._make_partition_loop(p),
                                       f"indexer-{self.state_topic}-{p}")
                    self._tasks[p] = t
                    t.start()

            spawn_reaped(self._chains, chain(),
                         f"indexer {self.state_topic}[{p}] restart chain")
            return
        t = BackgroundTask(self._make_partition_loop(p),
                           f"indexer-{self.state_topic}-{p}")
        self._tasks[p] = t
        t.start()

    @property
    def running(self) -> bool:
        return self._running

    def register_state_listener(self, fn: Callable[[str], None]) -> None:
        """Listener(state) on running/stopped transitions (partition-tracker feed)."""
        self._state_listeners.append(fn)

    def _notify_state(self, state: str) -> None:
        for fn in self._state_listeners:
            try:
                fn(state)
            except Exception:  # noqa: BLE001 — listener bugs must not kill the indexer
                logger.exception("state listener failed")

    # -- read path ----------------------------------------------------------------------

    def get_aggregate_bytes(self, aggregate_id: str) -> Optional[bytes]:
        return self.store.get(aggregate_id)

    def indexed_watermark(self, topic: str, partition: int) -> int:
        if topic != self.state_topic:
            return 0
        return self._watermarks.get(partition, 0)

    def total_lag(self) -> int:
        """Sum over assigned partitions of (end offset − indexed watermark)."""
        return self.lag_for(self.partitions)

    def lag_for(self, partitions: Sequence[int]) -> int:
        """Sum of (end offset − indexed watermark) over ``partitions`` (the
        standby-lag gauge input; KafkaProducerActorImpl.scala:701-708 role)."""
        return sum(
            max(self.log.end_offset(self.state_topic, p)
                - self._watermarks.get(p, 0), 0)
            for p in partitions)

    # -- restore priming ----------------------------------------------------------------

    def prime(self, watermarks: Dict[int, int]) -> None:
        """Fast-forward watermarks after a bulk restore filled the store out-of-band
        (the TPU replay writeback path, surge_tpu.store.restore)."""
        for p, off in watermarks.items():
            if p in self._watermarks:
                self._watermarks[p] = max(self._watermarks[p], off)

    # -- indexing loop ------------------------------------------------------------------

    def _make_partition_loop(self, partition: int):
        async def loop() -> None:
            # a transient transport failure (e.g. the whole broker set briefly
            # unreachable mid-failover, after the client's own target cycle is
            # exhausted) must not END this task silently: the partition would
            # stop indexing forever, the publisher's lag gate would never
            # advance, and every aggregate on it would stall with no root
            # cause. Log, signal the health bus, back off — escalating, so a
            # DETERMINISTIC failure (poison record, store bug) throttles its
            # own traceback spam and reads differently from transport blips.
            backoff = 0.25
            while True:
                try:
                    offset = self._watermarks[partition]
                    # end captured BEFORE the read: an empty read then proves
                    # [offset, end) held only compacted-away records — anything
                    # committed after the capture has offset >= end and stays
                    # past the fast-forwarded watermark
                    end = self.log.end_offset(self.state_topic, partition)
                    records = self.log.read(self.state_topic, partition,
                                            offset, max_records=self._max_poll)
                    if records:
                        self._apply(records)
                        self._watermarks[partition] = records[-1].offset + 1
                        backoff = 0.25  # reset only on a FULL success, so a
                        continue        # poison _apply still escalates
                    if end > offset:
                        # compaction hole at the tail of our position: without
                        # this the watermark would stall below end_offset
                        # forever and the publisher's lag gate would never open
                        self._watermarks[partition] = end
                        backoff = 0.25
                        continue
                    await cancel_safe_wait_for(
                        self.log.wait_for_append(self.state_topic, partition,
                                                 offset),
                        timeout=self._poll_timeout)
                    backoff = 0.25
                except asyncio.TimeoutError:
                    backoff = 0.25  # an idle wait is healthy too
                except Exception:  # noqa: BLE001 — keep the tail alive
                    logger.exception(
                        "indexer poll failed on %s[%d]; retrying in %.2fs",
                        self.state_topic, partition, backoff)
                    try:
                        self.on_signal("surge.state-store.poll-error", "error")
                    except Exception:  # noqa: BLE001
                        logger.exception("on_signal failed")
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 30.0)

        return loop

    def _apply(self, records: Sequence[LogRecord]) -> None:
        for r in records:
            if r.key is None:
                continue  # flush/control record (publisher init sentinel)
            if r.value is None:
                self.store.delete(r.key)
            else:
                self.store.put(r.key, r.value)
