"""Pluggable key-value store backing the materialized state.

The plugin seam mirrors ``SurgeKafkaStreamsPersistencePlugin`` (modules/common/src/main/
scala/surge/kafka/streams/SurgeKafkaStreamsPersistencePlugin.scala:12-51 — RocksDB by
default, loadable by name from ``surge.kafka-streams.state-store-plugin``). Backends here:
``memory`` (dict), and ``native`` (the C++ mmap store in ``csrc/``, loaded via ctypes)
selected by ``surge.state-store.backend``.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Protocol, Tuple


class KeyValueStore(Protocol):
    """Byte-oriented KV contract (ReadOnlyKeyValueStore + write side)."""

    def get(self, key: str) -> Optional[bytes]: ...

    def put(self, key: str, value: bytes) -> None: ...

    def delete(self, key: str) -> None: ...

    def all_items(self) -> Iterator[Tuple[str, bytes]]: ...

    def range_items(self, start: str, stop: str) -> Iterator[Tuple[str, bytes]]:
        """Keys in ``[start, stop]`` (inclusive, like ReadOnlyKeyValueStore.range)."""

    def approximate_num_entries(self) -> int: ...

    def clear(self) -> None: ...


class InMemoryKeyValueStore:
    """Dict-backed store (the in-memory persistence plugin analog)."""

    def __init__(self) -> None:
        self._data: Dict[str, bytes] = {}

    def get(self, key: str) -> Optional[bytes]:
        return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def all_items(self) -> Iterator[Tuple[str, bytes]]:
        return iter(sorted(self._data.items()))

    def range_items(self, start: str, stop: str) -> Iterator[Tuple[str, bytes]]:
        return iter((k, v) for k, v in sorted(self._data.items()) if start <= k <= stop)

    def approximate_num_entries(self) -> int:
        return len(self._data)

    def clear(self) -> None:
        self._data.clear()


def create_store(backend: str) -> KeyValueStore:
    """Backend selection by config name (plugin-loader analog,
    SurgeKafkaStreamsPersistencePluginLoader.load:30-51)."""
    if backend == "memory":
        return InMemoryKeyValueStore()
    if backend == "native":
        from surge_tpu.store.native import NativeKeyValueStore, native_available

        if native_available():
            return NativeKeyValueStore()
        return InMemoryKeyValueStore()
    raise ValueError(f"unknown state-store backend {backend!r}")
