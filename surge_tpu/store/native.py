"""ctypes loader for the C++ state store (csrc/). Falls back cleanly when unbuilt.

The native backend replaces the reference's RocksDB JNI dependency
(SurgeKafkaStreamsPersistencePlugin.scala:17-22, CustomRocksDBConfigSetter.scala) with a
first-party C++ hash-indexed KV store. ``create_store("native")`` uses it when the
shared library has been built (``csrc/build.sh``) and silently degrades to the
in-memory store otherwise.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Iterator, Optional, Tuple

#: csrc/build/ — every first-party native library lives here
CSRC_BUILD_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "csrc", "build")


def load_native_library(filename: str, signatures: Dict[str, tuple],
                        extra_dirs: tuple = ()):
    """Shared ctypes loader for the csrc/ libraries: resolves ``filename``
    under ``csrc/build/`` (or ``extra_dirs``), applies the declared
    ``signatures`` ({symbol: (argtypes, restype)}) and returns the CDLL, or
    None when the library is unbuilt — callers degrade to their Python path.

    The signature tables are the loader's ABI contract with csrc/*.cc; the
    tier-1 ABI-drift test (tests/test_abi_drift.py) cross-checks every table
    against the exported C signatures, because a silent mismatch here would
    corrupt data rather than crash."""
    for d in (CSRC_BUILD_DIR, *extra_dirs):
        path = os.path.join(d, filename)
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
            for name, (argtypes, restype) in signatures.items():
                fn = getattr(lib, name)
                fn.argtypes = list(argtypes)
                fn.restype = restype
        except (AttributeError, OSError) as exc:
            # a stale build missing a newly-declared symbol, or a corrupt /
            # wrong-arch .so: DEGRADE (the documented contract), don't crash
            # FileLog/LogServer construction — rebuild via csrc/build.sh
            import logging

            logging.getLogger("surge").warning(
                "native library %s unusable (%s); falling back to the "
                "pure-Python path — rerun csrc/build.sh", path, exc)
            return None
        return lib
    return None


_C = ctypes
#: ABI contract with csrc/store.cc (checked by tests/test_abi_drift.py)
STORE_SIGNATURES: Dict[str, tuple] = {
    "surge_store_new": ((), _C.c_void_p),
    "surge_store_free": ((_C.c_void_p,), None),
    "surge_store_put": ((_C.c_void_p, _C.c_char_p, _C.c_size_t,
                         _C.c_char_p, _C.c_size_t), None),
    "surge_store_get": ((_C.c_void_p, _C.c_char_p, _C.c_size_t,
                         _C.POINTER(_C.c_size_t)), _C.POINTER(_C.c_char)),
    "surge_store_delete": ((_C.c_void_p, _C.c_char_p, _C.c_size_t), None),
    "surge_store_size": ((_C.c_void_p,), _C.c_size_t),
    "surge_store_clear": ((_C.c_void_p,), None),
    "surge_store_iter_new": ((_C.c_void_p,), _C.c_void_p),
    "surge_store_iter_next": ((_C.c_void_p,
                               _C.POINTER(_C.POINTER(_C.c_char)),
                               _C.POINTER(_C.c_size_t),
                               _C.POINTER(_C.POINTER(_C.c_char)),
                               _C.POINTER(_C.c_size_t)), _C.c_int),
    "surge_store_iter_free": ((_C.c_void_p,), None),
}

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    _lib = load_native_library(
        "libsurge_store.so", STORE_SIGNATURES,
        extra_dirs=(os.path.dirname(__file__),))
    return _lib


def native_available() -> bool:
    return _load() is not None


class NativeKeyValueStore:
    """KV store backed by the C++ open-addressing hash store (csrc/store.cc)."""

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native store library not built (run csrc/build.sh)")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.surge_store_new())

    def __del__(self) -> None:  # pragma: no cover
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.surge_store_free(h)

    def put(self, key: str, value: bytes) -> None:
        k = key.encode()
        self._lib.surge_store_put(self._h, k, len(k), value, len(value))

    def get(self, key: str) -> Optional[bytes]:
        k = key.encode()
        n = ctypes.c_size_t(0)
        p = self._lib.surge_store_get(self._h, k, len(k), ctypes.byref(n))
        if not p:
            return None
        return ctypes.string_at(p, n.value)

    def delete(self, key: str) -> None:
        k = key.encode()
        self._lib.surge_store_delete(self._h, k, len(k))

    def approximate_num_entries(self) -> int:
        return int(self._lib.surge_store_size(self._h))

    def clear(self) -> None:
        self._lib.surge_store_clear(self._h)

    def all_items(self) -> Iterator[Tuple[str, bytes]]:
        items = []
        it = ctypes.c_void_p(self._lib.surge_store_iter_new(self._h))
        try:
            kp = ctypes.POINTER(ctypes.c_char)()
            vp = ctypes.POINTER(ctypes.c_char)()
            kn = ctypes.c_size_t(0)
            vn = ctypes.c_size_t(0)
            while self._lib.surge_store_iter_next(
                    it, ctypes.byref(kp), ctypes.byref(kn),
                    ctypes.byref(vp), ctypes.byref(vn)):
                items.append((ctypes.string_at(kp, kn.value).decode(),
                              ctypes.string_at(vp, vn.value)))
        finally:
            self._lib.surge_store_iter_free(it)
        return iter(sorted(items))

    def range_items(self, start: str, stop: str) -> Iterator[Tuple[str, bytes]]:
        return iter((k, v) for k, v in self.all_items() if start <= k <= stop)
