"""ctypes loader for the C++ state store (csrc/). Falls back cleanly when unbuilt.

The native backend replaces the reference's RocksDB JNI dependency
(SurgeKafkaStreamsPersistencePlugin.scala:17-22, CustomRocksDBConfigSetter.scala) with a
first-party C++ hash-indexed KV store. ``create_store("native")`` uses it when the
shared library has been built (``csrc/build.sh``) and silently degrades to the
in-memory store otherwise.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Tuple

_LIB_PATHS = [
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
                 "csrc", "build", "libsurge_store.so"),
    os.path.join(os.path.dirname(__file__), "libsurge_store.so"),
]

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    for path in _LIB_PATHS:
        if os.path.exists(path):
            lib = ctypes.CDLL(path)
            lib.surge_store_new.restype = ctypes.c_void_p
            lib.surge_store_free.argtypes = [ctypes.c_void_p]
            lib.surge_store_put.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t]
            lib.surge_store_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_size_t)]
            lib.surge_store_get.restype = ctypes.POINTER(ctypes.c_char)
            lib.surge_store_delete.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
            lib.surge_store_size.argtypes = [ctypes.c_void_p]
            lib.surge_store_size.restype = ctypes.c_size_t
            lib.surge_store_clear.argtypes = [ctypes.c_void_p]
            lib.surge_store_iter_new.argtypes = [ctypes.c_void_p]
            lib.surge_store_iter_new.restype = ctypes.c_void_p
            lib.surge_store_iter_next.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_size_t)]
            lib.surge_store_iter_next.restype = ctypes.c_int
            lib.surge_store_iter_free.argtypes = [ctypes.c_void_p]
            _lib = lib
            return _lib
    return None


def native_available() -> bool:
    return _load() is not None


class NativeKeyValueStore:
    """KV store backed by the C++ open-addressing hash store (csrc/store.cc)."""

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native store library not built (run csrc/build.sh)")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.surge_store_new())

    def __del__(self) -> None:  # pragma: no cover
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.surge_store_free(h)

    def put(self, key: str, value: bytes) -> None:
        k = key.encode()
        self._lib.surge_store_put(self._h, k, len(k), value, len(value))

    def get(self, key: str) -> Optional[bytes]:
        k = key.encode()
        n = ctypes.c_size_t(0)
        p = self._lib.surge_store_get(self._h, k, len(k), ctypes.byref(n))
        if not p:
            return None
        return ctypes.string_at(p, n.value)

    def delete(self, key: str) -> None:
        k = key.encode()
        self._lib.surge_store_delete(self._h, k, len(k))

    def approximate_num_entries(self) -> int:
        return int(self._lib.surge_store_size(self._h))

    def clear(self) -> None:
        self._lib.surge_store_clear(self._h)

    def all_items(self) -> Iterator[Tuple[str, bytes]]:
        items = []
        it = ctypes.c_void_p(self._lib.surge_store_iter_new(self._h))
        try:
            kp = ctypes.POINTER(ctypes.c_char)()
            vp = ctypes.POINTER(ctypes.c_char)()
            kn = ctypes.c_size_t(0)
            vn = ctypes.c_size_t(0)
            while self._lib.surge_store_iter_next(
                    it, ctypes.byref(kp), ctypes.byref(kn),
                    ctypes.byref(vp), ctypes.byref(vn)):
                items.append((ctypes.string_at(kp, kn.value).decode(),
                              ctypes.string_at(vp, vn.value)))
        finally:
            self._lib.surge_store_iter_free(it)
        return iter(sorted(items))

    def range_items(self, start: str, stop: str) -> Iterator[Tuple[str, bytes]]:
        return iter((k, v) for k, v in self.all_items() if start <= k <= stop)
