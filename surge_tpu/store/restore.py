"""Bulk store restore — the cold-start rebuild path (north-star workload).

Two sources, selected by the engine on cold start:

- :func:`restore_from_state_topic` — scan the compacted state topic's latest snapshot
  per aggregate into the store. This is the reference's only restore path (Kafka Streams
  changelog restore, SURVEY.md §3.3 "bulk replay is Kafka Streams restore").
- :func:`restore_from_events` — rebuild every aggregate's state by folding the events
  topic. **New capability**: routed through the batched TPU replay engine when
  ``surge.replay.backend = tpu`` (ReplayEngine: vmap×scan over event tensors) or the
  scalar fold when ``cpu`` — both must produce byte-identical stores (golden-tested).

Both return ``(partition → next offset)`` watermarks so the indexer can be primed and
resume tail-indexing exactly where the restore left off (the checkpoint/resume contract,
SURVEY.md §5.4 TPU mapping).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence

from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec, fold_events
from surge_tpu.store.kv import KeyValueStore


@dataclass
class RestoreResult:
    num_aggregates: int
    num_events: int
    watermarks: Dict[int, int]  # partition -> next offset (on the scanned topic)
    backend: str


def restore_from_state_topic(log, state_topic: str, store: KeyValueStore,
                             partitions: Optional[Sequence[int]] = None) -> RestoreResult:
    """Latest-snapshot-per-key scan of the compacted state topic into the store."""
    parts = list(partitions if partitions is not None
                 else range(log.num_partitions(state_topic)))
    n = 0
    watermarks: Dict[int, int] = {}
    for p in parts:
        for key, rec in log.latest_by_key(state_topic, p).items():
            store.put(key, rec.value)
            n += 1
        watermarks[p] = log.end_offset(state_topic, p)
    return RestoreResult(num_aggregates=n, num_events=n, watermarks=watermarks,
                         backend="state-topic")


def restore_from_events(
        log, events_topic: str, store: KeyValueStore, *,
        deserialize_event: Callable[[bytes], Any],
        serialize_state: Callable[[str, Any], bytes],
        model=None, replay_spec: Optional[ReplaySpec] = None,
        encode_event: Callable[[Any], Any] | None = None,
        decode_state: Callable[[str, Any], Any] | None = None,
        config: Config | None = None, mesh=None,
        partitions: Optional[Sequence[int]] = None) -> RestoreResult:
    """Fold the whole events topic into per-aggregate states and write them back.

    Backend comes from ``surge.replay.backend``: ``tpu`` batches the fold through
    :class:`surge_tpu.replay.ReplayEngine` (requires ``replay_spec``; ``encode_event``
    maps raw events into tensor-schema form, e.g. Vocab dictionary encoding, and
    ``decode_state`` post-processes each decoded state given its aggregate id);
    ``cpu`` runs the scalar per-aggregate fold (requires ``model``).
    """
    cfg = config or default_config()
    backend = cfg.get_str("surge.replay.backend", "tpu")
    parts = list(partitions if partitions is not None
                 else range(log.num_partitions(events_topic)))

    # group events by aggregate id, preserving per-partition offset order (the log's
    # per-aggregate order guarantee: one partition per aggregate)
    logs: Dict[str, list] = {}
    num_events = 0
    watermarks: Dict[int, int] = {}
    for p in parts:
        for rec in log.read(events_topic, p):
            if rec.key is None or rec.value is None:
                continue
            logs.setdefault(rec.key, []).append(deserialize_event(rec.value))
            num_events += 1
        watermarks[p] = log.end_offset(events_topic, p)

    agg_ids = list(logs)
    if backend == "cpu":
        if model is None:
            raise ValueError("cpu replay backend requires `model`")
        states = [fold_events(model, model.initial_state(a) if hasattr(model, "initial_state") else None,
                              logs[a]) for a in agg_ids]
    elif backend == "tpu":
        if replay_spec is None:
            raise ValueError("tpu replay backend requires `replay_spec`")
        from surge_tpu.codec.tensor import decode_states
        from surge_tpu.replay.engine import ReplayEngine

        engine = ReplayEngine(replay_spec, config=cfg, mesh=mesh)
        result = engine.replay_ragged([logs[a] for a in agg_ids], encode=encode_event)
        states = decode_states(replay_spec.registry.state, result.states)
    else:
        raise ValueError(f"unknown replay backend {backend!r}")

    for agg_id, state in zip(agg_ids, states):
        if state is None:
            continue
        state = _with_aggregate_id(state, agg_id)
        if decode_state is not None and backend == "tpu":
            # decode_state maps tensor-schema records back to domain states (e.g.
            # Vocab-decoded strings); cpu-path states are already domain objects
            state = decode_state(agg_id, state)
        store.put(agg_id, serialize_state(agg_id, state))
    return RestoreResult(num_aggregates=len(agg_ids), num_events=num_events,
                         watermarks=watermarks, backend=backend)


def _chunk_wire(engine, segment_path: str, chunk):
    """Per-chunk wire cache beside the segment: ``<segment>.wires/<key>/``.

    The host-side flat pack is the expensive half of a resident replay on a
    1-core host, and segment chunks are IMMUTABLE once written (extends append
    new chunks, never rewrite), so the packed wire is cached keyed by the
    chunk's aggregate-id set — within one segment that set uniquely identifies
    the chunk. A cached wire whose layout fingerprint no longer matches the
    engine's schema is repacked (ReplayEngine.check_wire refuses it), so
    schema evolution invalidates the cache instead of corrupting states.
    Cold starts after the first mmap straight from disk — the same pack-once
    contract as ResidentWire in the bench."""
    import hashlib
    import json
    import os
    import shutil

    from surge_tpu.codec.wire import WireFormat
    from surge_tpu.replay.engine import ResidentWire

    if chunk.source_ordinal is None:
        return engine.pack_resident(chunk)  # not from a segment reader
    # O(1) key: chunks are immutable once written (extends append, never
    # rewrite), so the chunk's global ordinal within the segment identifies
    # its content; the engine's wire-layout fingerprint is part of the key so
    # schema evolution creates a NEW entry instead of fighting the stale one
    wire_fmt = WireFormat(engine.spec.registry, dict(chunk.derived_cols))
    h = hashlib.sha1()
    h.update(json.dumps(wire_fmt.layout_fingerprint(),
                        sort_keys=True).encode())
    h.update(f"|{chunk.source_ordinal}|{chunk.num_events}".encode())
    root = os.path.join(f"{segment_path}.wires", h.hexdigest()[:20])
    if os.path.isdir(root):
        try:
            wire = ResidentWire.load(root)
            engine.check_wire(wire)
            return wire
        except Exception:
            pass  # corrupt entry: repack below
    wire = engine.pack_resident(chunk)
    # atomic publication: a crash or concurrent writer must never leave a
    # torn entry at the final path (rename is atomic; losing the race to
    # another writer of the SAME keyed entry is harmless). Any failure —
    # including ENOSPC mid-save — removes the tmp dir.
    tmp = f"{root}.tmp-{os.getpid()}"
    try:
        wire.save(tmp)
        os.rename(tmp, root)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
    return wire


def restore_from_segment(
        path: str, store: KeyValueStore, *,
        replay_spec: ReplaySpec,
        serialize_state: Callable[[str, Any], bytes],
        decode_state: Callable[[str, Any], Any] | None = None,
        config: Config | None = None, mesh=None,
        partitions: Optional[Sequence[int]] = None) -> RestoreResult:
    """Rebuild the store from a columnar segment (log/columnar.py) — the scalable
    cold-start path: per-event Python objects never exist; chunks stream through
    :meth:`ReplayEngine.replay_columnar` and only the per-AGGREGATE writeback is
    host-side Python. The segment's snapshot section (state-only aggregates) and
    build-time watermarks make it a complete cold-start image, so no state-topic
    scan follows (the restore-throughput knob this replaces: restore consumer
    max.poll.records, common reference.conf:198-199).

    ``partitions`` restores only chunks/snapshot sections recorded for those
    source partitions (per-assigned-task restore, SURVEY.md §3.3): a multi-node
    cold start reads 1/N of the segment and never writes unowned aggregates.
    """
    from surge_tpu.codec.tensor import decode_states
    from surge_tpu.log.columnar import (
        read_segment,
        read_segment_snapshots,
        segment_info,
    )
    from surge_tpu.replay.engine import ReplayEngine

    import numpy as np

    cfg = config or default_config()
    engine = ReplayEngine(replay_spec, config=cfg, mesh=mesh)
    info = segment_info(path)
    schema = info["schema"]
    extra = schema.get("extra", {})
    part_filter = None if partitions is None else {int(p) for p in partitions}
    # single-device restores fold each chunk through the resident path (one
    # upload + one program + one sync per chunk) — on a high-latency device
    # link the streaming path's per-window host round-trips dominate instead;
    # mesh-sharded restores keep the streaming fold (resident is single-device)
    use_resident = mesh is None and cfg.get_str(
        "surge.replay.segment-backend", "resident") == "resident"
    wire_cache = cfg.get_bool("surge.replay.segment-wire-cache", True)

    # Incremental segments append DELTA chunks whose aggregates CONTINUE earlier
    # chunks' folds: keep each chunk's tensor states + an id index so a later
    # chunk's init_carry gathers the already-folded state (and new aggregates
    # start from the model default). Base-only segments (no extends) skip the
    # retention entirely — the common cold path stays streaming.
    track = info.get("num_extends", 0) > 0
    chunk_states: list = []
    where: Dict[str, tuple] = {}
    restored: set = set()
    num_events = 0
    for chunk in read_segment(path, partitions=part_filter):
        if chunk.aggregate_ids is None:
            raise ValueError(
                f"{path}: segment chunks carry no aggregate ids; rebuild the "
                "segment with build_segment_from_topic to restore through it")
        init = None
        if track:
            hits = [(i, a) for i, a in enumerate(chunk.aggregate_ids)
                    if a in where]
            if hits:
                init = engine.init_carry_np(chunk.num_aggregates)
                for name, col in init.items():
                    for i, a in hits:
                        ci, row = where[a]
                        col[i] = chunk_states[ci][name][row]
        if use_resident:
            wire = (_chunk_wire(engine, path, chunk) if wire_cache
                    else engine.pack_resident(chunk))
            res = engine.replay_resident(engine.upload_resident(wire),
                                         init_carry=init)
        else:
            res = engine.replay_columnar(chunk, init_carry=init)
        if track:
            chunk_states.append({k: np.asarray(v)
                                 for k, v in res.states.items()})
            ci = len(chunk_states) - 1
            for i, agg_id in enumerate(chunk.aggregate_ids):
                where[agg_id] = (ci, i)
        states = decode_states(replay_spec.registry.state, res.states)
        for agg_id, state in zip(chunk.aggregate_ids, states):
            if state is None:
                continue
            state = _with_aggregate_id(state, agg_id)
            if decode_state is not None:
                state = decode_state(agg_id, state)
            store.put(agg_id, serialize_state(agg_id, state))
            restored.add(agg_id)
        num_events += res.num_events
    # snapshot sections apply in file order AFTER chunks: a delta snapshot for
    # an aggregate supersedes its (older) chunk-folded state, latest-wins
    for key, value in read_segment_snapshots(path, partitions=part_filter):
        store.put(key, value)
        restored.add(key)
    num_aggregates = len(restored)

    # indexer priming: the segment covers the state topic up to its build-time
    # state watermarks. Empty when the segment was built without a state topic —
    # the caller must then overlay snapshots and prime itself.
    wm_raw = extra.get("state_watermarks") or {}
    watermarks = {int(p): int(off) for p, off in wm_raw.items()
                  if part_filter is None or int(p) in part_filter}
    return RestoreResult(num_aggregates=num_aggregates, num_events=num_events,
                         watermarks=watermarks, backend="segment")


def _with_aggregate_id(state: Any, aggregate_id: str) -> Any:
    """Re-attach the aggregate id to states reconstructed from tensor columns (string
    fields are excluded from the tensor schema, surge_tpu.codec.schema)."""
    if dataclasses.is_dataclass(state) and any(
            f.name == "aggregate_id" for f in dataclasses.fields(state)):
        current = getattr(state, "aggregate_id", None)
        if not current:
            return dataclasses.replace(state, aggregate_id=aggregate_id)
    return state
