"""Bulk store restore — the cold-start rebuild path (north-star workload).

Two sources, selected by the engine on cold start:

- :func:`restore_from_state_topic` — scan the compacted state topic's latest snapshot
  per aggregate into the store. This is the reference's only restore path (Kafka Streams
  changelog restore, SURVEY.md §3.3 "bulk replay is Kafka Streams restore").
- :func:`restore_from_events` — rebuild every aggregate's state by folding the events
  topic. **New capability**: routed through the batched TPU replay engine when
  ``surge.replay.backend = tpu`` (ReplayEngine: vmap×scan over event tensors) or the
  scalar fold when ``cpu`` — both must produce byte-identical stores (golden-tested).

Both return ``(partition → next offset)`` watermarks so the indexer can be primed and
resume tail-indexing exactly where the restore left off (the checkpoint/resume contract,
SURVEY.md §5.4 TPU mapping).
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence

from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec, fold_events
from surge_tpu.store.kv import KeyValueStore

_log = logging.getLogger(__name__)


@dataclass
class RestoreResult:
    num_aggregates: int
    num_events: int
    watermarks: Dict[int, int]  # partition -> next offset (on the scanned topic)
    backend: str


def restore_from_state_topic(log, state_topic: str, store: KeyValueStore,
                             partitions: Optional[Sequence[int]] = None) -> RestoreResult:
    """Latest-snapshot-per-key scan of the compacted state topic into the store."""
    parts = list(partitions if partitions is not None
                 else range(log.num_partitions(state_topic)))
    n = 0
    watermarks: Dict[int, int] = {}
    for p in parts:
        for key, rec in log.latest_by_key(state_topic, p).items():
            store.put(key, rec.value)
            n += 1
        watermarks[p] = log.end_offset(state_topic, p)
    return RestoreResult(num_aggregates=n, num_events=n, watermarks=watermarks,
                         backend="state-topic")


def restore_from_events(
        log, events_topic: str, store: KeyValueStore, *,
        deserialize_event: Callable[[bytes], Any],
        serialize_state: Callable[[str, Any], bytes],
        model=None, replay_spec: Optional[ReplaySpec] = None,
        encode_event: Callable[[Any], Any] | None = None,
        decode_state: Callable[[str, Any], Any] | None = None,
        config: Config | None = None, mesh=None,
        partitions: Optional[Sequence[int]] = None,
        checkpoint=None,
        deserialize_state: Callable[[bytes], Any] | None = None,
        encode_state: Callable[[str, Any], Any] | None = None) -> RestoreResult:
    """Fold the whole events topic into per-aggregate states and write them back.

    Backend comes from ``surge.replay.backend``: ``tpu`` batches the fold through
    :class:`surge_tpu.replay.ReplayEngine` (requires ``replay_spec``; ``encode_event``
    maps raw events into tensor-schema form, e.g. Vocab dictionary encoding, and
    ``decode_state`` post-processes each decoded state given its aggregate id);
    ``cpu`` runs the scalar per-aggregate fold (requires ``model``).

    ``checkpoint`` (a :class:`surge_tpu.store.checkpoint.Checkpoint` plus
    ``deserialize_state`` to reopen its snapshots) bounds the cold start: only
    events past the checkpoint's per-partition watermarks are read and folded —
    on top of the snapshot states — and untouched aggregates restore their
    checkpointed bytes verbatim. The resulting store is byte-identical to the
    full fold on both backends (golden-tested); ``encode_state`` (mirroring
    ``encode_event``) maps a domain snapshot into tensor-schema form for the
    tpu carry when the two differ.
    """
    cfg = config or default_config()
    backend = cfg.get_str("surge.replay.backend", "tpu")
    parts = list(partitions if partitions is not None
                 else range(log.num_partitions(events_topic)))
    if checkpoint is not None and deserialize_state is None:
        raise ValueError("checkpointed restore requires `deserialize_state`")
    if checkpoint is not None:
        tail = sum(max(log.end_offset(events_topic, p)
                       - checkpoint.watermarks.get(p, 0), 0) for p in parts)
        spill = cfg.get_int("surge.replay.restore-spill-events", 1_000_000)
        if not (0 <= spill < tail):
            return _restore_events_checkpointed(
                log, events_topic, store, parts, checkpoint=checkpoint,
                deserialize_event=deserialize_event,
                serialize_state=serialize_state,
                deserialize_state=deserialize_state, model=model,
                replay_spec=replay_spec, encode_event=encode_event,
                decode_state=decode_state, encode_state=encode_state,
                backend=backend, cfg=cfg, mesh=mesh)
        # a tail large enough to spill gets the bounded-memory full restore —
        # correct, just not checkpoint-accelerated
        _log.warning("checkpoint tail (%d events) exceeds the spill "
                     "threshold; falling back to the full restore", tail)

    # Bounded-memory route (VERDICT r4 missing #4): above the spill threshold
    # the whole-topic dict of per-event Python objects below would OOM — a
    # 100M-event topic is tens of GB of dataclass instances. The tpu backend
    # streams the topic into a THROWAWAY columnar segment (spill files + one
    # chunk of objects at a time) and restores through the mmapped chunks;
    # the cpu backend folds in key-hash-range passes.
    spill_threshold = cfg.get_int("surge.replay.restore-spill-events",
                                  1_000_000)
    total_records = sum(log.end_offset(events_topic, p) for p in parts)
    if 0 <= spill_threshold < total_records:
        if backend == "tpu":
            return _restore_events_via_segment(
                log, events_topic, store, parts,
                deserialize_event=deserialize_event,
                serialize_state=serialize_state, replay_spec=replay_spec,
                encode_event=encode_event, decode_state=decode_state,
                cfg=cfg, mesh=mesh)
        if backend == "cpu":
            return _restore_events_cpu_ranges(
                log, events_topic, store, parts,
                deserialize_event=deserialize_event,
                serialize_state=serialize_state, model=model,
                total_records=total_records, threshold=spill_threshold)

    # group events by aggregate id, preserving per-partition offset order (the
    # log's per-aggregate order guarantee: one partition per aggregate). The
    # watermark is captured BEFORE the scan and clamps it — a record committed
    # mid-restore must never be covered-but-unfolded (the indexer resumes at
    # the watermark and would skip it forever)
    from surge_tpu.log.transport import page_keyed_records

    logs: Dict[str, list] = {}
    num_events = 0
    watermarks: Dict[int, int] = {p: log.end_offset(events_topic, p)
                                  for p in parts}
    for p in parts:
        for rec in page_keyed_records(log, events_topic, p,
                                      upto=watermarks[p]):
            logs.setdefault(rec.key, []).append(deserialize_event(rec.value))
            num_events += 1

    agg_ids = list(logs)
    if backend == "cpu":
        if model is None:
            raise ValueError("cpu replay backend requires `model`")
        states = [fold_events(model, model.initial_state(a) if hasattr(model, "initial_state") else None,
                              logs[a]) for a in agg_ids]
    elif backend == "tpu":
        if replay_spec is None:
            raise ValueError("tpu replay backend requires `replay_spec`")
        from surge_tpu.codec.tensor import decode_states
        from surge_tpu.replay.engine import ReplayEngine

        engine = ReplayEngine(replay_spec, config=cfg, mesh=mesh)
        result = engine.replay_ragged([logs[a] for a in agg_ids], encode=encode_event)
        states = decode_states(replay_spec.registry.state, result.states)
    else:
        raise ValueError(f"unknown replay backend {backend!r}")

    for agg_id, state in zip(agg_ids, states):
        if state is None:
            continue
        state = _with_aggregate_id(state, agg_id)
        if decode_state is not None and backend == "tpu":
            # decode_state maps tensor-schema records back to domain states (e.g.
            # Vocab-decoded strings); cpu-path states are already domain objects
            state = decode_state(agg_id, state)
        store.put(agg_id, serialize_state(agg_id, state))
    return RestoreResult(num_aggregates=len(agg_ids), num_events=num_events,
                         watermarks=watermarks, backend=backend)


def _restore_events_checkpointed(log, events_topic: str, store, parts, *,
                                 checkpoint, deserialize_event,
                                 serialize_state, deserialize_state,
                                 model, replay_spec, encode_event,
                                 decode_state, encode_state,
                                 backend, cfg, mesh) -> RestoreResult:
    """Bounded cold start: checkpoint snapshots + fold of the post-watermark
    tail only. Invariant (golden-tested): the store this produces is
    byte-identical to the full fold from offset 0 on both backends —
    ``fold(init, head + tail) == fold(fold(init, head), tail)`` plus the
    checkpoint writer serializing with the same ``serialize_state``."""
    from surge_tpu.log.transport import page_keyed_records

    watermarks: Dict[int, int] = {p: log.end_offset(events_topic, p)
                                  for p in parts}
    logs: Dict[str, list] = {}
    num_events = 0
    for p in parts:
        for rec in page_keyed_records(
                log, events_topic, p,
                start=checkpoint.watermarks.get(p, 0), upto=watermarks[p]):
            logs.setdefault(rec.key, []).append(deserialize_event(rec.value))
            num_events += 1
    # scoped restore (multi-node: parts ⊂ all): take only the snapshots whose
    # source partition this node owns — unowned aggregates must never enter
    # the local store, matching the full fold's per-partition scan
    part_set = set(int(p) for p in parts)
    owned_states = {a: raw for a, raw in checkpoint.states.items()
                    if checkpoint.partition_of(a) in part_set}

    def snapshot(agg_id):
        """(present, state): a checkpointed None must resume from None, not
        from the model's initial state — only truly-new aggregates start
        fresh."""
        if agg_id not in owned_states:
            return False, None
        raw = owned_states[agg_id]
        return True, (None if raw is None else deserialize_state(raw))

    agg_ids = list(logs)
    if backend == "cpu":
        if model is None:
            raise ValueError("cpu replay backend requires `model`")
        states = []
        for a in agg_ids:
            present, init = snapshot(a)
            if not present and hasattr(model, "initial_state"):
                init = model.initial_state(a)
            states.append(fold_events(model, init, logs[a]))
    elif backend == "tpu":
        if replay_spec is None:
            raise ValueError("tpu replay backend requires `replay_spec`")
        from surge_tpu.codec.tensor import decode_states, encode_states
        from surge_tpu.replay.engine import ReplayEngine

        engine = ReplayEngine(replay_spec, config=cfg, mesh=mesh)
        carry = engine.init_carry_np(max(len(agg_ids), 1))
        for i, a in enumerate(agg_ids):
            present, st = snapshot(a)
            if not present or st is None:
                continue  # init record — the tensor form of the None state
            if encode_state is not None:
                st = encode_state(a, st)
            row = encode_states(replay_spec.registry.state, [st])
            for name in carry:
                carry[name][i] = row[name][0]
        result = engine.replay_ragged([logs[a] for a in agg_ids],
                                      encode=encode_event, init_carry=carry)
        states = decode_states(replay_spec.registry.state, result.states)
    else:
        raise ValueError(f"unknown replay backend {backend!r}")

    for agg_id, state in zip(agg_ids, states):
        if state is None:
            continue
        state = _with_aggregate_id(state, agg_id)
        if decode_state is not None and backend == "tpu":
            state = decode_state(agg_id, state)
        store.put(agg_id, serialize_state(agg_id, state))
    # untouched aggregates restore their checkpointed bytes verbatim (the
    # writer serialized them with this same serialize_state, so bytes match
    # the full fold exactly); folded-to-None snapshots stay unwritten, like
    # the full fold's `state is None` skip
    for agg_id, raw in owned_states.items():
        if agg_id in logs or raw is None:
            continue
        store.put(agg_id, raw)
    num_aggregates = len(set(owned_states) | set(logs))
    return RestoreResult(num_aggregates=num_aggregates, num_events=num_events,
                         watermarks=watermarks, backend=backend)


def _restore_events_via_segment(log, events_topic: str, store, parts, *,
                                deserialize_event, serialize_state,
                                replay_spec, encode_event, decode_state,
                                cfg, mesh) -> RestoreResult:
    """Bounded tpu-backend restore: topic → throwaway columnar segment
    (build_segment_from_topic spills raw bytes per chunk range and encodes one
    chunk at a time) → restore_from_segment (mmapped chunks, per-AGGREGATE
    writeback only). Peak host memory is one chunk's decoded events, set by
    ``surge.replay.restore-chunk-aggregates``."""
    import os
    import shutil
    import tempfile

    from surge_tpu.log.columnar import build_segment_from_topic

    if replay_spec is None:
        raise ValueError("tpu replay backend requires `replay_spec`")
    tmp = tempfile.mkdtemp(prefix="surge-restore-seg-")
    try:
        seg_path = os.path.join(tmp, "restore.scol")
        info = build_segment_from_topic(
            log, events_topic, replay_spec.registry,
            lambda m: deserialize_event(m.value), seg_path,
            partitions=parts, encode_event=encode_event,
            chunk_aggregates=cfg.get_int(
                "surge.replay.restore-chunk-aggregates", 65536))
        res = restore_from_segment(
            seg_path, store, replay_spec=replay_spec,
            serialize_state=serialize_state, decode_state=decode_state,
            # the segment dies with this call: caching its wires is pure waste
            config=cfg.with_overrides(
                {"surge.replay.segment-wire-cache": False}),
            mesh=mesh)
        wm = info["schema"]["extra"]["watermarks"]
        return RestoreResult(
            # distinct keys, like the in-memory route (restore_from_segment's
            # own count excludes None-state aggregates — crossing the spill
            # threshold must not change the reported semantics)
            num_aggregates=len(info["aggregate_order"]),
            num_events=res.num_events,
            watermarks={int(p): int(v) for p, v in wm.items()}, backend="tpu")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _restore_events_cpu_ranges(log, events_topic: str, store, parts, *,
                               deserialize_event, serialize_state, model,
                               total_records: int,
                               threshold: int) -> RestoreResult:
    """Bounded cpu-backend restore: K key-hash-range passes over the topic,
    each holding only ~total/K events as objects (K scans of the log trade IO
    for memory — the scalar fold is the bottleneck anyway). Watermarks are
    captured before the first pass and clamp every pass: an event committed
    mid-restore into an already-finished range must stay PAST the recorded
    watermark so the resuming indexer folds it, never silently lost. K is
    capped so a tiny threshold degrades to more memory per pass, not O(N^2)
    rescans."""
    import zlib

    from surge_tpu.log.transport import page_keyed_records

    if model is None:
        raise ValueError("cpu replay backend requires `model`")
    num_ranges = min(64, max(2, -(-total_records // max(threshold, 1))))
    watermarks = {p: log.end_offset(events_topic, p) for p in parts}
    num_aggregates = 0
    num_events = 0
    for j in range(num_ranges):
        logs: Dict[str, list] = {}
        for p in parts:
            for rec in page_keyed_records(log, events_topic, p,
                                          upto=watermarks[p]):
                if zlib.crc32(rec.key.encode()) % num_ranges != j:
                    continue
                logs.setdefault(rec.key, []).append(
                    deserialize_event(rec.value))
                num_events += 1
        for agg_id, events in logs.items():
            init = (model.initial_state(agg_id)
                    if hasattr(model, "initial_state") else None)
            state = fold_events(model, init, events)
            if state is None:
                continue
            state = _with_aggregate_id(state, agg_id)
            store.put(agg_id, serialize_state(agg_id, state))
        num_aggregates += len(logs)
    return RestoreResult(
        num_aggregates=num_aggregates, num_events=num_events,
        watermarks=watermarks, backend="cpu")


def _chunk_wire(engine, segment_path: str, chunk, build_id: str | None = None):
    """Per-chunk wire cache beside the segment: ``<segment>.wires/<key>/``.

    The host-side flat pack is the expensive half of a resident replay on a
    1-core host, and segment chunks are IMMUTABLE once written (extends append
    new chunks, never rewrite), so the packed wire is cached keyed by
    (segment build id, chunk ordinal, event count, engine wire-layout
    fingerprint). The build id (header ``extra.build_id``, stamped by
    ColumnarSegmentWriter on every fresh segment — which also deletes the
    sidecar cache outright) prevents a REBUILT segment at the same path from
    hitting the previous build's wires when a chunk happens to share an
    ordinal and event count (ADVICE r4). A cached wire whose layout
    fingerprint no longer matches the engine's schema is repacked
    (ReplayEngine.check_wire refuses it), so schema evolution invalidates the
    cache instead of corrupting states. Cold starts after the first mmap
    straight from disk — the same pack-once contract as ResidentWire in the
    bench."""
    import hashlib
    import json
    import logging
    import os
    import shutil
    import time

    from surge_tpu.codec.wire import WireFormat
    from surge_tpu.replay.engine import ResidentWire

    if chunk.source_ordinal is None:
        return engine.pack_resident(chunk)  # not from a segment reader
    # O(1) key: chunks are immutable once written (extends append, never
    # rewrite), so (build id, global chunk ordinal) identifies the content;
    # the engine's wire-layout fingerprint is part of the key so schema
    # evolution creates a NEW entry instead of fighting the stale one
    wire_fmt = WireFormat(engine.spec.registry, dict(chunk.derived_cols))
    h = hashlib.sha1()
    h.update(json.dumps(wire_fmt.layout_fingerprint(),
                        sort_keys=True).encode())
    h.update(f"|{build_id or ''}|{chunk.source_ordinal}|"
             f"{chunk.num_events}".encode())
    cache_root = f"{segment_path}.wires"
    root = os.path.join(cache_root, h.hexdigest()[:20])
    if os.path.isdir(root):
        try:
            wire = ResidentWire.load(root)
            engine.check_wire(wire)
            return wire
        except Exception as exc:  # noqa: BLE001 — fall through to repack
            # never silent: a corrupt/stale entry is expected after a schema
            # change, but masking e.g. a failing disk here would look like a
            # mysteriously slow restore (VERDICT r4 weak #8)
            logging.getLogger(__name__).warning(
                "wire cache entry %s unusable (%s: %s); repacking",
                root, type(exc).__name__, exc)
    wire = engine.pack_resident(chunk)
    # crash hygiene: tmp dirs orphaned by an earlier kill are swept once they
    # are plausibly dead (older than an hour); live writers are younger
    try:
        cutoff = time.time() - 3600
        for entry in os.listdir(cache_root) if os.path.isdir(cache_root) else ():
            if ".tmp-" in entry:
                stale = os.path.join(cache_root, entry)
                if os.path.getmtime(stale) < cutoff:
                    shutil.rmtree(stale, ignore_errors=True)
    except OSError:
        pass
    # atomic publication: a crash or concurrent writer must never leave a
    # torn entry at the final path (rename is atomic; losing the race to
    # another writer of the SAME keyed entry is harmless). ANY failure —
    # including a non-OSError mid-save (serialization bug) — removes the tmp
    # dir; only the benign rename race is swallowed.
    tmp = f"{root}.tmp-{os.getpid()}"
    try:
        wire.save(tmp)
        os.rename(tmp, root)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return wire


def restore_from_segment(
        path: str, store: KeyValueStore, *,
        replay_spec: ReplaySpec,
        serialize_state: Callable[[str, Any], bytes],
        decode_state: Callable[[str, Any], Any] | None = None,
        config: Config | None = None, mesh=None,
        partitions: Optional[Sequence[int]] = None) -> RestoreResult:
    """Rebuild the store from a columnar segment (log/columnar.py) — the scalable
    cold-start path: per-event Python objects never exist; chunks stream through
    :meth:`ReplayEngine.replay_columnar` and only the per-AGGREGATE writeback is
    host-side Python. The segment's snapshot section (state-only aggregates) and
    build-time watermarks make it a complete cold-start image, so no state-topic
    scan follows (the restore-throughput knob this replaces: restore consumer
    max.poll.records, common reference.conf:198-199).

    ``partitions`` restores only chunks/snapshot sections recorded for those
    source partitions (per-assigned-task restore, SURVEY.md §3.3): a multi-node
    cold start reads 1/N of the segment and never writes unowned aggregates.
    """
    from surge_tpu.codec.tensor import decode_states
    from surge_tpu.log.columnar import (
        read_segment,
        read_segment_snapshots,
        segment_info,
    )
    from surge_tpu.replay.engine import ReplayEngine

    import numpy as np

    cfg = config or default_config()
    engine = ReplayEngine(replay_spec, config=cfg, mesh=mesh)
    info = segment_info(path)
    schema = info["schema"]
    extra = schema.get("extra", {})
    part_filter = None if partitions is None else {int(p) for p in partitions}
    # single-device restores fold each chunk through the resident path (one
    # upload + one program + one sync per chunk) — on a high-latency device
    # link the streaming path's per-window host round-trips dominate instead;
    # mesh-sharded restores keep the streaming fold (resident is single-device)
    use_resident = mesh is None and cfg.get_str(
        "surge.replay.segment-backend", "resident") == "resident"
    wire_cache = cfg.get_bool("surge.replay.segment-wire-cache", True)

    # Incremental segments append DELTA chunks whose aggregates CONTINUE earlier
    # chunks' folds: keep each chunk's tensor states + an id index so a later
    # chunk's init_carry gathers the already-folded state (and new aggregates
    # start from the model default). Base-only segments (no extends) skip the
    # retention entirely — the common cold path stays streaming.
    track = info.get("num_extends", 0) > 0
    chunk_states: list = []
    where: Dict[str, tuple] = {}
    restored: set = set()
    num_events = 0
    for chunk in read_segment(path, partitions=part_filter):
        if chunk.aggregate_ids is None:
            raise ValueError(
                f"{path}: segment chunks carry no aggregate ids; rebuild the "
                "segment with build_segment_from_topic to restore through it")
        init = None
        if track:
            hits = [(i, a) for i, a in enumerate(chunk.aggregate_ids)
                    if a in where]
            if hits:
                init = engine.init_carry_np(chunk.num_aggregates)
                for name, col in init.items():
                    for i, a in hits:
                        ci, row = where[a]
                        col[i] = chunk_states[ci][name][row]
        if use_resident:
            wire = (_chunk_wire(engine, path, chunk,
                                build_id=extra.get("build_id")) if wire_cache
                    else engine.pack_resident(chunk))
            resident = engine.upload_resident(wire)
            # each restore chunk folds exactly once — the dense layout's
            # one-time gather would never amortize
            resident.cache["oneshot"] = True
            res = engine.replay_resident(resident, init_carry=init)
        else:
            res = engine.replay_columnar(chunk, init_carry=init)
        if track:
            chunk_states.append({k: np.asarray(v)
                                 for k, v in res.states.items()})
            ci = len(chunk_states) - 1
            for i, agg_id in enumerate(chunk.aggregate_ids):
                where[agg_id] = (ci, i)
        states = decode_states(replay_spec.registry.state, res.states)
        for agg_id, state in zip(chunk.aggregate_ids, states):
            if state is None:
                continue
            state = _with_aggregate_id(state, agg_id)
            if decode_state is not None:
                state = decode_state(agg_id, state)
            store.put(agg_id, serialize_state(agg_id, state))
            restored.add(agg_id)
        num_events += res.num_events
    # snapshot sections apply in file order AFTER chunks: a delta snapshot for
    # an aggregate supersedes its (older) chunk-folded state, latest-wins
    for key, value in read_segment_snapshots(path, partitions=part_filter):
        store.put(key, value)
        restored.add(key)
    num_aggregates = len(restored)

    # indexer priming: the segment covers the state topic up to its build-time
    # state watermarks. Empty when the segment was built without a state topic —
    # the caller must then overlay snapshots and prime itself.
    wm_raw = extra.get("state_watermarks") or {}
    watermarks = {int(p): int(off) for p, off in wm_raw.items()
                  if part_filter is None or int(p) in part_filter}
    return RestoreResult(num_aggregates=num_aggregates, num_events=num_events,
                         watermarks=watermarks, backend="segment")


def _with_aggregate_id(state: Any, aggregate_id: str) -> Any:
    """Re-attach the aggregate id to states reconstructed from tensor columns (string
    fields are excluded from the tensor schema, surge_tpu.codec.schema)."""
    if dataclasses.is_dataclass(state) and any(
            f.name == "aggregate_id" for f in dataclasses.fields(state)):
        current = getattr(state, "aggregate_id", None)
        if not current:
            return dataclasses.replace(state, aggregate_id=aggregate_id)
    return state
