"""Test support for applications built on surge_tpu.

Two halves:

- :mod:`surge_tpu.testing.support` — the mockable-engine pattern
  (:class:`StubAggregateRef` / :class:`StubEngine`), replay golden-check
  helpers, and the random model-driven log generators. Everything that used
  to live in the old single-module ``surge_tpu/testing.py`` re-exports from
  here unchanged.
- :mod:`surge_tpu.testing.faults` — the deterministic, seedable
  fault-injection plane (:class:`FaultPlane`) the log broker, the FileLog
  WAL, and the chaos tooling hook into: drop/delay/duplicate transport
  messages, fail or stall fsync rounds, tear journal writes, crash a broker
  at named crash points. Armable from tests, from config
  (``surge.log.faults.plan``), and at runtime via the broker's ``ArmFaults``
  RPC (``tools/chaos.py``).
"""

from surge_tpu.testing.support import (  # noqa: F401
    StubAggregateRef,
    StubEngine,
    assert_replay_matches_scalar,
    random_bank_log,
    random_cart_log,
    random_counter_log,
)
from surge_tpu.testing.faults import (  # noqa: F401
    FaultPlane,
    FaultRule,
    NAMED_PLANS,
    SimulatedCrash,
)

__all__ = [
    "StubAggregateRef",
    "StubEngine",
    "assert_replay_matches_scalar",
    "random_counter_log",
    "random_cart_log",
    "random_bank_log",
    "FaultPlane",
    "FaultRule",
    "NAMED_PLANS",
    "SimulatedCrash",
]
