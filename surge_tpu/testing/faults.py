"""Deterministic, seedable fault-injection plane for the log substrate.

"Simple Testing Can Prevent Most Critical Failures" (Yuan et al., OSDI '14)
found most production outages live in untested error-handling paths. Before
this module every failure-semantics test hand-rolled its own monkeypatching;
this is the shared plane those paths are exercised through instead — the
broker (:mod:`surge_tpu.log.server`), the FileLog WAL
(:mod:`surge_tpu.log.file`) and the chaos tooling (``tools/chaos.py``,
``SURGE_BENCH_FAILOVER=1``) all consult one :class:`FaultPlane`.

**Sites.** Instrumented code names the point it is passing through; rules
match sites by ``fnmatch`` pattern:

- ``rpc.<Method>`` — an inbound broker RPC (``rpc.Transact``,
  ``rpc.Replicate``, ``rpc.*``): actions ``drop`` (answer UNAVAILABLE — the
  message never arrives), ``delay``/``reorder`` (hold the handler; reorder
  draws a random hold in ``[0, delay_ms]`` per message, which permutes
  concurrent pipelined seqs), ``dup`` (run the handler twice — exercises
  idempotent ingest / txn dedup), ``error`` (answer UNAVAILABLE with the
  rule's message).
- ``ship.<target>`` — a leader→follower replication ship: ``drop``/``error``
  fail the ship (the follower never sees it — drives ISR eviction), ``delay``
  stalls it.
- ``fsync.journal`` — a FileLog group-sync round: ``error`` fails the round
  (every covered commit sees the failure), ``stall``/``delay`` holds it.
- ``journal.write`` — tear the journal line: the rule's ``fraction`` of the
  line's bytes are written, then :class:`SimulatedCrash` raises (recovery
  must discard the torn tail).
- ``crash.<point>`` — named crash points (``crash.transact.post-apply``,
  ``crash.repl.pre-ship``, the handoff's ``crash.handoff.pre-promote`` /
  ``crash.handoff.post-promote`` …): the broker hard-stops (socket closes,
  in-flight calls answer UNAVAILABLE) exactly there. Cluster-scale RPC sites
  ride the same ``rpc.*`` pattern (``rpc.VoteLeader`` drops starve a quorum;
  ``rpc.InstallSlice`` drops stall a handoff's bulk phase).

**Determinism.** One seeded :class:`random.Random` drives every probability
draw and reorder hold, in call order, under a lock — the same seed against
the same workload schedule fires the same faults. ``times`` bounds how often
a rule fires; ``after`` skips its first N matches (fire on the Nth
crossing, not the first).

Arm it three ways: construct and pass (``FileLog(..., faults=plane)``,
``LogServer(..., faults=plane)``); from config
(``surge.log.faults.plan`` — a named plan or a JSON rule list, with
``surge.log.faults.seed``); or at runtime via the broker's ``ArmFaults`` RPC
(`tools/chaos.py` is the operator CLI for it).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from random import Random
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["FaultPlane", "FaultRule", "SimulatedCrash", "NAMED_PLANS"]


class SimulatedCrash(Exception):
    """Raised at an armed crash point / torn write: the component must stop
    exactly here, as a real power cut would."""


@dataclass
class FaultRule:
    """One armed fault. ``site`` is an fnmatch pattern against the site names
    above; ``action`` one of drop | delay | reorder | dup | error | stall |
    torn | crash; ``p`` the per-crossing fire probability; ``times`` caps
    total fires (None = unlimited); ``after`` skips the first N matching
    crossings; ``delay_ms`` parameterizes delay/reorder/stall; ``fraction``
    how much of a torn write survives; ``error`` the injected message."""

    site: str
    action: str  # drop|delay|reorder|dup|error|stall|torn|crash|corrupt
    p: float = 1.0
    times: Optional[int] = 1
    after: int = 0
    delay_ms: float = 50.0
    fraction: float = 0.5
    error: str = "fault injected"
    fired: int = field(default=0, compare=False)
    seen: int = field(default=0, compare=False)

    def as_dict(self) -> dict:
        return {"site": self.site, "action": self.action, "p": self.p,
                "times": self.times, "after": self.after,
                "delay_ms": self.delay_ms, "fraction": self.fraction,
                "error": self.error, "fired": self.fired, "seen": self.seen}

    @staticmethod
    def from_dict(obj: dict) -> "FaultRule":
        known = {"site", "action", "p", "times", "after", "delay_ms",
                 "fraction", "error"}
        return FaultRule(**{k: v for k, v in obj.items() if k in known})


#: operator-nameable fault plans (tools/chaos.py arms them by name). Each is a
#: rule-list factory so repeated arms get fresh fire counters.
NAMED_PLANS: Dict[str, Callable[[], List[FaultRule]]] = {
    # kill the leader right after a commit applied locally but before it
    # enqueues for replication — the canonical lost-unreplicated-tail crash
    "leader-crash-mid-commit": lambda: [
        FaultRule(site="crash.transact.post-apply", action="crash")],
    # kill the leader after the batch is queued for replication but before
    # the client is acked (retry + dedup territory)
    "leader-crash-pre-ack": lambda: [
        FaultRule(site="crash.transact.post-enqueue", action="crash")],
    # every ship to every follower fails: drives ISR eviction, then commits
    # proceed at min-insync
    "follower-blackhole": lambda: [
        FaultRule(site="ship.*", action="drop", times=None)],
    # flaky network: 20% of ships fail, 20% of RPCs take an extra 0-40ms
    "flaky-network": lambda: [
        FaultRule(site="ship.*", action="drop", p=0.2, times=None),
        FaultRule(site="rpc.Transact", action="reorder", p=0.2, times=None,
                  delay_ms=40.0)],
    # one journal fsync round fails, later rounds heal (the transient-disk
    # hiccup the broker's retry ladder must absorb)
    "fsync-hiccup": lambda: [
        FaultRule(site="fsync.journal", action="error", times=1)],
    # tear the next journal write mid-line and crash
    "torn-journal": lambda: [
        FaultRule(site="journal.write", action="torn", fraction=0.5)],
    # cluster-scale: drop every VoteLeader RPC this broker receives — a
    # candidate that cannot reach this voter must fail its majority and
    # stand down instead of promoting on its own liveness view
    "vote-blackhole": lambda: [
        FaultRule(site="rpc.VoteLeader", action="drop", times=None)],
    # kill the old leader mid-handoff, AFTER the journal tail shipped but
    # BEFORE the destination promoted: the handoff must fail cleanly (no
    # second leader minted) and the normal kill-failover path takes over
    "handoff-crash-pre-promote": lambda: [
        FaultRule(site="crash.handoff.pre-promote", action="crash")],
    # silent state rot: flip one bit in one resident slab row at the end of
    # the next refresh round (post-fold, post-ack — the log stays correct,
    # the device slab lies). Only the consistency auditor's shadow replay
    # can see this; the corruption-to-page e2e arms it and expects a
    # state-divergence page within 3 audit cycles.
    "corrupt.slab-row": lambda: [
        FaultRule(site="corrupt.slab-row", action="corrupt")],
    # silent replica rot: flip one bit in one record's payload as the NEXT
    # replication ship is ingested on this (follower) broker — leader and
    # follower logs diverge below the hwm with no error anywhere. Only the
    # cross-replica digest compare can see this.
    "corrupt.segment-payload": lambda: [
        FaultRule(site="corrupt.segment-payload", action="corrupt")],
}


class FaultPlane:
    """The armed rule set + the deterministic decision engine."""

    def __init__(self, rules: Optional[Sequence[FaultRule]] = None,
                 seed: int = 0, metrics=None,
                 clock: Callable[[float], None] = time.sleep) -> None:
        self._lock = threading.Lock()
        self._rng = Random(seed)
        self.seed = seed
        self.rules: List[FaultRule] = list(rules or [])
        self.metrics = metrics  # EngineMetrics quiver (optional)
        self._sleep = clock
        #: crash hook installed by the component hosting the plane (the
        #: broker's hard-stop); called once, before SimulatedCrash raises
        self.on_crash: Optional[Callable[[str], None]] = None
        #: optional FlightRecorder (surge_tpu.observability): every fired
        #: rule joins the host's black-box ring, so a post-incident timeline
        #: shows which injected fault preceded which transition
        self.flight = None
        self.injected = 0
        self.crashed: Optional[str] = None  # first crash point that fired

    # -- arming ---------------------------------------------------------------------------

    @staticmethod
    def from_spec(spec: str, seed: int = 0, metrics=None) -> "FaultPlane":
        """Build a plane from a named plan or a JSON rule list / object
        (``{"seed": ..., "rules": [...]}`` or bare ``[...]``)."""
        plan = NAMED_PLANS.get(spec.strip())
        if plan is not None:
            return FaultPlane(plan(), seed=seed, metrics=metrics)
        obj = json.loads(spec)
        if isinstance(obj, dict):
            seed = int(obj.get("seed", seed))
            rules = [FaultRule.from_dict(r) for r in obj.get("rules", [])]
        else:
            rules = [FaultRule.from_dict(r) for r in obj]
        return FaultPlane(rules, seed=seed, metrics=metrics)

    @staticmethod
    def from_config(config) -> Optional["FaultPlane"]:
        """The config arming path (``surge.log.faults.plan``); None when no
        plan is configured — the hot paths then skip every hook."""
        spec = config.get_str("surge.log.faults.plan", "") if config else ""
        if not spec:
            return None
        return FaultPlane.from_spec(spec,
                                    seed=config.get_int(
                                        "surge.log.faults.seed", 0))

    def arm(self, rules: Sequence[FaultRule], seed: Optional[int] = None) -> None:
        """Replace the armed rule set (the ArmFaults RPC path)."""
        with self._lock:
            if seed is not None:
                self._rng = Random(seed)
                self.seed = seed
            self.rules = list(rules)
            self.crashed = None
            self._record_armed()

    def disarm(self) -> None:
        with self._lock:
            self.rules = []
            self._record_armed()

    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed, "injected": self.injected,
                    "crashed": self.crashed,
                    "rules": [r.as_dict() for r in self.rules]}

    def _record_armed(self) -> None:
        if self.metrics is not None:
            self.metrics.faults_armed.record(len(self.rules))

    # -- decision engine ------------------------------------------------------------------

    def _match(self, site: str) -> Optional[FaultRule]:
        """First matching armed rule that elects to fire (seeded draw, seen /
        after / times bookkeeping). Caller holds no locks."""
        with self._lock:
            for rule in self.rules:
                if not fnmatchcase(site, rule.site):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.fired += 1
                self.injected += 1
                if self.metrics is not None:
                    self.metrics.faults_injected.record()
                if self.flight is not None:
                    self.flight.record("fault.fire", site=site,
                                       action=rule.action)
                return rule
        return None

    def _hold_s(self, rule: FaultRule) -> float:
        if rule.action == "reorder":
            with self._lock:
                return self._rng.random() * rule.delay_ms / 1000.0
        return rule.delay_ms / 1000.0

    # -- hook surface (what instrumented code calls) --------------------------------------

    def on_rpc(self, method: str) -> Optional[FaultRule]:
        """Inbound-RPC site. Returns the fired rule for the caller to apply
        (the broker wrapper owns drop/dup semantics); delay/reorder/stall are
        applied HERE so every caller gets them uniformly."""
        rule = self._match(f"rpc.{method}")
        if rule is not None and rule.action in ("delay", "reorder", "stall"):
            self._sleep(self._hold_s(rule))
        return rule

    def on_ship(self, target: str) -> Optional[str]:
        """Leader→follower ship site: an error string fails the ship (as a
        transport error would); None lets it proceed (after any delay)."""
        rule = self._match(f"ship.{target}")
        if rule is None:
            return None
        if rule.action in ("delay", "reorder", "stall"):
            self._sleep(self._hold_s(rule))
            return None
        return f"fault injected ({rule.action}): {rule.error}"

    def on_fsync(self, which: str) -> None:
        """fsync-round site: raises to fail the round, sleeps to stall it."""
        rule = self._match(f"fsync.{which}")
        if rule is None:
            return
        if rule.action in ("stall", "delay", "reorder"):
            self._sleep(self._hold_s(rule))
            return
        raise OSError(f"fault injected: fsync {which} failed ({rule.error})")

    def torn(self, site: str, data: bytes) -> Optional[bytes]:
        """Torn-write site: the surviving prefix to write before crashing, or
        None to write normally."""
        rule = self._match(site)
        if rule is None or rule.action != "torn":
            return None
        keep = max(1, int(len(data) * rule.fraction))
        return data[:min(keep, len(data) - 1)]

    def raise_point(self, site: str) -> None:
        """Exception-injection site (action "error"): raises RuntimeError at
        a named internal point — e.g. ``raise.repl.iteration`` poisons the
        replication worker's head item deterministically."""
        rule = self._match(f"raise.{site}")
        if rule is not None and rule.action == "error":
            raise RuntimeError(f"fault injected at {site}: {rule.error}")

    def point(self, site: str) -> None:
        """Generic in-process site matched on the BARE site name (no
        prefix): delay/stall rules hold the caller, error rules raise.
        The resident plane's refresh executor passes through
        ``resident.refresh.dispatch`` — the device-observatory stall
        anatomy e2e arms a delay here and expects the round's
        device-dispatch leg to dominate its kept tail trace."""
        rule = self._match(site)
        if rule is None:
            return
        if rule.action in ("delay", "reorder", "stall"):
            self._sleep(self._hold_s(rule))
        elif rule.action == "error":
            raise RuntimeError(f"fault injected at {site}: {rule.error}")

    def corrupt_point(self, site: str) -> bool:
        """Corruption site matched on the bare name: True when an armed
        ``corrupt`` rule fires and the caller must rot its own state (the
        resident plane's ``corrupt.slab-row`` flips a bit in one live slab
        row). The caller owns the mutation — the plane only decides."""
        rule = self._match(site)
        return rule is not None and rule.action == "corrupt"

    def corrupt_records(self, site: str, records):
        """Record-stream corruption site: when an armed ``corrupt`` rule
        fires, returns a copy of ``records`` with one bit flipped in one
        record's value (the replication-ingest ``corrupt.segment-payload``
        site — the follower durably applies bytes the leader never sent).
        Otherwise returns ``records`` unchanged."""
        rule = self._match(site)
        if rule is None or rule.action != "corrupt" or not records:
            return records
        import dataclasses

        with self._lock:
            i = self._rng.randrange(len(records))
        victim = records[i]
        value = victim.value or b""
        if not value:
            flipped = b"\x01"
        else:
            j = len(value) // 2
            flipped = value[:j] + bytes([value[j] ^ 0x01]) + value[j + 1:]
        out = list(records)
        out[i] = dataclasses.replace(victim, value=flipped)
        return out

    def crash_point(self, name: str) -> None:
        """Named crash point: fires the host's hard-stop then raises."""
        rule = self._match(f"crash.{name}")
        if rule is None or rule.action != "crash":
            return
        with self._lock:
            if self.crashed is None:
                self.crashed = name
        hook = self.on_crash
        if hook is not None:
            try:
                hook(name)
            except Exception:  # noqa: BLE001 — the crash must still happen
                pass
        raise SimulatedCrash(f"crash point {name!r} fired")
