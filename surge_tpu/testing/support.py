"""Test support for applications built on surge_tpu.

The reference documents a "mockable engine" pattern for user tests: mock
``SurgeCommand`` / ``AggregateRef`` so application services can be exercised
without a broker (surge-docs testing.md + the Java ``TestEngine`` sample,
surge-docs/src/test/java/javadocs/commandapp/Test.java — SURVEY.md §4 item 8).
This module is that pattern as a first-class API:

- :class:`StubAggregateRef` — an in-memory AggregateRef double. By default it
  runs YOUR model's real ``process_command`` / ``handle_event`` against a
  per-aggregate in-memory state, so service-layer tests exercise real domain
  logic with zero infrastructure; canned replies and injected failures layer
  on top for the unhappy paths.
- :class:`StubEngine` — ``aggregate_for``-compatible factory of those stubs
  with a shared state map and a command journal for assertions.

For integration-level tests, prefer a REAL engine over ``InMemoryLog`` (the
EmbeddedKafka equivalent) — see docs/testing.md; these stubs are for the layer
above, where starting an engine per test is noise.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from surge_tpu.engine.entity import (
    CommandFailure,
    CommandRejected,
    CommandSuccess,
)
from surge_tpu.engine.model import fold_events

__all__ = ["StubAggregateRef", "StubEngine", "ZipfKeys",
           "assert_replay_matches_scalar", "random_counter_log",
           "random_cart_log", "random_bank_log", "random_saga_log"]


class ZipfKeys:
    """Seedable Zipf-skewed key sampler (production-shaped workloads,
    ROADMAP 5(a)): key rank ``r`` (1-based) is drawn with probability
    ``r**-s / H``, so a handful of hot keys dominate while the tail stays
    long — the shape the saga soak, the autobalancer, and the workload
    generator all need.

    ::

        keys = ZipfKeys(rng, n=1_000, s=1.1, prefix="acct-")
        keys.draw()   # -> "acct-0" ~7% of the time at n=1000, s=1.1

    The cumulative table is precomputed once (O(n)); ``draw`` is a binary
    search (O(log n)).  ``rank()`` returns the raw 0-based rank for callers
    composing their own key space.
    """

    def __init__(self, rng, n: int, s: float = 1.1,
                 prefix: str = "key-") -> None:
        if n <= 0:
            raise ValueError("n must be positive")
        self._rng = rng
        self.n = n
        self.s = s
        self.prefix = prefix
        acc, cum = 0.0, []
        for rank in range(1, n + 1):
            acc += rank ** -s
            cum.append(acc)
        self._cum = cum
        self._total = acc

    def rank(self) -> int:
        """0-based rank: 0 is the hottest key."""
        import bisect

        return bisect.bisect_left(self._cum, self._rng.random() * self._total)

    def draw(self) -> str:
        return f"{self.prefix}{self.rank()}"

    def pmf(self, rank0: int) -> float:
        """The exact probability of 0-based ``rank0`` (distribution tests)."""
        return (rank0 + 1) ** -self.s / self._total


# --------------------------------------------------------------------------------------
# random-but-semantically-valid event logs for the fixture families — shared by
# the mixed-replay golden test and the on-chip verification sweep; also a
# worked example of driving a model's command path to produce test logs
# (tests/test_replay_golden.py keeps its own batch-form generators with
# different length distributions)
# --------------------------------------------------------------------------------------

def random_counter_log(rng, agg: str) -> list:
    """A counter-family event log via the REAL command path (inc/dec/noop)."""
    from surge_tpu.models import counter

    model = counter.CounterModel()
    state, log = None, []
    for _ in range(rng.randrange(0, 25)):
        r = rng.random()
        if r < 0.6:
            cmd = counter.Increment(agg)
        elif r < 0.9:
            cmd = counter.Decrement(agg)
        else:
            cmd = counter.CreateNoOpEvent(agg)
        for e in model.process_command(state, cmd):
            state = model.handle_event(state, e)
            log.append(e)
    return log


def random_cart_log(rng, agg: str) -> list:
    """A shopping-cart log: add/remove/checkout until checked out."""
    from surge_tpu.models import shopping_cart

    model = shopping_cart.CartModel()
    state, log = None, []
    for _ in range(rng.randrange(0, 20)):
        if state is not None and state.checked_out:
            break
        try:
            r = rng.random()
            if r < 0.6:
                cmd = shopping_cart.AddItem(agg, rng.randrange(1, 50),
                                            rng.randrange(1, 4),
                                            rng.randrange(100, 900))
            elif r < 0.9:
                cmd = shopping_cart.RemoveItem(agg, rng.randrange(1, 50),
                                               rng.randrange(1, 3),
                                               rng.randrange(100, 900))
            else:
                cmd = shopping_cart.Checkout(agg)
            events = model.process_command(state, cmd)
        except Exception:  # noqa: BLE001 — rejected command, try another
            continue
        for e in events:
            state = model.handle_event(state, e)
            log.append(e)
    return log


def random_bank_log(rng, agg: str) -> list:
    """A bank-account log of RAW domain events (encode with
    ``bank_account.encode_event(vocab, e)`` before replay); ~20% orphan
    updates exercise the created-gate."""
    from surge_tpu.models import bank_account

    log = []
    if rng.random() < 0.8:
        log.append(bank_account.BankAccountCreated(agg, f"owner{agg}",
                                                   f"sec{agg}", 100.0))
        bal = 100.0
        for _ in range(rng.randrange(0, 12)):
            bal += rng.randrange(1, 40) * 0.25
            log.append(bank_account.BankAccountUpdated(agg, bal))
    else:
        log.append(bank_account.BankAccountUpdated(agg, 42.0))  # orphan
    return log


def random_saga_log(rng, agg: str) -> list:
    """A saga-family event log via the REAL command path: started, then a
    random walk of step commits / a failure flipping to compensation /
    compensations in reverse, sometimes ending in the dead letter —
    exercising every status transition the replay handlers fold."""
    from surge_tpu.saga import model as saga

    m = saga.SagaModel()
    state, log = None, []

    def run(cmd):
        nonlocal state
        try:
            events = m.process_command(state, cmd)
        except Exception:  # noqa: BLE001 — rejected command, caller moves on
            return False
        for e in events:
            state = m.handle_event(state, e)
            log.append(e)
        return True

    if rng.random() < 0.9:
        num_steps = rng.randrange(1, 7)
        run(saga.StartSaga(agg, def_id=rng.randrange(1, 4),
                           num_steps=num_steps, c0=float(rng.randrange(100)),
                           c1=float(rng.randrange(2))))
        while state is not None and state.status == saga.RUNNING:
            if rng.random() < 0.75:
                run(saga.RecordStepCommitted(agg, state.step))
            else:
                run(saga.RecordStepFailed(agg, state.step,
                                          rng.randrange(1, 5)))
                break
            if rng.random() < 0.15:
                break  # leave some sagas in flight mid-run
        while state is not None and state.status == saga.COMPENSATING:
            pending = state.committed & ~state.compensated
            if rng.random() < 0.1:
                run(saga.RecordDeadLetter(agg, pending.bit_length() - 1))
                break
            run(saga.RecordStepCompensated(agg, pending.bit_length() - 1))
            if rng.random() < 0.1:
                break  # mid-compensation in-flight rows too
    return log


def assert_replay_matches_scalar(model, replay_spec, logs,
                                 fields: Optional[Sequence[str]] = None,
                                 encode: Callable[[Any], Any] | None = None,
                                 config=None) -> None:
    """The golden-check every new model family should ship (the framework's
    own test pattern, docs/testing.md §4): batched TPU replay of ``logs``
    must equal the scalar ``handle_event`` fold, field by field.

    ``encode`` maps raw events into tensor-schema form before replay (the
    ``replay_ragged`` hook — e.g. bank_account's Vocab dictionary encoding);
    the scalar fold always runs on the RAW events. ``fields`` selects which
    state columns to compare; by default every column of the replay spec's
    state schema whose name is an attribute of the scalar states. An empty
    log's baseline is the spec's initial record, and float columns compare
    with a float32-appropriate relative tolerance. Raises ``AssertionError``
    naming the first diverging (aggregate, field) — or, if nothing at all
    was comparable (all logs empty with no field overlap), the vacuous run
    itself."""
    import math

    import numpy as np

    from surge_tpu.replay import ReplayEngine

    logs = [list(log) for log in logs]
    truth = [fold_events(model, None, log) for log in logs]
    res = ReplayEngine(replay_spec, config=config).replay_ragged(
        logs, encode=encode)
    init = replay_spec.init_state_tree()
    if fields is None:
        fields = [f.name for f in replay_spec.registry.state.fields
                  if any(hasattr(s, f.name) for s in truth if s is not None)]
        if not fields:
            # nothing to compare would pass vacuously: fall back to checking
            # every schema column against the initial record
            fields = [f.name for f in replay_spec.registry.state.fields]
    compared = 0
    for i, scalar in enumerate(truth):
        for name in fields:
            if scalar is not None and not hasattr(scalar, name):
                continue
            want = (getattr(scalar, name) if scalar is not None
                    else np.asarray(init[name]).item())
            got = np.asarray(res.states[name][i]).item()
            compared += 1
            if isinstance(want, bool):
                got = bool(got)
            ok = (math.isclose(got, want, rel_tol=1e-5, abs_tol=1e-6)
                  if isinstance(want, float) else got == want)
            if not ok:
                raise AssertionError(
                    f"replay diverges from the scalar fold at aggregate {i} "
                    f"field {name!r}: replay={got!r} scalar={want!r}")
    if not compared:
        raise AssertionError(
            "assert_replay_matches_scalar compared nothing (no logs, or no "
            "state column matches any scalar-state attribute) — pass "
            "`fields` explicitly")


class StubAggregateRef:
    """In-memory double of :class:`surge_tpu.engine.ref.AggregateRef`.

    With a ``model``, commands run the real domain logic::

        ref = StubAggregateRef("a-1", model=counter.CounterModel())
        result = await ref.send_command(counter.Increment("a-1"))
        assert isinstance(result, CommandSuccess) and result.state.count == 1

    Canned behavior for unhappy paths:

    - ``ref.reply_with(result)`` — queue an exact reply for the next
      ``send_command`` (e.g. ``CommandFailure(TimeoutError())`` to test your
      service's retry path);
    - ``ref.fail_with(exc)`` — shorthand for ``reply_with(CommandFailure(exc))``.

    Every command/events batch is recorded on ``.commands`` / ``.applied`` for
    assertions, mirroring what a TestProbe would capture.
    """

    def __init__(self, aggregate_id: str, model: Any = None,
                 state: Any = None,
                 states: Optional[Dict[str, Any]] = None,
                 journal: Optional[List[Any]] = None) -> None:
        self.aggregate_id = aggregate_id
        self.model = model
        #: shared map when built via StubEngine; private map otherwise
        self._states: Dict[str, Any] = states if states is not None else {}
        #: shared cross-aggregate command journal (StubEngine.commands)
        self._journal = journal
        if state is not None:
            self._states[aggregate_id] = state
        elif aggregate_id not in self._states and model is not None:
            init = getattr(model, "initial_state", None)
            self._states[aggregate_id] = init(aggregate_id) if init else None
        self.commands: List[Any] = []
        self.request_ids: List[Optional[str]] = []
        self.applied: List[Sequence[Any]] = []
        self._canned: List[Any] = []

    # -- canned behavior ------------------------------------------------------------

    def reply_with(self, result: Any) -> "StubAggregateRef":
        """Queue an exact reply consumed by the NEXT call on this ref —
        ``send_command``, ``apply_events``, or ``get_state`` share one queue
        (a ``CommandFailure`` popped by ``get_state`` raises its error, like
        the real ref)."""
        self._canned.append(result)
        return self

    def fail_with(self, exc: Exception) -> "StubAggregateRef":
        return self.reply_with(CommandFailure(exc))

    # -- state accessors ------------------------------------------------------------

    @property
    def state(self) -> Any:
        return self._states.get(self.aggregate_id)

    @state.setter
    def state(self, value: Any) -> None:
        self._states[self.aggregate_id] = value

    # -- AggregateRef surface ---------------------------------------------------------

    async def send_command(self, command: Any, *,
                           request_id: Optional[str] = None):
        # request_id is accepted for signature parity with the real ref (the
        # saga manager passes its deterministic rids); the stub has no
        # publisher dedup window, so it is recorded and otherwise ignored
        self.commands.append(command)
        self.request_ids.append(request_id)
        if self._journal is not None:
            self._journal.append(command)
        if self._canned:
            return self._canned.pop(0)
        if self.model is None:
            return CommandFailure(RuntimeError(
                f"StubAggregateRef({self.aggregate_id!r}) has no model and no "
                "canned reply — pass model= or call reply_with()"))
        # mirror the REAL entity's semantics exactly (engine/entity.py
        # _process_command): RejectedCommand -> CommandRejected, any other
        # user-code exception -> CommandFailure, awaitable results awaited
        # (async models), and the same fold (incl. batch handle_events).
        import inspect

        from surge_tpu.engine.model import RejectedCommand

        try:
            result = self.model.process_command(self.state, command)
            if inspect.isawaitable(result):
                result = await result
            events = list(result)
        except RejectedCommand as rej:
            return CommandRejected(rej)
        except Exception as exc:  # noqa: BLE001 — the failure path under test
            return CommandFailure(exc)
        return await self._fold(events)

    async def apply_events(self, events: Sequence[Any]):
        events = list(events)
        self.applied.append(events)
        if self._canned:
            return self._canned.pop(0)
        if self.model is None:
            return CommandFailure(RuntimeError(
                f"StubAggregateRef({self.aggregate_id!r}) has no model and no "
                "canned reply — pass model= or call reply_with()"))
        return await self._fold(events)

    async def _fold(self, events: Sequence[Any]):
        import inspect

        try:
            new_state = fold_events(self.model, self.state, events)
            if inspect.isawaitable(new_state):
                new_state = await new_state
        except Exception as exc:  # noqa: BLE001 — the failure path under test
            return CommandFailure(exc)
        self.state = new_state
        return CommandSuccess(new_state)

    async def get_state(self) -> Optional[Any]:
        if self._canned:
            result = self._canned.pop(0)
            if isinstance(result, CommandFailure):
                raise result.error
            return result
        return self.state


class StubEngine:
    """``aggregate_for``-compatible engine double: one shared state map, one
    :class:`StubAggregateRef` per aggregate id (stable across calls), and a
    flat command journal across all aggregates for assertions.

    ``seed_state({"a-1": State(...)})`` pre-loads aggregates; ``ref_factory``
    swaps in a custom stub subclass.
    """

    def __init__(self, model: Any = None,
                 ref_factory: Callable[..., StubAggregateRef] | None = None
                 ) -> None:
        self.model = model
        self.states: Dict[str, Any] = {}
        self.commands: List[Any] = []  # cross-aggregate, in send order
        self._refs: Dict[str, StubAggregateRef] = {}
        self._ref_factory = ref_factory or StubAggregateRef

    def seed_state(self, states: Dict[str, Any]) -> "StubEngine":
        self.states.update(states)
        return self

    def aggregate_for(self, aggregate_id: str) -> StubAggregateRef:
        ref = self._refs.get(aggregate_id)
        if ref is None:
            ref = self._ref_factory(aggregate_id, model=self.model,
                                    states=self.states,
                                    journal=self.commands)
            self._refs[aggregate_id] = ref
        return ref

    # the lifecycle surface service code may touch — no-ops on the stub
    async def start(self) -> None:
        return None

    async def stop(self) -> None:
        return None
