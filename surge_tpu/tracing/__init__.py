"""Tracing: OTel-shaped spans + W3C trace-context propagation across async hops.

Equivalents of the reference tracing stack (SURVEY.md §5.1): spans wrap every message
hop (``ActorWithTracing`` wraps receive; spans created at the AggregateRef ask boundary
AggregateRefTrait.scala:77-79, in the router/shard KafkaPartitionShardRouterActor.scala:216,
and in the aggregate actor PersistentActor.scala:166-168); ``TracedMessage`` carries W3C
``traceparent`` headers across hops (internal/tracing/TracedMessage.scala:10-26);
inject/extract mirrors ``TracePropagation.asHeaders``/``childFrom``
(TracePropagation.scala:13-61 — W3CTraceContextPropagator format:
``00-{trace_id:32x}-{span_id:16x}-{flags:02x}``).

No OpenTelemetry SDK dependency: :class:`Tracer` is the pluggable surface (users supply
an exporter; the reference's noop-by-default ``openTelemetry`` override,
SurgeGenericBusinessLogicTrait.scala:33), with :class:`InMemoryTracer` for tests and
:class:`NoopTracer` as the default.
"""

from __future__ import annotations

import contextvars
import json
import random
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional

__all__ = [
    "InMemoryTracer",
    "JsonlSpanExporter",
    "NoopTracer",
    "Span",
    "SpanContext",
    "Tracer",
    "active_span",
    "active_trace_id",
    "extract_context",
    "inject_context",
]

#: the span the current context is inside of (set by ``with span:``) — what
#: OpenMetrics exemplars read so a histogram bucket can link to the trace that
#: produced its sample (contextvars: isolated per thread AND per asyncio task)
_ACTIVE_SPAN: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "surge_active_span", default=None)


def active_span() -> Optional["Span"]:
    """The span the current context is inside of, or None — the parenting
    anchor for spans started on the caller's behalf (the log client parents
    its broker-call spans here so a pipelined retry's failover histograms
    carry the ORIGINATING command's trace id, not a fresh root's)."""
    return _ACTIVE_SPAN.get()


def active_trace_id() -> Optional[str]:
    """Trace id of the innermost SAMPLED span the caller is running under, or
    None — the exemplar source for histograms (an unsampled trace has no
    exported spans to link to, so it yields no exemplar either)."""
    span = _ACTIVE_SPAN.get()
    if span is not None and span.context.sampled:
        return span.context.trace_id
    return None

_TRACEPARENT = "traceparent"
_RE_TRACEPARENT = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$")


@dataclass(frozen=True)
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def inject_context(ctx: SpanContext, headers: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """TracePropagation.asHeaders: W3C traceparent into a header map."""
    out = dict(headers or {})
    out[_TRACEPARENT] = f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"
    return out


def extract_context(headers: Mapping[str, str]) -> Optional[SpanContext]:
    """TracePropagation.childFrom: parse traceparent; None if absent/malformed."""
    raw = headers.get(_TRACEPARENT, "")
    m = _RE_TRACEPARENT.match(raw)
    if not m:
        return None
    return SpanContext(trace_id=m.group("trace"), span_id=m.group("span"),
                       sampled=m.group("flags") == "01")


@dataclass
class Span:
    """One operation's span. ``finish`` hands it to the tracer's exporter.

    Carries BOTH clocks: ``start_time``/``end_time`` are wall stamps (the
    human anchor, and what the JSONL exporter ships), ``start_mono``/
    ``end_mono`` are ``time.monotonic()`` stamps — the ordering truth the
    cross-process trace assembly (observability/anatomy.py) places spans by,
    via the same per-host mono↔wall offset estimation the flight recorder's
    merge uses, so a skewed wall clock cannot scramble a trace."""

    name: str
    context: SpanContext
    parent_id: Optional[str] = None
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    start_mono: float = field(default_factory=time.monotonic)
    end_mono: Optional[float] = None
    attributes: Dict[str, object] = field(default_factory=dict)
    events: List[tuple] = field(default_factory=list)
    status: str = "ok"  # "ok" | "error"
    _tracer: Optional["Tracer"] = field(default=None, repr=False)
    _cv_token: Optional[object] = field(default=None, repr=False, compare=False)

    def set_attribute(self, key: str, value: object) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, attributes: Optional[dict] = None) -> "Span":
        """TracingHelper's log op."""
        self.events.append((time.time(), name, attributes or {}))
        return self

    def record_exception(self, exc: BaseException) -> "Span":
        """TracingHelper's error op."""
        self.status = "error"
        self.add_event("exception", {"type": type(exc).__name__, "message": str(exc)})
        return self

    def activate(self) -> "Span":
        """Make this span the context's ACTIVE span (what exemplar capture
        reads) without a ``with`` block — for call sites that manage
        ``finish()`` manually, like the entity's receive span. ``finish()``
        (and ``__exit__``) deactivates."""
        if self._cv_token is None:
            self._cv_token = _ACTIVE_SPAN.set(self)
        return self

    def _deactivate(self) -> None:
        if self._cv_token is None:
            return
        token, self._cv_token = self._cv_token, None
        # only restore the snapshot if THIS span is still the active one:
        # finishing a stored span from another context (callback, timeout
        # handler) or out of nesting order must never clobber an unrelated
        # still-open span's activation
        if _ACTIVE_SPAN.get() is not self:
            return
        try:
            _ACTIVE_SPAN.reset(token)
        except ValueError:  # token from another context; we ARE active: clear
            _ACTIVE_SPAN.set(None)

    def finish(self) -> None:
        self._deactivate()
        if self.end_time is None:
            self.end_time = time.time()
            self.end_mono = time.monotonic()
            if self._tracer is not None:
                self._tracer._on_finished(self)

    @property
    def duration_ms(self) -> float:
        return ((self.end_time or time.time()) - self.start_time) * 1000.0

    # context-manager sugar
    def __enter__(self) -> "Span":
        return self.activate()

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.record_exception(exc)
        self.finish()  # deactivates too


class Tracer:
    """Span factory with an exporter hook and head-based probability sampling.

    ``sample_rate`` is the probability a NEW trace (root span) is sampled; the
    decision rides the W3C ``sampled`` flag so every downstream hop — including
    remote ones — honors the head's verdict without its own coin flip. Unsampled
    spans are still created (context propagation stays intact, attributes are
    cheap dict writes) but never reach the exporter.

    ``tail`` (a :class:`surge_tpu.tracing.tail.TailSampler`, attached by
    :func:`surge_tpu.tracing.tail.install_tail`) rides BEHIND the head gate:
    every head-sampled span is also offered to the tail sampler, which
    buffers per trace and decides keep/drop only once the trace completes
    (erred, breached the latency threshold, or landed in an SLO breach
    window). Head sampling stays the fast-path cost gate; the tail decision
    rides completed traces only.
    """

    def __init__(self, service: str = "surge",
                 exporter: Optional[Callable[[Span], None]] = None,
                 sample_rate: float = 1.0,
                 seed: Optional[int] = None) -> None:
        self.service = service
        self._exporter = exporter
        self.sample_rate = sample_rate
        self.tail = None  # Optional[tail.TailSampler]
        self._rng = random.Random(seed)

    def _sample_root(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return self._rng.random() < self.sample_rate

    def start_span(self, name: str,
                   parent: Optional[SpanContext | Span] = None,
                   headers: Optional[Mapping[str, str]] = None) -> Span:
        """Child of ``parent`` (or of the context in ``headers``), else a new root."""
        parent_ctx = parent.context if isinstance(parent, Span) else parent
        if parent_ctx is None and headers is not None:
            parent_ctx = extract_context(headers)
        if parent_ctx is not None:
            ctx = SpanContext(trace_id=parent_ctx.trace_id, span_id=_new_span_id(),
                              sampled=parent_ctx.sampled)
            span = Span(name=name, context=ctx, parent_id=parent_ctx.span_id,
                        _tracer=self)
        else:
            ctx = SpanContext(trace_id=_new_trace_id(), span_id=_new_span_id(),
                              sampled=self._sample_root())
            span = Span(name=name, context=ctx, _tracer=self)
        if self.tail is not None and ctx.sampled:
            self.tail.on_start(span)
        return span

    def _on_finished(self, span: Span) -> None:
        if not span.context.sampled:
            return
        if self._exporter is not None:
            self._exporter(span)
        if self.tail is not None:
            self.tail.on_finish(span)


class NoopTracer(Tracer):
    """Default: spans are created but never exported (noop OpenTelemetry default)."""

    def __init__(self) -> None:
        super().__init__(exporter=None)


class InMemoryTracer(Tracer):
    """Collects finished spans for assertions (test exporter)."""

    def __init__(self, service: str = "surge", sample_rate: float = 1.0,
                 seed: Optional[int] = None) -> None:
        self.finished: List[Span] = []
        super().__init__(service=service, exporter=self.finished.append,
                         sample_rate=sample_rate, seed=seed)

    def spans_named(self, name: str) -> List[Span]:
        return [s for s in self.finished if s.name == name]


class JsonlSpanExporter:
    """Span exporter appending one JSON object per finished span to a file.

    The production-shaped sink for the no-SDK tracer: the JSONL stream is what
    an OTel collector sidecar (or plain ``jq``) tails. Thread-safe — spans
    finish on the event loop AND on executor/log-client threads — and flushed
    per span so a crash loses at most the span being written.

    Usage: ``tracer = Tracer(exporter=JsonlSpanExporter(path), sample_rate=0.1)``.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._file = open(path, "a", encoding="utf-8")

    def __call__(self, span: Span) -> None:
        record = {
            "name": span.name,
            "trace_id": span.context.trace_id,
            "span_id": span.context.span_id,
            "parent_id": span.parent_id,
            "start_time": span.start_time,
            "end_time": span.end_time,
            "duration_ms": span.duration_ms,
            "status": span.status,
            "attributes": span.attributes,
            "events": [{"time": t, "name": n, "attributes": a}
                       for t, n, a in span.events],
        }
        line = json.dumps(record, default=str)
        with self._lock:
            if self._file.closed:
                return
            self._file.write(line + "\n")
            self._file.flush()

    def close(self) -> None:
        with self._lock:
            if not self._file.closed:
                self._file.close()

    def __enter__(self) -> "JsonlSpanExporter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
