"""Tail-based trace sampling + the kept-trace ring (the command-anatomy
plane's capture half, ISSUE 14).

Head sampling (:class:`~surge_tpu.tracing.Tracer` ``sample_rate``) decides
*per trace, up front, blind* — it bounds tracing cost but keeps a uniform
sample, which on a host with 2-3× run-to-run latency swings is almost all
boring traces. The :class:`TailSampler` decides *per trace, at the end,
informed*: every head-sampled span is buffered per trace id until the trace
quiesces (no span of it still open in this process), and the completed trace
is **kept** iff it

- **erred** — any span finished with ``status="error"``;
- **breached the latency threshold** — its slowest span (the local root
  covers every child) ran at least ``surge.trace.tail.latency-ms``;
- **landed in an SLO breach window** — the SLO burn-rate engine opened a
  window via :meth:`TailSampler.open_breach_window` (breach-adjacent traces
  are evidence even when individually fast); or
- was **marked** explicitly (:meth:`TailSampler.mark_trace` — exemplar ids a
  breach event cites must stay dumpable).

Keeps are **budgeted** (``surge.trace.tail.keep-budget`` per
``surge.trace.tail.budget-window-ms``): an incident that makes *every* trace
keep-worthy must not OOM the ring or the dump path; keep-eligible traces past
the budget are dropped and counted. The span buffer itself is bounded
(``surge.trace.tail.max-buffer-spans``): leaked or never-finishing traces are
evicted oldest-first, also counted. Drop counters ride
``surge.trace.dropped`` next to ``surge.trace.kept`` and the
``surge.trace.tail-buffer-spans`` gauge, on whichever quiver (engine or
broker) the installer wired.

Kept traces land in a :class:`TraceRing` — the flight-recorder pattern: a
bounded ring of merge-ready envelopes, pulled over the new ``DumpTraces``
RPCs (log-service for brokers, engine-admin for engines). The envelope
carries the host's two clocks stamped at one instant (``dumped_wall`` /
``dumped_mono``), so :mod:`surge_tpu.observability.anatomy` can place spans
from several processes on one timeline through the same mono↔wall offset
estimation the flight merge uses — wall skew during the incident cannot
scramble a trace.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from surge_tpu.tracing import Span

__all__ = ["TailSampler", "TraceRing", "install_tail", "span_to_dict"]


def _span_ms(span: Span) -> float:
    """A span's duration from the MONOTONIC clock when both stamps exist —
    a wall step landing mid-span (the exact skew this module's envelope
    machinery defends against) must not shrink a slow span under the keep
    threshold or inflate a fast one over it."""
    if span.end_mono is not None:
        return max((span.end_mono - span.start_mono) * 1000.0, 0.0)
    return span.duration_ms


def span_to_dict(span: Span) -> dict:
    """The merge-ready span record: both clocks, tree identity, leg attrs."""
    return {
        "name": span.name,
        "trace_id": span.context.trace_id,
        "span_id": span.context.span_id,
        "parent_id": span.parent_id,
        "start_wall": span.start_time,
        "end_wall": span.end_time,
        "start_mono": span.start_mono,
        "end_mono": span.end_mono,
        "duration_ms": _span_ms(span),
        "status": span.status,
        "attributes": dict(span.attributes),
        "events": [{"time": t, "name": n, "attributes": a}
                   for t, n, a in span.events],
    }


class TraceRing:
    """Bounded ring of kept traces (the flight recorder's trace twin).

    One per broker and one per engine. Thread-safe: keeps arrive from gRPC
    handler threads, publisher lane threads and the event loop alike.
    ``dump()`` returns the merge-ready envelope — recorder identity, ring
    stats, the mono↔wall header pair, and one entry per kept trace
    (``{"trace_id", "reason", "spans"}``; a trace whose late spans finished
    after its keep decision may appear as several entries — consumers group
    by trace id).
    """

    def __init__(self, capacity: int = 256, name: str = "",
                 role: str = "broker") -> None:
        self._ring: "deque" = deque(maxlen=max(capacity, 4))
        self._lock = threading.Lock()
        #: kept traces the bounded ring evicted to make room — a dump reader
        #: must be able to tell the ring wrapped mid-incident
        self._dropped = 0
        self._kept_total = 0
        self.name = name  # set lazily (broker: advertised addr at start())
        self.role = role  # "broker" | "engine" — the merged-timeline lane
        self.node = socket.gethostname()

    def keep(self, trace_id: str, reason: str, spans: List[dict]) -> None:
        """Retain one completed trace; never raises (the sampler must not be
        able to take down the path it observes)."""
        try:
            with self._lock:
                self._kept_total += 1
                if len(self._ring) == self._ring.maxlen:
                    self._dropped += 1
                self._ring.append({"trace_id": trace_id, "reason": reason,
                                   "spans": spans})
        except Exception:  # noqa: BLE001 — observability stays passive
            pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        return {"traces": len(self._ring),
                "capacity": self._ring.maxlen,
                "kept_total": self._kept_total,
                "dropped": self._dropped}

    def trace_ids(self, last: int = 3) -> List[str]:
        """The newest ``last`` kept trace ids (newest first) — what an SLO
        breach event cites as its exemplars."""
        with self._lock:
            items = list(self._ring)[-max(last, 0):]
        seen: List[str] = []
        for entry in reversed(items):
            tid = entry["trace_id"]
            if tid not in seen:
                seen.append(tid)
        return seen

    def dump(self, last: Optional[int] = None) -> dict:
        """The merge-ready dump envelope. Stats and entries snapshot under
        ONE lock hold; ``dumped_wall``/``dumped_mono`` pair the host's two
        clocks at one instant — the header anatomy.py estimates this host's
        mono↔wall offset from."""
        with self._lock:
            stats = self._stats_locked()
            items = list(self._ring)
        if last is not None:
            items = items[-last:] if last > 0 else []
        return {"recorder": self.name, "node": self.node, "pid": os.getpid(),
                "role": self.role, "stats": stats,
                "dumped_wall": time.time(), "dumped_mono": time.monotonic(),
                "traces": items}

    def dump_to(self, path: str, last: Optional[int] = None) -> None:
        """Write the dump as JSON (best-effort, like the flight twin)."""
        try:
            with open(path, "w") as f:
                json.dump(self.dump(last), f)
        except OSError:
            pass


class _TraceBuf:
    """Per-trace buffer while the trace is in flight: finished span dicts +
    how many of its spans are still open in this process."""

    __slots__ = ("spans", "open", "erred", "max_ms")

    def __init__(self) -> None:
        self.spans: List[dict] = []
        self.open = 0
        self.erred = False
        self.max_ms = 0.0


class TailSampler:
    """Buffers head-sampled spans per trace; keeps completed traces that
    erred / breached latency / landed in a breach window (module doc).

    Attach via :func:`install_tail` (or ``tracer.tail = sampler``). The
    tracer calls :meth:`on_start`/:meth:`on_finish` for sampled spans only —
    head sampling remains the fast-path cost gate.
    """

    def __init__(self, ring: TraceRing, latency_ms: float = 250.0,
                 keep_budget: int = 64, budget_window_s: float = 10.0,
                 max_buffer_spans: int = 4096,
                 breach_window_s: float = 30.0,
                 metrics=None, clock=time.monotonic) -> None:
        self.ring = ring
        self.latency_ms = latency_ms
        self.keep_budget = max(keep_budget, 1)
        self.budget_window_s = budget_window_s
        self.max_buffer_spans = max(max_buffer_spans, 8)
        self.breach_window_s = breach_window_s
        self.metrics = metrics  # quiver with trace_kept/trace_dropped/
        #                         trace_tail_buffer (engine or broker)
        self._clock = clock
        self._lock = threading.Lock()
        #: insertion-ordered: eviction under the buffer bound walks oldest
        #: traces first
        self._buf: Dict[str, _TraceBuf] = {}
        self._buffered_spans = 0
        self._keeps: "deque" = deque()  # keep stamps inside the budget window
        #: recently kept trace ids → keep reason (bounded): spans finishing
        #: AFTER their trace's keep decision (a pipelined retry leg) append
        #: straight to the ring under the original verdict
        self._kept_recent: "OrderedDict[str, str]" = OrderedDict()
        self._breach_until = 0.0
        self._marked: set = set()
        self.kept = 0
        #: drop tallies by reason: "sampled-out" (completed, nothing
        #: keep-worthy), "budget" (keep-worthy past the window budget),
        #: "buffer" (evicted by the span-buffer bound before completing)
        self.dropped: Dict[str, int] = {"sampled-out": 0, "budget": 0,
                                        "buffer": 0}

    @classmethod
    def from_config(cls, config, ring: TraceRing,
                    metrics=None) -> "TailSampler":
        return cls(
            ring,
            latency_ms=config.get_float("surge.trace.tail.latency-ms", 250.0),
            keep_budget=config.get_int("surge.trace.tail.keep-budget", 64),
            budget_window_s=config.get_seconds(
                "surge.trace.tail.budget-window-ms", 10_000),
            max_buffer_spans=config.get_int(
                "surge.trace.tail.max-buffer-spans", 4096),
            breach_window_s=config.get_seconds(
                "surge.trace.tail.breach-window-ms", 30_000),
            metrics=metrics)

    # -- tracer hooks (never raise: recording must not break the traced path) --

    def on_start(self, span: Span) -> None:
        try:
            with self._lock:
                buf = self._buf.get(span.context.trace_id)
                if buf is None:
                    buf = self._buf[span.context.trace_id] = _TraceBuf()
                buf.open += 1
        except Exception:  # noqa: BLE001 — observability stays passive
            pass

    def on_finish(self, span: Span) -> None:
        try:
            self._on_finish(span)
        except Exception:  # noqa: BLE001 — observability stays passive
            pass

    def _on_finish(self, span: Span) -> None:
        tid = span.context.trace_id
        keep: Optional[tuple] = None
        fresh_keep = False
        evicted = 0
        with self._lock:
            buf = self._buf.get(tid)
            reason = self._kept_recent.get(tid)
            if reason is not None:
                # the trace was already kept (a late span finishing after
                # the decision — a pipelined retry leg): append straight
                # through under the original verdict, flushing anything the
                # start hook re-buffered meanwhile
                spans = [span_to_dict(span)]
                if buf is not None:
                    spans = buf.spans + spans
                    self._buffered_spans -= len(buf.spans)
                    self._buf.pop(tid, None)
                keep = (tid, reason, spans)
            else:
                if buf is None:
                    # finish without a start: a span created before the
                    # sampler attached, or its trace was evicted mid-flight —
                    # re-open so a late keep-worthy leg is not silently lost
                    buf = self._buf[tid] = _TraceBuf()
                    buf.open = 1
                buf.spans.append(span_to_dict(span))
                self._buffered_spans += 1
                buf.open = max(buf.open - 1, 0)
                if span.status == "error":
                    buf.erred = True
                buf.max_ms = max(buf.max_ms, _span_ms(span))
                if buf.open == 0:
                    keep = self._decide_locked(tid, buf)
                    fresh_keep = keep is not None
                evicted = self._evict_over_bound_locked()
            buffered = self._buffered_spans
        if keep is not None:
            self.ring.keep(*keep)
        m = self.metrics
        if m is not None:
            if fresh_keep:
                m.trace_kept.record()
            if evicted:
                m.trace_dropped.record(evicted)
            m.trace_tail_buffer.record(buffered)

    # -- decision -------------------------------------------------------------------------

    def _decide_locked(self, tid: str, buf: _TraceBuf) -> Optional[tuple]:
        """Keep/drop a quiescent trace; returns the ring entry to keep (the
        actual ring append happens outside the lock) or None."""
        now = self._clock()
        reason = None
        if buf.erred:
            reason = "error"
        elif buf.max_ms >= self.latency_ms:
            reason = "latency"
        elif tid in self._marked:
            reason = "marked"
        elif now < self._breach_until:
            reason = "breach-window"
        self._marked.discard(tid)
        if reason is None:
            self._drop_locked(tid, buf, "sampled-out")
            return None
        while self._keeps and self._keeps[0] < now - self.budget_window_s:
            self._keeps.popleft()
        if len(self._keeps) >= self.keep_budget:
            self._drop_locked(tid, buf, "budget")
            return None
        self._keeps.append(now)
        self.kept += 1
        self._kept_recent[tid] = reason
        while len(self._kept_recent) > 1024:
            self._kept_recent.popitem(last=False)
        spans, buf.spans = buf.spans, []
        self._buffered_spans -= len(spans)
        self._buf.pop(tid, None)
        return (tid, reason, spans)

    def _drop_locked(self, tid: str, buf: _TraceBuf, why: str) -> None:
        self.dropped[why] = self.dropped.get(why, 0) + 1
        self._buffered_spans -= len(buf.spans)
        self._buf.pop(tid, None)
        if self.metrics is not None:
            self.metrics.trace_dropped.record()

    def _evict_over_bound_locked(self) -> int:
        """Evict oldest traces while the span buffer exceeds its bound (a
        leaked span's trace never quiesces; unbounded growth is not an
        option). Returns evictions for the out-of-lock counter."""
        evicted = 0
        while self._buffered_spans > self.max_buffer_spans and self._buf:
            tid, buf = next(iter(self._buf.items()))
            self.dropped["buffer"] += 1
            self._buffered_spans -= len(buf.spans)
            self._buf.pop(tid, None)
            evicted += 1
        return evicted

    # -- SLO / exemplar wiring ------------------------------------------------------------

    def open_breach_window(self, duration_s: Optional[float] = None) -> None:
        """Keep every trace completing within the window (the SLO engine
        calls this when an objective breaches: breach-adjacent traces are the
        anatomy evidence, even the individually fast ones)."""
        with self._lock:
            self._breach_until = max(
                self._breach_until,
                self._clock() + (duration_s if duration_s is not None
                                 else self.breach_window_s))

    def mark_trace(self, trace_id: str) -> None:
        """Force-keep one trace when it completes (exemplar ids cited by a
        breach event must stay dumpable)."""
        with self._lock:
            self._marked.add(trace_id)

    def stats(self) -> dict:
        with self._lock:
            return {"buffered_spans": self._buffered_spans,
                    "buffered_traces": len(self._buf),
                    "kept": self.kept, "dropped": dict(self.dropped),
                    "breach_window_open":
                        self._clock() < self._breach_until}


def install_tail(tracer, config, *, name: str = "", role: str = "broker",
                 metrics=None) -> Optional[TraceRing]:
    """Attach tail sampling + a kept-trace ring to ``tracer`` (idempotent).

    Returns the ring (the ``DumpTraces`` RPC's source), or None when tracing
    is off (``tracer is None``) or ``surge.trace.tail.enabled`` is false.
    A tracer shared between co-resident components keeps the FIRST
    installer's ring — spans from all of them land in one ring, which is
    exactly what a single-process deployment wants dumped.
    """
    if tracer is None or not config.get_bool("surge.trace.tail.enabled", True):
        return None
    existing = getattr(tracer, "tail", None)
    if existing is not None:
        return existing.ring
    ring = TraceRing(
        capacity=config.get_int("surge.trace.ring-capacity", 256),
        name=name, role=role)
    tracer.tail = TailSampler.from_config(config, ring, metrics=metrics)
    return ring
