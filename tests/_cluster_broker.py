"""Broker process for the multi-process cluster test: shared log + control plane.

Prints one JSON line ``{"log_port": N, "cp_port": M}`` when ready, then serves until
killed. The log broker is the external-Kafka-broker role; the control plane is the
consumer-group/seed role (SURVEY.md §2.9 item 3, §2.10 distributed backend).
"""

import asyncio
import json
import sys

sys.path.insert(0, ".")  # repo root

from surge_tpu.log import InMemoryLog, LogServer  # noqa: E402
from surge_tpu.remote.control_plane import ControlPlaneServer  # noqa: E402


async def main() -> None:
    num_partitions = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    log_server = LogServer(InMemoryLog())
    log_port = log_server.start()
    cp = ControlPlaneServer(num_partitions=num_partitions, member_timeout_s=1.5)
    cp_port = await cp.start()
    print(json.dumps({"log_port": log_port, "cp_port": cp_port}), flush=True)
    await asyncio.Event().wait()  # serve until killed


if __name__ == "__main__":
    asyncio.run(main())
