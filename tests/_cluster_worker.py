"""Worker process for the multi-process cluster test: one EngineNode.

    python tests/_cluster_worker.py <cp_target> <log_target> <my_name> <peer_name> \
        <result_path>

Round 1 (after both members are visible): increment 12 of MY aggregates — spread
across every partition, so some route to the peer process over real gRPC — and
write ``{agg: count}`` to ``<result_path>.r1``.

Round 2 (triggered by the driver creating ``<result_path>.go2``): increment my
aggregates AND the peer's — run after the peer was SIGKILLed, proving heartbeat
expiry → rebalance → takeover with state recovered from the shared log — and write
``<result_path>.r2``.
"""

import asyncio
import json
import os
import sys

sys.path.insert(0, ".")

from surge_tpu import SurgeCommandBusinessLogic, default_config  # noqa: E402
from surge_tpu.engine.entity import CommandSuccess  # noqa: E402
from surge_tpu.log import GrpcLogTransport  # noqa: E402
from surge_tpu.models import counter  # noqa: E402
from surge_tpu.remote.node import EngineNode  # noqa: E402

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 10,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 4,
    "surge.control-plane.ping-interval-ms": 200,
    # each worker keeps a warm standby of the peer's partitions so the
    # post-kill takeover needs no state re-read (VERDICT r3 next #4)
    "surge.state-store.num-standby-replicas": 1,
})


def aggs_for(name: str) -> list:
    return [f"{name}-{i}" for i in range(12)]


async def send_round(node: EngineNode, aggregates: list) -> dict:
    out = {}
    for agg in aggregates:
        last_err = None
        for _ in range(10):  # rebalance handoffs can fail a command transiently
            r = await node.aggregate_for(agg).send_command(counter.Increment(agg))
            if isinstance(r, CommandSuccess):
                out[agg] = r.state.count
                last_err = None
                break
            last_err = r
            await asyncio.sleep(0.3)
        if last_err is not None:
            out[agg] = f"FAILED: {last_err}"
    return out


async def main() -> None:
    cp_target, log_target, my_name, peer_name, result_path = sys.argv[1:6]
    node = EngineNode(
        SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting(),
            command_format=counter.command_formatting()),
        cp_target, GrpcLogTransport(log_target), node_name=my_name, config=CFG)
    await node.start()

    # wait until both members are visible (so partitions are really split)
    for _ in range(100):
        if len(node.client.membership.members) >= 2:
            break
        await asyncio.sleep(0.1)
    await asyncio.sleep(0.5)  # let regions settle after the join rebalance

    result = await send_round(node, aggs_for(my_name))
    with open(result_path + ".r1.tmp", "w") as f:
        json.dump(result, f)
    os.replace(result_path + ".r1.tmp", result_path + ".r1")

    # idle until the driver triggers round 2 (after killing the peer). While
    # waiting — peer alive, partitions still split — keep snapshotting the
    # indexer watermarks: nonzero watermarks on NON-owned partitions here can
    # only come from standby tailing, which is what makes the takeover below a
    # promotion (no re-read) rather than a recovery scan.
    engine = node.engine

    def snapshot():
        return ({str(p): engine.indexer.indexed_watermark(
                    engine.logic.state_topic, p) for p in range(4)},
                {str(p) for p in engine.owned_partitions()})

    # snapshot BEFORE the wait loop too: if .go2 already exists on the first
    # check, the captured values must still reflect the pre-kill split
    standby_watermarks, owned_now = snapshot()
    while not os.path.exists(result_path + ".go2"):
        standby_watermarks, owned_now = snapshot()
        await asyncio.sleep(0.1)
    await asyncio.sleep(0.5)  # let expiry + rebalance settle

    result = await send_round(node, aggs_for(my_name) + aggs_for(peer_name))
    result["_standby_watermarks"] = standby_watermarks
    result["_owned_before_kill"] = sorted(owned_now)
    result["_standby_partitions"] = [str(p) for p in standby_watermarks
                                     if standby_watermarks[p] > 0
                                     and p not in owned_now]
    with open(result_path + ".r2.tmp", "w") as f:
        json.dump(result, f)
    os.replace(result_path + ".r2.tmp", result_path + ".r2")

    await asyncio.Event().wait()  # stay alive until the driver kills us


if __name__ == "__main__":
    asyncio.run(main())
