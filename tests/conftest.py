"""tests/ conftest: the tier-1 mesh contract.

The root conftest forces an 8-device virtual CPU platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) for every test run.
Mesh-sharded paths used to guard themselves with ``skipif device_count < 8``,
which meant a broken forcing (an env var override, a jax upgrade changing
flag handling) silently SKIPPED the multi-device byte-identity proofs while
tier-1 still went green. The ``mesh8`` fixture inverts that: mesh tests
REQUIRE the 8 devices and fail loudly when the platform lost them — the
sharded fold, gather lanes and query scans run on every tier-1 pass.
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

# tools/regen_golden_metrics.py puts tests/ AHEAD of the repo root on
# sys.path, so `import conftest` resolves HERE instead of the root conftest
# some test modules pull helpers from — re-export them by loading the root
# module explicitly (under pytest the root conftest wins the name and this
# indirection is never consulted)
_root_path = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "conftest.py")
_spec = importlib.util.spec_from_file_location("_root_conftest", _root_path)
_root_conftest = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_root_conftest)
free_ports = _root_conftest.free_ports


@pytest.fixture
def mesh8():
    """An 8-device 1-D ``data`` mesh over the forced host platform. FAILS
    (never skips) when fewer than 8 devices exist — tier-1 must always
    exercise the mesh paths."""
    devs = jax.devices()
    assert len(devs) >= 8, (
        f"tier-1 requires 8 forced host devices (got {len(devs)}): the root "
        "conftest sets XLA_FLAGS=--xla_force_host_platform_device_count=8 — "
        "check nothing overrode XLA_FLAGS/JAX_PLATFORMS before jax "
        "initialized")
    return jax.sharding.Mesh(np.array(devs[:8]), ("data",))
