"""Known-bad: awaits lexically inside threading-lock bodies."""
import threading


class Broker:
    def __init__(self):
        self._role_lock = threading.RLock()
        self.cond = threading.Condition(self._role_lock)

    async def transact(self, batch):
        with self._role_lock:
            await self._replicate(batch)  # line 12: await under RLock
        with self.cond:
            return await self._finalize()  # line 14: await under Condition
