"""Known-good: lock holds are await-free; awaits happen outside, or under an
asyncio.Lock (which is built for exactly this)."""
import asyncio
import threading


class Broker:
    def __init__(self):
        self._role_lock = threading.RLock()
        self._aio_lock = asyncio.Lock()

    async def transact(self, batch):
        with self._role_lock:
            fenced = self._check_fence(batch)
        if not fenced:
            await self._replicate(batch)
        async with self._aio_lock:
            await self._finalize()

    def snapshot(self):
        with self._role_lock:
            return dict(self._state)

    async def dispatch(self, loop):
        # a nested thunk handed to an executor runs OFF the loop: its body
        # is a separate execution context, not an await under the lock
        def _locked_io():
            with self._role_lock:
                return self._fsync()
        return await loop.run_in_executor(None, _locked_io)
