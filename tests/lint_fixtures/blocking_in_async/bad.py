"""Known-bad: blocking syscalls directly on the event loop."""
import os
import time

import grpc


class Journal:
    async def flush(self, executor):
        time.sleep(0.01)  # line 10: sleep on the loop
        with open("journal.log", "ab") as f:  # line 11: sync file I/O
            os.fsync(f.fileno())  # line 12: fsync on the loop
        fut = executor.submit(self._sync_round)
        return fut.result()  # line 14: executor future blocks the loop

    async def dial(self, target):
        return grpc.insecure_channel(target)  # line 17: sync gRPC channel
