"""Known-good: the same operations dispatched correctly — async sleeps,
executor thunks for file I/O, aio channels, awaited executor futures."""
import asyncio
import os
import time

import grpc


class Journal:
    async def flush(self, loop, executor):
        await asyncio.sleep(0.01)

        def _sync_round():
            with open("journal.log", "ab") as f:  # executor thunk: off-loop
                os.fsync(f.fileno())
                time.sleep(0.001)
        await loop.run_in_executor(executor, _sync_round)
        fut = executor.submit(_sync_round)
        return await asyncio.wrap_future(fut)

    async def dial(self, target):
        return grpc.aio.insecure_channel(target)

    def sync_maintenance(self):
        # a plain def may block all it wants — it runs on a worker thread
        time.sleep(0.01)
        with open("journal.log", "ab") as f:
            os.fsync(f.fileno())
