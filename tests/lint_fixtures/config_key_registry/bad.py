"""Known-bad: reads a surge.* key that has no DEFAULTS row (and no docs row)."""
from surge_tpu.config import default_config


def load():
    cfg = default_config()
    return cfg.get_int("surge.lint-fixture.unregistered-key", 7)  # line 7
