"""Known-good: reads only registered, documented keys."""
from surge_tpu.config import default_config


def load():
    cfg = default_config()
    return (cfg.get_str("surge.replay.backend", "tpu"),
            cfg.get_int("surge.replay.batch-size", 8192))
