"""Known-bad: per-item event-loop round-trips in a fast-path module."""
# surgelint: fast-path-module
import asyncio


class Publisher:
    async def publish_all(self, records):
        for r in records:
            await self.log.append(r)  # line 9: await per record

    async def queue_all(self, loop, records):
        futs = []
        for r in records:
            futs.append(loop.create_future())  # line 14: Future per record
        return futs

    async def ask(self, fut):
        return await asyncio.wait_for(fut, 5.0)  # line 18: wrapper task
