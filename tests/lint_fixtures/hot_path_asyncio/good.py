"""Known-good: batched awaits, batch-level futures, bare timer waits."""
# surgelint: fast-path-module
import asyncio

from surge_tpu.common import wait_future


class Publisher:
    async def publish_all(self, records):
        ack = asyncio.get_running_loop().create_future()  # one per batch
        for r in records:
            self._pending.append((r, ack))
        self._wake.set()
        await wait_future(ack, 5.0, owned=False)  # one await per batch

    async def retry_ladder(self, fut):
        for _attempt in range(3):  # bounded retry ladder, not per-record
            try:
                return await wait_future(fut, 5.0)
            except asyncio.TimeoutError:
                continue
