"""Known-bad: Python side effects inside a staged fold."""
import time

import jax

TRACE_LOG = []
CACHE = {}


def build(width):
    def fold(carry, window):
        print("folding", width)  # line 12: trace-time print
        TRACE_LOG.append(window)  # line 13: closed-over mutation
        t0 = time.time()  # line 14: wall-clock read baked into the trace
        CACHE["last"] = carry  # line 15: closed-over subscript assignment
        return carry, t0
    return jax.jit(fold)
