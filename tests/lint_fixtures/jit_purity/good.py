"""Known-good: staged folds mutate only their own locals and call only
array ops; timestamps arrive as arguments."""
import jax
import jax.numpy as jnp


def build(width):
    def fold(carry, window, now):
        parts = []
        parts.append(carry)  # local list: trace-time assembly is fine
        acc = {}
        acc["w"] = window  # local dict subscript is fine
        return jnp.add(carry, window) + now

    return jax.jit(fold)


def host_side(records, stats):
    # unstaged host code may print/mutate freely
    print("decoded", len(records))
    stats.append(len(records))
