"""Known-bad: creates an instrument missing from the docs catalog."""
from surge_tpu.metrics import MetricInfo, Metrics


def build(m: Metrics):
    return m.timer(MetricInfo("surge.lint-fixture.mystery-timer", "x"))  # line 6
