"""Known-good: creates only cataloged instruments."""
from surge_tpu.metrics import MetricInfo, Metrics


def build(m: Metrics):
    return m.timer(MetricInfo("surge.aggregate.command-handling-timer", "x"))
