"""Known-bad: task handles dropped on the floor."""
import asyncio


class Engine:
    def kick(self):
        asyncio.ensure_future(self._refresh())  # line 7: dropped

    def schedule(self, loop):
        loop.create_task(self._refresh())  # line 10: dropped (loop method)
