"""Known-good: every spawned task is retained, awaited, or supervised."""
import asyncio

from surge_tpu.common import BackgroundTask


class Engine:
    def __init__(self):
        self._tasks = set()
        self._loop_task = BackgroundTask(self._refresh, "engine-refresh")

    def kick(self):
        task = asyncio.ensure_future(self._refresh())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def start(self):
        self._loop_task.start()

    async def once(self):
        await asyncio.create_task(self._refresh())
