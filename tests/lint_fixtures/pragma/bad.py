"""Known-bad: a disable pragma without the required justification comment."""
import asyncio


class Engine:
    def kick(self):
        asyncio.ensure_future(self._go())  # surgelint: disable=orphan-task
