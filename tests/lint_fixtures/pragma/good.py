"""Known-good: a justified suppression — tallied, not failed."""
import asyncio


class Engine:
    def kick(self):
        asyncio.ensure_future(self._go())  # surgelint: disable=orphan-task  # teardown is fire-and-forget by design; stop() reaps it
