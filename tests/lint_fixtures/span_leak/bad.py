"""Known-bad: spans leaked — never finished, happy-path-only, discarded."""


class Engine:
    def __init__(self, tracer):
        self.tracer = tracer

    def never_finished(self):
        span = self.tracer.start_span("op")  # line 9: never finished
        span.set_attribute("k", 1)

    def happy_path_only(self, work):
        span = self.tracer.start_span("op")  # line 13: not on except paths
        work()
        span.finish()

    def discarded(self):
        self.tracer.start_span("op")  # line 18: result dropped on the floor
