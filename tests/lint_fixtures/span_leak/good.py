"""Known-good: context-managed, finally-finished, or escaping spans."""


class Engine:
    def __init__(self, tracer):
        self.tracer = tracer
        self.current = None

    def managed(self):
        with self.tracer.start_span("op") as span:
            span.set_attribute("k", 1)

    def finally_finished(self, work):
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span("op")
        try:
            work()
        finally:
            if span is not None:
                span.finish()

    def attrs_then_with(self):
        span = self.tracer.start_span("op")
        span.set_attribute("k", 1)
        with span:
            pass

    def escapes_return(self):
        span = self.tracer.start_span("op")
        return span, {"headers": True}

    def escapes_attribute(self):
        self.current = self.tracer.start_span("op")

    def escapes_argument(self, sink):
        span = self.tracer.start_span("op")
        sink(span)
