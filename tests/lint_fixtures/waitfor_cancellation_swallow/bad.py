"""Known-bad: bare asyncio.wait_for in a poll loop and on a task."""
import asyncio


class Poller:
    async def run(self):
        while True:
            await asyncio.wait_for(self._poll(), timeout=0.5)  # line 8: loop

    async def join(self):
        task = asyncio.create_task(self._poll())
        await asyncio.wait_for(task, timeout=1.0)  # line 12: on a task
