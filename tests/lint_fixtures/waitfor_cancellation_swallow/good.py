"""Known-good: the shield + re-cancel pattern (BackgroundTask.stop), and a
one-shot wait_for outside any loop."""
import asyncio


class Poller:
    async def stop(self, task):
        task.cancel()
        for _ in range(120):
            try:
                await asyncio.wait_for(asyncio.shield(task), timeout=0.25)
                return
            except asyncio.TimeoutError:
                task.cancel()

    async def ask(self, fut):
        return await asyncio.wait_for(fut, timeout=1.0)
