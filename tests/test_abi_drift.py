"""ABI-drift gate: csrc/*.cc exported C signatures vs every ctypes table.

The native loaders (store/native.py, log/segment.py, log/native_gate.py)
declare their ABI as signature tables; the C side declares it as
``extern "C"`` function definitions. The loader silently degrades when a
symbol is MISSING — but a symbol whose signature silently drifted (a param
added, a scalar became a pointer) would corrupt data rather than crash, so
this test parses the C sources and cross-checks, both directions:

- every ctypes-declared function exists in its .cc with the same parameter
  count and per-parameter pointer-ness, and a matching return kind;
- every exported C function is covered by its loader's table (a new export
  must be declared, or Python could call it un-prototyped).

Pure text analysis — runs (and gates) even when no compiler is present.
"""

from __future__ import annotations

import ctypes
import os
import re

import pytest

from surge_tpu.log.native_gate import TXN_SIGNATURES
from surge_tpu.log.segment import SEGMENT_SIGNATURES
from surge_tpu.store.native import STORE_SIGNATURES

CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "csrc")

#: loader table -> the .cc file whose extern "C" exports it binds
TABLES = [
    ("store/native.py STORE_SIGNATURES", STORE_SIGNATURES, "store.cc"),
    ("log/segment.py SEGMENT_SIGNATURES", SEGMENT_SIGNATURES, "segment.cc"),
    ("log/native_gate.py TXN_SIGNATURES", TXN_SIGNATURES, "txn.cc"),
]

_FN = re.compile(
    r"\n([A-Za-z_][\w :<>*&]*?)[ \t\n]+(surge_\w+)\s*\(([^)]*)\)\s*\{")


def _c_exports(filename: str):
    """{name: (return_kind, [param_kind, ...])} for every exported function
    DEFINITION in the file (prototypes — ``);`` — are not exports)."""
    with open(os.path.join(CSRC, filename)) as f:
        src = f.read()
    out = {}
    for ret, name, args in _FN.findall(src):
        params = []
        args = args.strip()
        if args and args != "void":
            for a in args.split(","):
                params.append("ptr" if "*" in a else "scalar")
        ret = ret.strip()
        kind = ("void" if ret == "void"
                else "ptr" if "*" in ret else "scalar")
        out[name] = (kind, params)
    return out


def _ctypes_kind(t) -> str:
    if t is None:
        return "void"
    if t in (ctypes.c_void_p, ctypes.c_char_p, ctypes.c_wchar_p):
        return "ptr"
    if isinstance(t, type) and issubclass(t, ctypes._Pointer):
        return "ptr"
    return "scalar"


@pytest.mark.parametrize("label,table,filename",
                         TABLES, ids=[t[2] for t in TABLES])
def test_ctypes_tables_match_c_signatures(label, table, filename):
    exports = _c_exports(filename)
    assert exports, f"no extern-C exports parsed from {filename}"
    for name, (argtypes, restype) in table.items():
        assert name in exports, (
            f"{label} declares {name} but {filename} does not define it")
        c_ret, c_params = exports[name]
        assert len(argtypes) == len(c_params), (
            f"{name}: ctypes declares {len(argtypes)} params, "
            f"{filename} defines {len(c_params)}")
        assert _ctypes_kind(restype) == c_ret, (
            f"{name}: ctypes restype kind {_ctypes_kind(restype)!r} vs "
            f"C return kind {c_ret!r}")
        for i, (a, c) in enumerate(zip(argtypes, c_params)):
            assert _ctypes_kind(a) == c, (
                f"{name} param {i}: ctypes {_ctypes_kind(a)!r} vs C {c!r}")


@pytest.mark.parametrize("label,table,filename",
                         TABLES, ids=[t[2] for t in TABLES])
def test_every_c_export_is_declared(label, table, filename):
    exports = _c_exports(filename)
    undeclared = sorted(set(exports) - set(table))
    assert not undeclared, (
        f"{filename} exports {undeclared} but {label} does not declare "
        "them — add signatures (the loader must never call un-prototyped)")


def test_issue12_exports_declared_both_sides():
    """The reply formatter, verbatim-ingest and reply-index exports this PR
    added must stay declared in the ctypes table AND defined in txn.cc (the
    generic both-direction check above then gates their param counts and
    pointer-ness) — a revert of either side fails loudly here."""
    exports = _c_exports("txn.cc")
    for sym in ("surge_txn_parse_packed_v", "surge_txn_group_base",
                "surge_txn_format_verbatim", "surge_reply_count",
                "surge_reply_index", "surge_reply_format"):
        assert sym in TXN_SIGNATURES, f"{sym} missing from TXN_SIGNATURES"
        assert sym in exports, f"{sym} missing from csrc/txn.cc"


def test_tables_bind_against_built_libraries():
    """When the libraries are built (conftest builds them when g++ exists),
    every declared symbol must actually resolve."""
    from surge_tpu.store.native import load_native_library

    libs = [("libsurge_store.so", STORE_SIGNATURES),
            ("libsurge_segment.so", SEGMENT_SIGNATURES),
            ("libsurge_txn.so", TXN_SIGNATURES)]
    missing = [n for n, _s in libs
               if not os.path.exists(os.path.join(CSRC, "build", n))]
    if missing:
        pytest.skip(f"native libraries not built: {missing} "
                    "(csrc/build.sh needs g++)")
    for name, sigs in libs:
        assert load_native_library(name, sigs) is not None, name
