"""Admin service: health/metrics introspection + restart/stop controls over gRPC
(the JMX MBean analog, surge/health/jmx/SurgeHealthActor.scala:20-132)."""

import asyncio

import grpc

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.admin import AdminClient, AdminServer
from surge_tpu.engine.pipeline import EngineStatus
from surge_tpu.models import counter

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 2,
})


def make_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


def test_admin_introspection_and_controls():
    async def scenario():
        engine = create_engine(make_logic(), config=CFG)
        await engine.start()
        await engine.aggregate_for("a-1").send_command(counter.Increment("a-1"))

        admin = AdminServer(engine)
        port = await admin.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        client = AdminClient(channel)

        health = await client.health()
        assert health["name"] == "counter" and health["status"] == "up"
        assert any(c["name"] == "router" for c in health["components"])

        metrics = await client.metrics()
        assert metrics["values"]["surge.engine.command-rate.one-minute-rate"] > 0
        assert "surge.aggregate.state-fetch-timer" in metrics["descriptions"]

        # OpenMetrics exposition over gRPC: typed families, EOF-terminated,
        # health counters joined in
        text = await client.metrics_text()
        assert text.endswith("# EOF\n")
        assert "# TYPE surge_engine_command_rate_one_minute_rate gauge" in text
        assert "surge_aggregate_command_handling_timer_ms_bucket" in text
        assert "# TYPE surge_health_signals counter" in text

        comps = await client.components()
        assert "state-store" in comps  # the engine registers its indexer

        ok, detail = await client.restart_component("state-store")
        assert ok, detail
        # restarted indexer still serves reads
        st = await engine.aggregate_for("a-1").get_state()
        assert st.count == 1
        # restart emitted the ComponentRestarted signal onto the bus
        assert any(s.name == "health.component-restarted"
                   for s in engine.health_bus.recent())

        ok, _ = await client.restart_component("no-such-thing")
        assert not ok

        # engine flight recorder over the admin plane: the command above
        # dispatched at least one group commit (lane.dispatch), and the
        # restart-driven health signal was tapped into the same ring
        dump = await client.flight_dump()
        types = [e["type"] for e in dump["events"]]
        assert "lane.dispatch" in types
        assert any(e["type"] == "health.signal"
                   and e["name"] == "health.component-restarted"
                   for e in dump["events"])
        assert dump["role"] == "engine"  # merges as the engine lane
        assert dump["stats"]["dropped"] == 0
        assert dump["stats"]["events"] == len(types)
        tail = await client.flight_dump(last=1)
        assert len(tail["events"]) == 1
        # ring occupancy + dropped count also ride the GetMetrics status
        assert (await client.metrics())["flight"]["capacity"] == 1024

        ok, detail = await client.stop_engine()
        assert ok and engine.status == EngineStatus.STOPPED
        await admin.stop()
        await channel.close()

    asyncio.run(scenario())


def test_admin_arm_faults_on_engine_log(tmp_path):
    """ArmFaults over the engine admin plane: arms the fault plane on the
    engine's in-process FileLog (WAL sites), reports stats, disarms."""
    from surge_tpu.log import FileLog

    async def scenario():
        log = FileLog(str(tmp_path / "log"), fsync="none")
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        admin = AdminServer(engine)
        port = await admin.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        client = AdminClient(channel)

        stats = await client.arm_faults("fsync-hiccup")
        assert stats["rules"][0]["site"] == "fsync.journal"
        assert log.faults is not None
        assert (await client.fault_stats())["rules"]
        stats = await client.disarm_faults()
        assert stats["rules"] == []

        await channel.close()
        await admin.stop()
        await engine.stop()
        log.close()

    asyncio.run(scenario())


def test_admin_dump_traces_round_trip():
    """DumpTraces over the engine admin plane (ISSUE 14): a traced command's
    tail-kept spans come back in the merge-ready envelope; an untraced
    engine answers an explicit error, not an empty ring."""
    import pytest

    from surge_tpu.tracing import Tracer

    async def scenario():
        tracer = Tracer(service="engine")
        cfg = CFG.with_overrides({"surge.trace.tail.latency-ms": 0})
        engine = create_engine(make_logic(), config=cfg, tracer=tracer)
        await engine.start()
        await engine.aggregate_for("a-1").send_command(counter.Increment("a-1"))
        await asyncio.sleep(0.05)

        admin = AdminServer(engine)
        port = await admin.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        client = AdminClient(channel)

        dump = await client.trace_dump()
        assert dump["role"] == "engine"
        assert dump["recorder"] == "engine:counter"
        names = {s["name"] for e in dump["traces"] for s in e["spans"]}
        # the whole command chain was tail-kept (latency threshold 0)
        assert {"aggregate-ref.ProcessMessage", "entity.ProcessMessage",
                "publisher.publish", "publisher.flush"} <= names
        # one command trace holds ref AND flush: the flush span parents on
        # the batch's first publish, keeping the trace contiguous
        by_tid = {}
        for e in dump["traces"]:
            for s in e["spans"]:
                by_tid.setdefault(e["trace_id"], set()).add(s["name"])
        assert any({"aggregate-ref.ProcessMessage", "publisher.flush"} <= ns
                   for ns in by_tid.values())
        tail = await client.trace_dump(last=1)
        assert len(tail["traces"]) == 1

        await engine.stop()
        await admin.stop()
        await channel.close()

        # untraced engine: explicit error, distinguishable from "nothing kept"
        engine2 = create_engine(make_logic(), config=CFG)
        await engine2.start()
        admin2 = AdminServer(engine2)
        port2 = await admin2.start()
        channel2 = grpc.aio.insecure_channel(f"127.0.0.1:{port2}")
        with pytest.raises(RuntimeError, match="no trace ring"):
            await AdminClient(channel2).trace_dump()
        await engine2.stop()
        await admin2.stop()
        await channel2.close()

    asyncio.run(scenario())


def test_admin_saga_rpcs_round_trip():
    """StartSaga / SagaStatus over the admin plane: start a transfer saga by
    RPC, poll its ledger to terminal, read the fleet summary with the
    reconciliation verdict — and get typed errors for an unknown definition
    and a clean 'unknown' status for a never-started id."""
    import time as _time

    import pytest
    from surge_tpu.log import InMemoryLog
    from surge_tpu.models.counter import Decrement, Increment
    from surge_tpu.saga import (SagaDefinition, SagaManager, SagaStep,
                                make_saga_logic)

    transfer = SagaDefinition(
        name="transfer", def_id=1,
        steps=(
            SagaStep("debit", participant="acct",
                     target=lambda sid, s: sid.split(":")[1],
                     command=lambda tid, s: Decrement(tid),
                     compensation=lambda tid, s: Increment(tid)),
            SagaStep("credit", participant="acct",
                     target=lambda sid, s: sid.split(":")[2],
                     command=lambda tid, s: Increment(tid),
                     compensation=lambda tid, s: Decrement(tid)),
        ))

    async def scenario():
        log = InMemoryLog()
        acct = create_engine(make_logic(), log=log, config=CFG)
        saga_cfg = CFG.with_overrides({"surge.saga.poll-interval-ms": 10})
        saga = create_engine(make_saga_logic(), log=log, config=saga_cfg)
        mgr = SagaManager(saga, [transfer],
                          {"acct": acct, "saga": saga}, config=saga_cfg)
        saga.register_saga_manager(mgr)
        await acct.start()
        await saga.start()
        admin = AdminServer(saga)
        port = await admin.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        client = AdminClient(channel)
        try:
            st = await client.start_saga("t:alice:bob:1", "transfer")
            assert st["saga_id"] == "t:alice:bob:1"
            deadline = _time.monotonic() + 20
            while st["status"] not in ("completed", "compensated",
                                       "dead-letter"):
                assert _time.monotonic() < deadline, st
                await asyncio.sleep(0.02)
                st = await client.saga_status("t:alice:bob:1")
            assert st["status"] == "completed"
            assert st["committed"] == [0, 1] and st["compensated"] == []

            summary = await client.saga_status()
            assert summary["ok"] and summary["total"] == 1
            assert summary["counts"]["completed"] == 1
            assert summary["violations"] == []

            assert (await client.saga_status("never-started"))["status"] \
                == "unknown"
            with pytest.raises(RuntimeError, match="unknown saga definition"):
                await client.start_saga("t:x:y:1", "no-such-definition")
        finally:
            await channel.close()
            await admin.stop()
            await saga.stop()
            await acct.stop()

    asyncio.run(scenario())
