"""Command anatomy (ISSUE 14): cross-process trace assembly under skewed
wall clocks, the critical-path leg attributor, the attribution table, and
the tools/trace_anatomy.py CLI smoke."""

import json
import os
import sys

from surge_tpu.observability.anatomy import (
    LEGS,
    assemble_traces,
    attribute_trace,
    attribution_table,
    dominant_leg,
)

TID = "a" * 32


def _span(name, span_id, parent, start_mono, end_mono, wall_skew,
          attrs=None, trace_id=TID):
    """A dump-shape span whose wall stamps are its host's (possibly wrong)
    clock: wall = mono + wall_skew AT RECORDING TIME."""
    return {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent,
            "start_mono": start_mono, "end_mono": end_mono,
            "start_wall": start_mono + wall_skew,
            "end_wall": end_mono + wall_skew,
            "duration_ms": (end_mono - start_mono) * 1000.0,
            "status": "ok", "attributes": attrs or {}, "events": []}


def _dump(role, recorder, spans, offset, node):
    """Envelope whose header pair encodes the host's TRUE mono→wall offset
    (stamped at dump time, after any mid-incident wall step healed)."""
    return {"recorder": recorder, "node": node, "pid": 1, "role": role,
            "stats": {}, "dumped_wall": 2000.0 + offset,
            "dumped_mono": 2000.0,
            "traces": [{"trace_id": TID, "reason": "latency",
                        "spans": spans}]}


def three_host_dumps():
    """One command trace across 3 hosts. True engine-host offset is +1000;
    broker B1's wall clock was 600s BEHIND while its spans recorded (raw
    wall stamps land before every engine span), broker B2's was 300s ahead.
    Raw wall ordering would put B1's fsync-carrying span FIRST — before the
    command even started; the mono↔wall header estimation must restore the
    true order."""
    e = [
        _span("aggregate-ref.ProcessMessage", "e1", None, 10.00, 10.50, 1000),
        _span("entity.ProcessMessage", "e2", "e1", 10.05, 10.45, 1000),
        _span("publisher.publish", "e3", "e2", 10.10, 10.44, 1000),
        _span("publisher.flush", "e4", "e3", 10.12, 10.43, 1000),
        _span("log.Transact", "e5", "e4", 10.13, 10.20, 1000),
        _span("log.Transact", "e6", "e4", 10.21, 10.42, 1000),
    ]
    # B1: wall clock 600s BEHIND while recording (raw wall ≈ -569, sorts
    # before the whole command); the header's true offset +980 maps its
    # mono 30.14 to est wall 1010.14 — inside the FIRST client call
    b1 = [_span("log.server.transact", "b1", "e5", 30.14, 30.19, -600,
                attrs={"leg.gate-wait-ms": 2.0})]
    # B2: wall clock ~690s AHEAD while recording (raw wall ≈ 1700, sorts
    # after everything); the header's true offset +1310 maps its mono
    # -299.78 to est wall 1010.22 — inside the SECOND client call
    b2 = [_span("log.server.transact", "b2", "e6", -299.78, -299.60, 2000,
                attrs={"leg.fsync-ms": 150.0, "leg.repl-ms": 20.0})]
    return [
        _dump("engine", "engine:test", e, 1000.0, "host-e"),
        _dump("broker", "127.0.0.1:16001", b1, 980.0, "host-b1"),
        _dump("broker", "127.0.0.1:16002", b2, 1310.0, "host-b2"),
    ]


def test_skewed_clock_assembly_restores_true_order():
    dumps = three_host_dumps()
    # the trap is real: raw wall order puts both broker spans BEFORE the
    # engine's root (B1 600s behind) / after everything (B2 300s ahead)
    raw = sorted((s for d in dumps for e in d["traces"]
                  for s in e["spans"]), key=lambda s: s["start_wall"])
    assert raw[0]["name"] == "log.server.transact"
    assert raw[-1]["name"] == "log.server.transact"
    traces = assemble_traces(dumps)
    spans = traces[TID]
    order = [s["span_id"] for s in spans]
    # estimated-wall placement: each broker span sits inside its client call
    assert order == ["e1", "e2", "e3", "e4", "e5", "b1", "e6", "b2"]
    assert spans[5]["recorder"] == "127.0.0.1:16001"
    assert spans[5]["lane"] == "broker"
    assert spans[0]["keep_reason"] == "latency"


def test_attributor_names_the_fsync_leg_despite_the_skew():
    traces = assemble_traces(three_host_dumps())
    row = attribute_trace(traces[TID])
    legs = row["legs"]
    assert row["duration_ms"] == 500.0
    assert legs["journal-fsync"] == 150.0        # measured broker attr
    assert legs["replication-ack"] == 20.0
    assert legs["gate-wait"] == 2.0
    assert legs["mailbox-wait"] == 50.0          # entity - root start
    assert legs["publisher-linger"] == 20.0      # flush - publish start
    assert legs["lane-dispatch"] == 10.0         # first call - flush start
    assert all(v >= 0.0 for v in legs.values())
    # legs are self-times on the critical path: they sum to the root
    assert abs(sum(legs.values()) - row["duration_ms"]) < 1e-6
    assert row["dominant"] == "journal-fsync"


def test_attribution_table_aggregates_and_filters_poll_traces():
    dumps = three_host_dumps()
    # a kept read-poll trace (one bare client span): must not dilute legs
    poll = _span("log.Read", "p1", None, 50.0, 50.3, 1000, trace_id="b" * 32)
    dumps[0]["traces"].append({"trace_id": "b" * 32, "reason": "latency",
                               "spans": [poll]})
    table = attribution_table(assemble_traces(dumps))
    assert table["traces"] == 1                  # the command trace only
    assert list(table["legs"]) == list(LEGS)
    assert table["dominant"] == "journal-fsync"
    assert table["dominant_share"] > 0.25
    assert table["slowest"][0]["trace_id"] == TID
    # opting in to everything includes the poll trace
    assert attribution_table(assemble_traces(dumps),
                             command_only=False)["traces"] == 2
    verdict = dominant_leg(dumps)
    assert verdict == {"dominant": "journal-fsync",
                       "dominant_share": table["dominant_share"],
                       "traces": 1}


def test_router_resolve_leg_is_self_time_not_double_counted():
    """router.resolve nests UNDER router.commit (and client calls under
    both): the leg must be router SELF-time — overlapped nested intervals
    subtracted once, never double-counted past the root duration."""
    spans = [
        _span("aggregate-ref.ProcessMessage", "r", None, 0.0, 0.2, 0),
        _span("router.commit", "rc", "r", 0.0, 0.1, 0),
        _span("router.resolve", "rr", "rc", 0.01, 0.05, 0),
        _span("log.Transact", "ct", "rc", 0.05, 0.10, 0),
    ]
    dump = _dump("engine", "e", spans, 0.0, "host-e")
    row = attribute_trace(assemble_traces([dump])[TID])
    # commit self (100-40-50=10) + resolve self (40) = 50ms of router work
    assert row["legs"]["router-resolve"] == 50.0
    assert sum(row["legs"].values()) <= row["duration_ms"] + 1e-6


def test_assembly_timer_records_on_the_fleet_quiver():
    from surge_tpu.metrics.fleet import fleet_metrics

    fm = fleet_metrics()
    attribution_table(assemble_traces(three_host_dumps()), metrics=fm)
    values = fm.registry.get_metrics()
    assert values["surge.trace.assembly-timer.max"] >= 0.0


def test_legacy_dump_without_header_pair_falls_back_to_wall():
    dumps = three_host_dumps()
    for d in dumps:
        d.pop("dumped_wall")
        d.pop("dumped_mono")
    spans = assemble_traces(dumps)[TID]
    # raw-wall fallback: the skewed B1 span now sorts first — documented
    # legacy behavior, which is exactly why the header pair exists
    assert spans[0]["span_id"] == "b1"


def test_trace_anatomy_cli_json_smoke(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace_anatomy

    paths = []
    for i, d in enumerate(three_host_dumps()):
        p = tmp_path / f"dump{i}.json"
        p.write_text(json.dumps(d))
        paths.append(str(p))
    rc = trace_anatomy.main(paths + ["--once", "--format=json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["traces"] == 1
    assert out["dominant"] == "journal-fsync"
    assert out["legs"]["journal-fsync"]["total_ms"] == 150.0
    assert out["sources"] == 3 and out["errors"] == []
    # the human table renders too
    rc = trace_anatomy.main(paths)
    assert rc == 0
    text = capsys.readouterr().out
    assert "dominant leg: journal-fsync" in text
    assert "slowest kept traces:" in text
