"""The consistency observatory (ISSUE 20): chained log digests, the
ConsistencyAuditor's three probes, and the corruption-to-page pipeline.

The load-bearing tests are the two acceptance e2es — an armed
`corrupt.slab-row` bit-flip and an armed `corrupt.segment-payload` replica
rot are each detected within 3 audit cycles, burn the `state-divergence`
SLO, stamp an `audit.divergence` flight event, and `chaos.py audit
--format=json` names the divergent aggregate / partition — and the
no-false-positive soak: a no-fault leader+followers cluster under write
load, kill-failover and evict/re-admit churn runs 20+ audit cycles with
zero findings."""

import asyncio
import json
import os
import sys
import tempfile
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_resident_state import (  # noqa: E402
    NPART,
    TOPIC,
    Expected,
    append_events,
    make_log,
    make_plane,
    wait_caught_up,
)

from surge_tpu.config import Config, default_config  # noqa: E402
from surge_tpu.log import (  # noqa: E402
    FileLog,
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.observability.audit import ConsistencyAuditor  # noqa: E402
from surge_tpu.observability.flight import FlightRecorder  # noqa: E402
from surge_tpu.observability.slo import DEFAULT_SLOS, SLOEngine  # noqa: E402
from surge_tpu.testing.faults import NAMED_PLANS, FaultPlane  # noqa: E402


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


def _commit(log, records, txn_id="seed"):
    p = log.transactional_producer(txn_id)
    p.begin()
    for r in records:
        p.send(r)
    p.commit()


def audit_config(**extra) -> Config:
    return default_config().with_overrides({
        "surge.audit.cohort-size": 64,  # whole slab per cycle by default
        **extra})


# -- chained digests (log/digest.py) --------------------------------------------------


def test_digest_is_backend_and_path_independent():
    """The chain covers (offset, key, value) only — the same commits produce
    the SAME digest on InMemoryLog and FileLog, queried in one shot or
    incrementally, so leader and follower are comparable byte-for-byte."""
    recs = [rec("events", f"k{i}", b"v%d" % i, partition=i % 2)
            for i in range(20)]
    mem = InMemoryLog()
    mem.create_topic(TopicSpec("events", 2))
    _commit(mem, recs)
    one_shot = mem.partition_digest("events", 0)
    assert one_shot["digest"] is not None and one_shot["base"] == 0

    with tempfile.TemporaryDirectory() as root:
        flog = FileLog(root, fsync="none")
        flog.create_topic(TopicSpec("events", 2))
        # incremental arm: digest queried between commits, so the chain is
        # maintained (checkpointed head), never recomputed from offset 0
        for i, r in enumerate(recs):
            _commit(flog, [r], txn_id=f"t{i}")
            flog.partition_digest("events", r.partition)
        for p in (0, 1):
            assert flog.partition_digest("events", p) == \
                mem.partition_digest("events", p)
        flog.close()


def test_digest_maintenance_is_incremental_not_a_rescan():
    """Acceptance: no full-segment rescan per cycle. After the first query
    establishes the chain, each following query folds ONLY the delta —
    the cumulative records folded never exceeds the records appended."""
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 1))
    total = 0
    for i in range(10):
        _commit(log, [rec("events", f"k{i}", b"x" * 64)], txn_id=f"t{i}")
        total += 1
        log.partition_digest("events", 0)
    stats = log._digests.snapshot()["stats"]
    folded = (stats["eager_records"] + stats["catchup_records"]
              + stats["refold_records"])
    assert folded <= total, stats  # a rescan per query would be ~N^2/2


def test_digest_same_offset_compare_and_rot_detection():
    """Identical prefixes agree at the same upto even when the logs have
    different tails; a differing byte at the same offsets flips the
    digest."""
    a, b = InMemoryLog(), InMemoryLog()
    for log in (a, b):
        log.create_topic(TopicSpec("events", 1))
    shared = [rec("events", f"k{i}", b"v%d" % i) for i in range(8)]
    _commit(a, shared)
    _commit(b, shared[:6])  # b lags: compare at the common prefix
    assert a.partition_digest("events", 0, upto=6) == \
        b.partition_digest("events", 0, upto=6)
    # one rotted byte at the same offsets → different digest
    c = InMemoryLog()
    c.create_topic(TopicSpec("events", 1))
    rotted = list(shared)
    rotted[3] = rec("events", "k3", b"vX")
    _commit(c, rotted)
    assert c.partition_digest("events", 0, upto=6)["digest"] != \
        a.partition_digest("events", 0, upto=6)["digest"]


def test_partition_digest_rpc_round_trip():
    """The PartitionDigest RPC: leader and replicating follower answer the
    SAME digest at the same below-hwm offset — two CRCs cross the wire,
    never records."""
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    log = GrpcLogTransport(f"127.0.0.1:{lport}")
    flog = GrpcLogTransport(f"127.0.0.1:{fport}")
    try:
        log.create_topic(TopicSpec("events", 2))
        _commit(log, [rec("events", f"k{i}", b"v%d" % i, partition=i % 2)
                      for i in range(10)])
        for p in (0, 1):
            upto = log.high_watermark("events", p)
            ld = log.partition_digest("events", p, upto=upto)
            fd = flog.partition_digest("events", p, upto=upto)
            assert ld == fd and ld["digest"] is not None
            assert ld["upto"] == upto
    finally:
        log.close()
        flog.close()
        leader.stop()
        follower.stop()


# -- shadow replay --------------------------------------------------------------------


def _seeded_plane_and_events(n_aggs=12, **plane_kw):
    log = make_log()
    exp = Expected()
    events = []
    for i in range(n_aggs):
        events += exp.events(f"agg-{i}", 5 + i)
    append_events(log, events)
    plane = make_plane(log, partitions=range(NPART), **plane_kw)
    return log, plane, exp


def test_shadow_replay_clean_plane_full_rotation_no_findings():
    """Every resident aggregate byte-matches its from-scratch refold; the
    rotation covers the whole slab; the dedup probe reports unsupported on
    the in-memory transport (no wire seq gate), never a hole."""
    log, plane, _ = _seeded_plane_and_events()

    async def scenario():
        await plane.start()
        try:
            await wait_caught_up(plane)
            aud = ConsistencyAuditor(
                plane, log=log, config=audit_config(**{
                    "surge.audit.cohort-size": 5}))
            for _ in range(5):
                out = await aud.cycle()
                assert out["divergent"] == [] and out["unverifiable"] == 0
                assert out["dedup"] == "unsupported"
            # rotation: 5 cycles x 5 ≥ 12 residents → every agg audited
            assert aud.stats["cohort_rows"] == 25
            assert aud.summary()["ok"] and aud.unresolved == {}
            assert aud.health_component().status == "up"
        finally:
            await plane.stop()

    asyncio.run(scenario())


def _burn_state_divergence(gauge_value: float):
    """Feed the `state-divergence` DEFAULT_SLOS entry a sustained nonzero
    `surge_audit_unresolved_divergences` gauge through the real burn-rate
    engine (fast windows) and return the breached status rows."""
    from surge_tpu.metrics.exposition import Family, Sample

    slo = next(s for s in DEFAULT_SLOS if s.name == "state-divergence")
    eng = SLOEngine([slo], config=Config(overrides={
        "surge.slo.fast-window-ms": 10_000,
        "surge.slo.slow-window-ms": 40_000,
        "surge.slo.burn-threshold": 2.0}))

    def fams(v):
        fam = Family(name=slo.family, mtype="gauge", help="")
        fam.samples.append(Sample("", (("instance", "e"),), float(v)))
        return {slo.family: fam}

    breaches = []
    for t in range(0, 60, 5):  # clean history, then the sustained finding
        eng.evaluate(fams(0.0), now=float(t))
    for t in range(60, 120, 5):
        breaches += [r for r in eng.evaluate(fams(gauge_value),
                                             now=float(t))
                     if r.get("breached")]
    return breaches


def _chaos_audit_verdict(auditor):
    """Run the REAL `chaos.py audit --format=json` against an AdminServer
    wrapping this auditor; returns (exit_code, machine-readable last line).
    The admin server lives on a background-thread loop because the CLI
    spins its own asyncio.run."""
    import contextlib
    import io
    from types import SimpleNamespace

    from surge_tpu.admin import AdminServer

    tools = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import chaos

    admin = AdminServer(SimpleNamespace(audit_status=auditor.summary))
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    try:
        port = asyncio.run_coroutine_threadsafe(
            admin.start(), loop).result(timeout=10)
        result = {}

        def run_cli():  # chaos.main spins asyncio.run — needs its own thread
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                result["code"] = chaos.main(
                    ["audit", f"127.0.0.1:{port}", "--format=json"])
            result["out"] = buf.getvalue()

        cli = threading.Thread(target=run_cli)
        cli.start()
        cli.join(timeout=30)
        code = result["code"]
        tail = json.loads(result["out"].strip().splitlines()[-1])
        asyncio.run_coroutine_threadsafe(admin.stop(), loop).result(
            timeout=10)
        return code, tail
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        loop.close()


def test_slab_corruption_to_page_e2e():
    """Acceptance arm 1: an armed `corrupt.slab-row` bit-flip (the log stays
    right, the slab lies) is detected within 3 audit cycles; the finding
    names the aggregate + differing fields, stamps `audit.divergence` on the
    flight ring, burns the `state-divergence` SLO to a breach, degrades (not
    downs) the health component, and `chaos.py audit --format=json` exits 1
    naming the aggregate. Re-folding the aggregate from the log (rebalance
    revoke + re-grant) resolves the finding and clears the verdict."""
    flight = FlightRecorder(name="engine:audit", role="engine")
    log, plane, exp = _seeded_plane_and_events(
        overrides={"surge.replay.resident.refresh-interval-ms": 5},
        flight=flight)
    plane._faults = FaultPlane(NAMED_PLANS["corrupt.slab-row"]())

    async def scenario():
        await plane.start()
        try:
            await wait_caught_up(plane)
            aud = ConsistencyAuditor(plane, log=log, config=audit_config(),
                                     flight=flight)
            # one more event lands → the next refresh round commits, then
            # the armed site fires and rots one LIVE row
            append_events(log, exp.events("agg-0", 1))
            deadline = asyncio.get_running_loop().time() + 10
            while not any(e["type"] == "fault.corrupt"
                          for e in flight.events()):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            corrupted = next(e for e in flight.events()
                             if e["type"] == "fault.corrupt")["aggregate"]
            findings = []
            for _ in range(3):  # acceptance: detected within 3 cycles
                findings = (await aud.cycle())["divergent"]
                if findings:
                    break
            assert [f["aggregate"] for f in findings] == [corrupted]
            assert findings[0]["fields"], "divergence must name the fields"
            assert not aud.summary()["ok"]
            assert aud.health_component().status == "degraded"
            div = [e for e in flight.events()
                   if e["type"] == "audit.divergence"]
            assert div and div[0]["aggregate"] == corrupted

            # the gauge drives the SLO engine to a sustained-burn page
            breaches = _burn_state_divergence(len(aud.unresolved))
            assert breaches
            assert breaches[0]["objective"] == "state-divergence"

            # chaos.py audit --format=json: exit 1, names the aggregate
            rc, tail = _chaos_audit_verdict(aud)
            assert rc == 1
            assert not tail["ok"]
            assert any(corrupted in item["key"]
                       for item in tail["unresolved"])

            # revoke + re-grant refolds the aggregate from the (good) log;
            # the next rotation re-verifies clean and resolves the finding
            plane.set_partitions([])
            plane.set_partitions([0, 1, 2, 3])
            await wait_caught_up(plane)
            out = await aud.cycle()
            assert out["divergent"] == []
            assert aud.summary()["ok"]
            assert [e["type"] for e in flight.events()].count(
                "audit.resolved") == 1
            rc, tail = _chaos_audit_verdict(aud)
            assert rc == 0 and tail["ok"]
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_verdict_fence_discards_stale_findings():
    """A re-anchor (rebalance / re-admit) racing the in-flight refold must
    discard the verdict — even a REAL divergence is withheld until it can be
    re-verified against stable ground truth, so churn can never page."""
    log, plane, _ = _seeded_plane_and_events()

    async def scenario():
        await plane.start()
        try:
            await wait_caught_up(plane)
            assert plane._corrupt_resident_row() is not None
            aud = ConsistencyAuditor(plane, log=log, config=audit_config())
            real_verify = aud._shadow_verify

            def racing_verify(pulled, part_of, wms):
                out = real_verify(pulled, part_of, wms)
                for p in range(NPART):  # re-anchor mid-flight
                    plane._anchor_gen[p] = plane._anchor_gen.get(p, 0) + 1
                return out

            aud._shadow_verify = racing_verify
            out = await aud.cycle()
            assert out["divergent"] == [] and aud.summary()["ok"]
            # ...and with stable anchors the same divergence IS reported
            aud._shadow_verify = real_verify
            out = await aud.cycle()
            assert len(out["divergent"]) == 1
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- digest audit + replica corruption e2e --------------------------------------------


def test_segment_corruption_to_page_e2e():
    """Acceptance arm 2: an armed `corrupt.segment-payload` rot during
    replica verbatim ingest is a silent below-hwm divergence no read path
    touches — the auditor's cross-replica digest compare flags the partition
    within 3 cycles (each replica's CRC in the finding), the flight timeline
    names it, and `chaos.py audit --format=json` exits 1 naming the
    partition. The probe producer's same-seq replay reports REPLAY (healthy
    dedup window) throughout."""
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    log = GrpcLogTransport(f"127.0.0.1:{lport}")
    flog = GrpcLogTransport(f"127.0.0.1:{fport}")
    flight = FlightRecorder(name="engine:audit", role="engine")
    try:
        log.create_topic(TopicSpec("events", 2))
        _commit(log, [rec("events", f"k{i}", b"v%d" % i, partition=i % 2)
                      for i in range(10)])

        async def scenario():
            aud = ConsistencyAuditor(None, log=log, config=audit_config(),
                                     flight=flight)
            aud.add_digest_peer("leader", log)
            aud.add_digest_peer("follower", flog)
            aud.set_digest_targets([("events", 0), ("events", 1)])
            out = await aud.cycle()
            assert out["digest_compared"] == 2
            assert out["digest_mismatches"] == []
            assert out["dedup"] == "replayed"  # the real gate REPLAYs

            # arm the follower's ingest rot; the next commit diverges below
            # the hwm on exactly one replica
            follower.faults = FaultPlane(
                NAMED_PLANS["corrupt.segment-payload"]())
            _commit(log, [rec("events", "rot", b"victim")], txn_id="t2")
            mismatches = []
            for _ in range(3):  # acceptance: detected within 3 cycles
                mismatches = (await aud.cycle())["digest_mismatches"]
                if mismatches:
                    break
            assert [m["partition"] for m in mismatches] == [0]
            assert set(mismatches[0]["digests"]) == {"leader", "follower"}
            assert len(set(mismatches[0]["digests"].values())) == 2
            assert not aud.summary()["ok"]
            div = [e for e in flight.events()
                   if e["type"] == "audit.divergence"]
            assert div and div[0]["partition"] == 0

            breaches = _burn_state_divergence(len(aud.unresolved))
            assert breaches
            assert breaches[0]["objective"] == "state-divergence"

            rc, tail = _chaos_audit_verdict(aud)
            assert rc == 1
            assert any(item["key"][:1] == ["digest"] and "0" in item["key"]
                       for item in tail["unresolved"])

        asyncio.run(scenario())
    finally:
        log.close()
        flog.close()
        leader.stop()
        follower.stop()


def test_digest_audit_skips_unreachable_peer():
    """A dead peer is liveness, never a divergence finding: the target is
    skipped this cycle and nothing lands in the unresolved ledger."""
    a = InMemoryLog()
    a.create_topic(TopicSpec("events", 1))
    _commit(a, [rec("events", f"k{i}", b"v%d" % i) for i in range(6)])

    class Dead:
        def end_offset(self, t, p):
            raise ConnectionError("unreachable")

        def partition_digest(self, t, p, upto=None):
            raise ConnectionError("unreachable")

    async def scenario():
        aud = ConsistencyAuditor(None, log=a, config=audit_config())
        aud.add_digest_peer("a", a)
        aud.add_digest_peer("dead", Dead())
        aud.set_digest_targets([("events", 0)])
        out = await aud.cycle()
        assert out["digest_compared"] == 0
        assert out["digest_mismatches"] == [] and aud.summary()["ok"]

    asyncio.run(scenario())


# -- dedup probe ----------------------------------------------------------------------


class _HoleyProducer:
    """A gate whose dedup window 'forgets': replay re-appends fresh."""

    def __init__(self):
        self.off = 0

    def begin(self):
        pass

    def send(self, r):
        self._rec = r

    def commit(self):
        self.off += 1
        return [LogRecord(topic=self._rec.topic, key=self._rec.key,
                          value=self._rec.value, offset=self.off)]

    def replay_commit(self, records, seq=None):
        return self.commit()  # ACCEPTED: fresh offsets — the hole


class _HealedProducer(_HoleyProducer):
    """The reference gate: replay answers the CACHED original ack."""

    def commit(self):
        self._acked = super().commit()
        return self._acked

    def replay_commit(self, records, seq=None):
        return self._acked


def test_dedup_probe_hole_detection_and_resolution():
    """A replay answered with FRESH offsets (instead of the dedup window's
    cached reply) is an exactly-once hole: counted, paged, and resolved
    when a later probe REPLAYs."""

    class HoleyLog:
        def topic(self, name):
            return None

        def transactional_producer(self, txn_id):
            return _HoleyProducer()

    async def scenario():
        aud = ConsistencyAuditor(None, log=HoleyLog(),
                                 config=audit_config())
        out = await aud.cycle()
        assert out["dedup"] == "hole"
        assert aud.stats["dedup_holes"] == 1
        assert not aud.summary()["ok"]
        assert ("dedup", "probe") in aud.unresolved
        # the gate heals (restarted broker restored dedup state): the next
        # probe replays its seq and the finding resolves
        aud._probe_producer = _HealedProducer()
        out = await aud.cycle()
        assert out["dedup"] == "replayed"
        assert aud.summary()["ok"] and aud.unresolved == {}

    asyncio.run(scenario())


# -- the no-false-positive soak -------------------------------------------------------


def test_churn_soak_no_false_positives():
    """Acceptance: a NO-FAULT cluster — leader + 2 replicating followers
    under continuous write load, a mid-soak leader kill-failover, and a
    capacity-starved resident plane churning evict/re-admit every round —
    runs 20+ audit cycles with ZERO findings of any kind. Every fence,
    skip and incomparable rule earns its keep here."""
    f1, f2 = LogServer(InMemoryLog()), LogServer(InMemoryLog())
    p1, p2 = f1.start(), f2.start()
    leader = LogServer(InMemoryLog(),
                       replicate_to=[f"127.0.0.1:{p1}",
                                     f"127.0.0.1:{p2}"])
    lport = leader.start()
    log = GrpcLogTransport(
        f"127.0.0.1:{lport},127.0.0.1:{p1},127.0.0.1:{p2}")
    c1 = GrpcLogTransport(f"127.0.0.1:{p1}")
    c2 = GrpcLogTransport(f"127.0.0.1:{p2}")
    try:
        log.create_topic(TopicSpec(TOPIC, NPART))
        exp = Expected()

        async def ship(n_aggs=16, per=1):
            events = []
            for i in range(n_aggs):
                events += exp.events(f"agg-{i}", per)
            for attempt in range(5):
                try:
                    append_events(log, events)
                    return
                except Exception:  # noqa: BLE001 — failover window retry
                    if attempt == 4:
                        raise
                    await asyncio.sleep(0.1)

        plane = make_plane(log, capacity=8,  # 16 aggs → evict/re-admit
                           partitions=range(NPART),
                           overrides={
                               "surge.replay.resident"
                               ".refresh-interval-ms": 5})

        async def scenario():
            await ship(per=3)
            await plane.start()
            try:
                await wait_caught_up(plane)
                aud = ConsistencyAuditor(
                    plane, log=log, config=audit_config(**{
                        "surge.audit.cohort-size": 4}))
                aud.add_digest_peer("leader", log)
                aud.add_digest_peer("f1", c1)
                aud.add_digest_peer("f2", c2)
                aud.set_digest_targets(
                    [(TOPIC, p) for p in range(NPART)])
                cycles = 0
                for round_ in range(24):
                    await ship(per=1)  # load + evict/re-admit churn
                    if round_ == 10:
                        # kill-failover mid-soak: the auditor must not
                        # mistake the roll / re-anchor for divergence
                        leader.stop()
                        c1.promote_follower(
                            replicate_to=[f"127.0.0.1:{p2}"])
                        await asyncio.sleep(0.1)
                    await aud.cycle()
                    cycles += 1
                    await asyncio.sleep(0.02)
                assert cycles >= 20
                s = aud.stats
                assert s["divergent_rows"] == 0, s
                assert s["digest_mismatches"] == 0, s
                assert s["dedup_holes"] == 0, s
                assert aud.summary()["ok"] and aud.unresolved == {}, \
                    aud.summary()
                assert s["cohort_rows"] > 0  # the soak audited real rows
            finally:
                await plane.stop()

        asyncio.run(scenario())
    finally:
        log.close()
        c1.close()
        c2.close()
        for srv in (leader, f1, f2):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — leader already killed
                pass


# -- lifecycle / wiring ---------------------------------------------------------------


def test_auditor_lifecycle_loop_and_admin_status():
    """start()/stop() run the supervised loop on the engine loop; the
    AuditStatus admin RPC serves the verdict and a disabled engine is a
    clean client-side error."""
    log, plane, _ = _seeded_plane_and_events(n_aggs=4)

    async def scenario():
        await plane.start()
        try:
            await wait_caught_up(plane)
            aud = ConsistencyAuditor(
                plane, log=log, config=audit_config(**{
                    "surge.audit.interval-ms": 10}))
            await aud.start()
            assert aud.running
            deadline = asyncio.get_running_loop().time() + 10
            while aud.stats["cycles"] < 3:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            await aud.stop()
            assert not aud.running
            frozen = aud.stats["cycles"]
            await asyncio.sleep(0.05)
            assert aud.stats["cycles"] == frozen  # loop actually stopped

            # AuditStatus RPC round trip + the not-enabled error path
            from types import SimpleNamespace

            import grpc

            from surge_tpu.admin import AdminClient, AdminServer

            admin = AdminServer(SimpleNamespace(audit_status=aud.summary))
            port = await admin.start()
            try:
                channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
                out = await AdminClient(channel).audit_status()
                assert out["ok"] and out["stats"]["cycles"] == frozen
                await channel.close()
            finally:
                await admin.stop()

            def disabled():
                raise RuntimeError("consistency auditor not enabled")

            bare = AdminServer(SimpleNamespace(audit_status=disabled))
            bare_port = await bare.start()
            try:
                ch2 = grpc.aio.insecure_channel(f"127.0.0.1:{bare_port}")
                with pytest.raises(RuntimeError, match="not enabled"):
                    await AdminClient(ch2).audit_status()
                await ch2.close()
            finally:
                await bare.stop()
        finally:
            await plane.stop()

    asyncio.run(scenario())


def test_engine_constructs_and_supervises_auditor():
    """surge.audit.enabled wires a ConsistencyAuditor into the engine:
    constructed with the plane, digest targets defaulted to the events
    topic, started under supervision, reported in health_check, stopped
    with the engine."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine
    from surge_tpu.models import counter

    logic = SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())
    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 2,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        "surge.replay.resident.enabled": True,
        "surge.replay.resident.refresh-interval-ms": 20,
        "surge.audit.enabled": True,
        "surge.audit.interval-ms": 50,
    })

    async def scenario():
        engine = create_engine(logic, config=cfg)
        assert engine.auditor is not None
        assert engine.auditor._digest_targets  # defaulted to events topic
        await engine.start()
        try:
            assert "consistency-auditor" in \
                engine.health_supervisor.registered()
            h = engine.health_check()
            assert any(c.name == "consistency-audit" and c.status == "up"
                       for c in h.components)
            assert engine.audit_status()["running"]
        finally:
            await engine.stop()
        assert not engine.auditor.running

    asyncio.run(scenario())
