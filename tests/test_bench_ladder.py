"""Smoke test for bench.py's SURGE_BENCH_LADDER=1 fast path: the command-path
throughput ladder must be regenerable WITHOUT the 100M-event corpus build, and
its JSON payload must carry the keys the BENCH artifact (and the driver's
last-line-wins parse) depend on."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_ladder_fast_path_emits_expected_json():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_LADDER": "1",
        "SURGE_BENCH_LATENCY_SECONDS": "0.4",
        "SURGE_BENCH_LATENCY_LADDER": "8",
        "SURGE_BENCH_SWEEP": "0",  # the sweep has its own knobs; smoke stays fast
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])  # last line wins for the driver
    for key in ("metric", "value", "unit", "commands_per_sec",
                "command_p50_ms", "command_p99_ms", "peak_commands_per_sec",
                "throughput_ladder", "linger_ms", "max_in_flight",
                "producer_stats"):
        assert key in payload, f"{key} missing from the ladder payload"
    assert payload["metric"] == "commands_per_sec"
    assert payload["value"] == payload["peak_commands_per_sec"] > 0
    rung = payload["throughput_ladder"][0]
    assert rung["workers"] == 8
    assert rung["commands"] > 0 and rung["commands_per_txn"] >= 1
    # the corpus phases really were skipped
    assert "num_events" not in payload and "cpu_baseline_events_per_sec" not in payload


def test_bench_native_paired_ladder_smoke():
    """SURGE_BENCH_NATIVE=1: the paired interleaved native-on/native-off
    ladder (the r07 protocol) emits per-rung medians for BOTH arms plus a
    speedup ratio, tiny-sized here."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_LADDER": "1",
        "SURGE_BENCH_NATIVE": "1",
        "SURGE_BENCH_NATIVE_ROUNDS": "1",
        "SURGE_BENCH_LATENCY_SECONDS": "0.3",
        "SURGE_BENCH_LATENCY_LADDER": "8",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    paired = payload["native_paired_ladder"]
    assert paired["protocol"]["interleaved"] and paired["protocol"]["medians"]
    (rung,) = paired["rungs"]
    assert rung["workers"] == 8
    for arm in ("native_on", "native_off"):
        assert rung[arm]["commands_per_sec_median"] > 0
        assert rung[arm]["rounds"]
    assert rung["speedup_median"] > 0
    assert payload["value"] == rung["native_on"]["commands_per_sec_median"]


def test_bench_lane_paired_ladder_smoke():
    """SURGE_BENCH_LANE=1 (the r08 protocol): the paired interleaved
    direct-vs-classic command-lane ladder emits per-rung medians for both
    arms plus a speedup ratio, tiny-sized here (inproc only for speed)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_LADDER": "1",
        "SURGE_BENCH_LANE": "1",
        "SURGE_BENCH_LANE_ROUNDS": "1",
        "SURGE_BENCH_LANE_BROKERS": "inproc",
        "SURGE_BENCH_LATENCY_SECONDS": "0.3",
        "SURGE_BENCH_LATENCY_LADDER": "8",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    paired = payload["lane_paired_ladder"]
    assert paired["protocol"]["interleaved"] and paired["protocol"]["medians"]
    (rung,) = paired["ladders"]["inproc"]
    assert rung["workers"] == 8
    for arm in ("direct", "classic"):
        assert rung[arm]["commands_per_sec_median"] > 0
        assert rung[arm]["rounds"]
    assert rung["speedup_median"] > 0
    assert payload["value"] == rung["direct"]["commands_per_sec_median"]


def test_bench_mesh_paired_ladder_smoke():
    """SURGE_BENCH_MESH=1: the mesh-native plane's paired interleaved ladder
    (device-local vs replicated-slab arms) plus the sharded-scan row emit
    per-arm medians, tiny-sized here."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_MESH": "1",
        "SURGE_BENCH_MESH_AGGREGATES": "64",
        "SURGE_BENCH_MESH_ROUNDS": "1",
        "SURGE_BENCH_MESH_CAP_LADDER": "64",
        "SURGE_BENCH_MESH_FOLD_EVENTS": "200",
        "SURGE_BENCH_MESH_FOLD_CYCLES": "2",
        "SURGE_BENCH_MESH_READ_WORKERS": "4",
        "SURGE_BENCH_MESH_READ_BATCH": "32",
        "SURGE_BENCH_MESH_SCAN_EVENTS": "4000",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    assert payload["metric"] == "mesh_fold_events_per_sec"
    assert payload["mesh_devices"] == 8
    rung = payload["mesh_fold_ladder"][0]
    for key in ("capacity", "local_events_per_sec",
                "replicated_events_per_sec", "local_vs_replicated",
                "local_rounds", "replicated_rounds"):
        assert key in rung, key
    assert rung["local_events_per_sec"] > 0
    assert rung["replicated_events_per_sec"] > 0
    assert payload["value"] == max(r["local_events_per_sec"]
                                   for r in payload["mesh_fold_ladder"])
    row = payload["mesh_read_row"]
    assert row["local_reads_per_sec"] > 0 and row["replicated_reads_per_sec"] > 0
    scan = payload["mesh_scan_row"]
    assert scan["mesh_events_per_sec"] > 0 and scan["single_events_per_sec"] > 0


def test_bench_resident_feed_paired_smoke():
    """SURGE_BENCH_RESIDENT_FEED=1: the paired native-feed vs Python-feed
    sustained-fold arms over one FileLog tail emit both medians + ratio."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_RESIDENT_FEED": "1",
        "SURGE_BENCH_FEED_EVENTS": "4000",
        "SURGE_BENCH_FEED_AGGREGATES": "512",
        "SURGE_BENCH_FEED_ROUNDS": "1",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    paired = payload["resident_feed_paired"]
    assert paired["native_feed_events_per_sec_median"] > 0
    assert paired["python_feed_events_per_sec_median"] > 0
    assert paired["speedup_median"] > 0
    assert payload["value"] == paired["native_feed_events_per_sec_median"]


def test_bench_ragged_paired_ladder_smoke():
    """SURGE_BENCH_RAGGED=1 (ISSUE 18): the paired interleaved dense vs
    bucketed vs bucketed+pallas refresh-dispatch ladder plus the donation
    probe emit per-arm medians and waste ratios off the ledger, tiny-sized
    here (probe capacity shrunk from 1M to 4096 rows so the smoke stays in
    tier-1 budget; the mesh topology and donate on/off arms still run)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_RAGGED": "1",
        "SURGE_BENCH_RAGGED_ROUNDS": "1",
        "SURGE_BENCH_RAGGED_CYCLES": "3",
        "SURGE_BENCH_RAGGED_DENSE_LANES": "32",
        "SURGE_BENCH_RAGGED_CAPACITY": "256",
        "SURGE_BENCH_RAGGED_PROBE_CAPACITY": "4096",
        "SURGE_BENCH_RAGGED_PROBE_CYCLES": "2",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    assert payload["metric"] == "ragged_fold_events_per_sec"
    assert payload["protocol"]["interleaved"] and payload["protocol"]["medians"]
    ladder = payload["ragged_ladder"]
    assert set(ladder) == {"steady_ragged", "dense_32"}
    for shape, row in ladder.items():
        for arm in ("dense", "bucketed", "bucketed_pallas"):
            assert row[arm]["events_per_sec_median"] > 0, (shape, arm)
            assert row[arm]["rounds"]
            assert row[arm]["waste_ratio"] >= 1.0
        assert row["waste_reduction"] > 0
        assert "bucketed_wins_every_round" in row
    # the bucketed arm sheds lane padding on the ragged shape even at
    # smoke size: its waste ratio must strictly improve on dense's
    ragged = ladder["steady_ragged"]
    assert ragged["bucketed"]["waste_ratio"] < ragged["dense"]["waste_ratio"]
    assert ragged["bucketed"]["bucket_fill_ratio"] > \
        ragged["dense"]["bucket_fill_ratio"]
    probe = payload["donation_probe"]
    assert probe["capacity"] == 4096
    assert probe["donated_ms_per_window"] > 0
    assert probe["copying_ms_per_window"] > 0
    assert probe["round10_local_ms_per_window"] == 19.0
    assert payload["value"] == max(
        row["bucketed"]["events_per_sec_median"] for row in ladder.values())


def test_bench_views_paired_smoke():
    """SURGE_BENCH_VIEWS=1 (ISSUE 17): the paired interleaved view-read vs
    scan-per-read reader ladder emits per-rung medians for both arms plus a
    speedup ratio, tiny-sized here — and even at smoke size the warm view
    must beat the from-scratch scan on medians."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_VIEWS": "1",
        "SURGE_BENCH_VIEWS_EVENTS": "4000",
        "SURGE_BENCH_VIEWS_AGGREGATES": "256",
        "SURGE_BENCH_VIEWS_ROUNDS": "1",
        "SURGE_BENCH_VIEWS_LADDER": "8",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    paired = payload["views_paired"]
    assert paired["protocol"]["interleaved"] and paired["protocol"]["medians"]
    (rung,) = paired["rungs"]
    assert rung["readers"] == 8
    for arm in ("view_read", "scan_per_read"):
        assert rung[arm]["reads_per_sec_median"] > 0
        assert rung[arm]["rounds"]
    assert rung["speedup_median"] > 1, \
        "a materialized view must beat a scan-per-read on medians"
    assert payload["value"] == rung["view_read"]["reads_per_sec_median"]


def test_bench_saga_storm_smoke():
    """SURGE_BENCH_SAGA=1 dispatch: one tiny seeded storm through the bench
    entrypoint — the JSON payload carries the three-zeros verdict keys the
    driver's last-line-wins parse gates on."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SURGE_BENCH_SAGA": "1",
        "SURGE_BENCH_SAGA_SEEDS": "31",
        "SURGE_BENCH_SAGA_SECONDS": "5",
        "SURGE_BENCH_SAGA_COUNT": "8",
        "SURGE_BENCH_SAGA_ACCOUNTS": "6",
        "SURGE_BENCH_SAGA_PARTITIONS": "4",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON payload on stdout: {proc.stdout!r}"
    payload = json.loads(lines[-1])
    assert payload["metric"] == "saga_started"
    for key in ("saga_rounds", "saga_seeds", "saga_started", "saga_poisoned",
                "saga_lost", "saga_duplicated", "saga_half_compensated",
                "saga_dead_letter", "saga_verdict"):
        assert key in payload, f"{key} missing from the saga payload"
    assert payload["saga_seeds"] == [31]
    assert payload["saga_started"] == 8
    assert payload["saga_verdict"] == \
        "ok: 0 lost / 0 duplicated / 0 half-compensated"
    assert payload["saga_lost"] == 0 and payload["saga_duplicated"] == 0
    assert payload["saga_half_compensated"] == 0
    round0 = payload["saga_rounds"][0]
    assert round0["reconcile"]["ok"] and round0["timeline_events"] > 0
