"""Checkpointed-restore subsystem: golden equivalence (checkpoint + tail fold
must produce a byte-identical store to the full fold from offset 0, on BOTH
replay backends), checkpoint-store durability, writer resume, partition-scoped
restores, and the engine-level bounded cold start.
"""

import asyncio
import os
import random
import time

import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.models import counter
from surge_tpu.serialization import SerializedMessage
from surge_tpu.store import (
    Checkpoint,
    CheckpointStore,
    CheckpointWriter,
    restore_from_events,
)
from surge_tpu.store.kv import InMemoryKeyValueStore

MODEL = counter.CounterModel()
EVT_FMT = counter.event_formatting()
STATE_FMT = counter.state_formatting()


def deserialize_event(raw: bytes):
    return EVT_FMT.read_event(SerializedMessage(key="", value=raw))


def serialize_state(agg_id: str, state) -> bytes:
    return STATE_FMT.write_state(state).value


def build_log(partitions=2, seed=7):
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", partitions))
    rng = random.Random(seed)
    seqs = {}
    prod = log.transactional_producer("seed")

    def publish(n, agg_pool=12):
        for _ in range(n):
            a = f"agg-{rng.randrange(agg_pool)}"
            seqs[a] = seqs.get(a, 0) + 1
            roll = rng.random()
            if roll < 0.1:
                ev = counter.NoOpEvent(a, seqs[a])
            elif roll < 0.75:
                ev = counter.CountIncremented(a, 1, seqs[a])
            else:
                ev = counter.CountDecremented(a, 1, seqs[a])
            prod.begin()
            prod.send(LogRecord(topic="events", key=a,
                                value=EVT_FMT.write_event(ev).value,
                                partition=hash(a) % partitions))
            prod.commit()

    return log, publish


def make_writer(log, store):
    return CheckpointWriter(
        log, "events", MODEL, store, serialize_state=serialize_state,
        deserialize_event=deserialize_event,
        deserialize_state=STATE_FMT.read_state)


def store_bytes(kv):
    return {k: kv.get(k) for k in kv._data}


# -- golden equivalence -----------------------------------------------------------------


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_checkpoint_plus_tail_fold_is_byte_identical(tmp_path, backend):
    """The acceptance invariant: restore via checkpoint + tail fold ==
    restore via full fold from offset 0, byte for byte, and the checkpointed
    route folds STRICTLY fewer events."""
    log, publish = build_log()
    publish(300)
    ck_store = CheckpointStore(str(tmp_path), fsync=False)
    make_writer(log, ck_store).write_now()
    publish(80)  # the tail: includes brand-new aggregates via the shared pool

    cfg = default_config().with_overrides({"surge.replay.backend": backend})
    full_kv, ckpt_kv = InMemoryKeyValueStore(), InMemoryKeyValueStore()
    full = restore_from_events(
        log, "events", full_kv, deserialize_event=deserialize_event,
        serialize_state=serialize_state, model=MODEL,
        replay_spec=counter.make_replay_spec(), config=cfg)
    tail = restore_from_events(
        log, "events", ckpt_kv, deserialize_event=deserialize_event,
        serialize_state=serialize_state, model=MODEL,
        replay_spec=counter.make_replay_spec(), config=cfg,
        checkpoint=ck_store.latest(), deserialize_state=STATE_FMT.read_state)
    assert store_bytes(full_kv) == store_bytes(ckpt_kv)
    assert tail.num_events < full.num_events
    assert tail.num_events == 80
    assert tail.num_aggregates == full.num_aggregates
    assert tail.watermarks == full.watermarks
    assert tail.backend == backend


def test_checkpoint_of_whole_topic_folds_zero_tail(tmp_path):
    log, publish = build_log()
    publish(120)
    ck_store = CheckpointStore(str(tmp_path), fsync=False)
    make_writer(log, ck_store).write_now()
    cfg = default_config().with_overrides({"surge.replay.backend": "cpu"})
    full_kv, ckpt_kv = InMemoryKeyValueStore(), InMemoryKeyValueStore()
    restore_from_events(log, "events", full_kv,
                        deserialize_event=deserialize_event,
                        serialize_state=serialize_state, model=MODEL,
                        config=cfg)
    tail = restore_from_events(
        log, "events", ckpt_kv, deserialize_event=deserialize_event,
        serialize_state=serialize_state, model=MODEL, config=cfg,
        checkpoint=ck_store.latest(), deserialize_state=STATE_FMT.read_state)
    assert tail.num_events == 0
    assert store_bytes(full_kv) == store_bytes(ckpt_kv)


# -- store durability -------------------------------------------------------------------


def test_checkpoint_store_roundtrip_prune_and_torn_fallback(tmp_path):
    ck_store = CheckpointStore(str(tmp_path), keep=2, fsync=False)
    for seq in (1, 2, 3):
        ck_store.write(Checkpoint(
            seq=seq, topic="events", created_at=time.time(),
            watermarks={0: seq * 10, 1: seq * 7},
            states={"a": f"s{seq}".encode(), "gone": None},
            partitions={"a": 0, "gone": 1}))
    assert ck_store.sequences() == [2, 3]  # pruned to keep=2
    ck = ck_store.latest()
    assert (ck.seq, ck.watermarks) == (3, {0: 30, 1: 21})
    assert ck.states == {"a": b"s3", "gone": None}
    assert ck.partitions == {"a": 0, "gone": 1}

    # a torn newer file (crash mid-write before the rename barrier ever ran)
    # must fall back to its intact predecessor, not fail the cold start
    with open(os.path.join(str(tmp_path), "ckpt-000000000004.ck"), "wb") as f:
        f.write(b"SCKP\x00\x01garbage")
    ck = ck_store.latest()
    assert ck.seq == 3


def test_checkpoint_writer_resumes_incrementally(tmp_path):
    log, publish = build_log()
    publish(100)
    ck_store = CheckpointStore(str(tmp_path), fsync=False)
    w1 = make_writer(log, ck_store)
    first = w1.write_now()
    publish(40)

    # a NEW writer (process restart) resumes from the durable checkpoint and
    # folds only the 40-event delta
    w2 = make_writer(log, ck_store)
    folded = w2.advance()
    assert folded == 40
    second = w2.write_now()  # advance() already consumed the tail
    assert second.seq == first.seq + 1
    assert second.events_covered() == 140
    # and the resumed-then-advanced states match a from-scratch fold
    w3 = CheckpointWriter(log, "events", MODEL,
                          CheckpointStore(str(tmp_path / "fresh"), fsync=False),
                          serialize_state=serialize_state,
                          deserialize_event=deserialize_event)
    scratch = w3.write_now()
    assert scratch.states == second.states
    assert scratch.watermarks == second.watermarks


# -- partition scoping ------------------------------------------------------------------


def test_scoped_restore_takes_only_owned_partitions(tmp_path):
    log, publish = build_log(partitions=2)
    publish(200)
    ck_store = CheckpointStore(str(tmp_path), fsync=False)
    make_writer(log, ck_store).write_now()
    publish(50)
    ck = ck_store.latest()
    cfg = default_config().with_overrides({"surge.replay.backend": "cpu"})

    scoped_kv, full_kv = InMemoryKeyValueStore(), InMemoryKeyValueStore()
    restore_from_events(
        log, "events", scoped_kv, deserialize_event=deserialize_event,
        serialize_state=serialize_state, model=MODEL, config=cfg,
        partitions=[0], checkpoint=ck,
        deserialize_state=STATE_FMT.read_state)
    restore_from_events(
        log, "events", full_kv, deserialize_event=deserialize_event,
        serialize_state=serialize_state, model=MODEL, config=cfg,
        partitions=[0])
    # identical to the full fold of partition 0 — and NOTHING from partition 1
    assert store_bytes(scoped_kv) == store_bytes(full_kv)
    assert all(ck.partition_of(a) == 0 for a in store_bytes(scoped_kv))


# -- engine-level bounded cold start ----------------------------------------------------


def test_engine_cold_start_folds_only_the_tail(tmp_path):
    async def scenario():
        ck_dir = str(tmp_path / "ckpt")
        base = {
            "surge.producer.flush-interval-ms": 5,
            "surge.producer.ktable-check-interval-ms": 5,
            "surge.state-store.commit-interval-ms": 20,
            "surge.engine.num-partitions": 2,
            "surge.replay.backend": "cpu",
            "surge.store.checkpoint.path": ck_dir,
            "surge.store.checkpoint.interval-ms": 60_000,  # manual writes only
        }

        def logic():
            return SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting())

        log = InMemoryLog()
        e1 = create_engine(logic(), log=log,
                           config=default_config().with_overrides(base))
        await e1.start()
        assert "checkpoint-writer" in e1.health_supervisor.registered()
        for i in range(24):
            await e1.aggregate_for(f"a-{i % 6}").send_command(
                counter.Increment(f"a-{i % 6}"))
        # checkpoint through the admin RPC (the operator trigger)
        import grpc

        from surge_tpu.admin import AdminClient, AdminServer

        admin = AdminServer(e1)
        port = await admin.start()
        client = AdminClient(grpc.aio.insecure_channel(f"127.0.0.1:{port}"))
        ok, detail = await client.write_checkpoint()
        assert ok, detail
        await admin.stop()
        ckpt = e1._checkpoint_store.latest()
        assert ckpt.events_covered() == 24
        for i in range(8):  # the tail a cold start should fold
            await e1.aggregate_for(f"a-{i % 6}").send_command(
                counter.Increment(f"a-{i % 6}"))
        await e1.stop()

        e2 = create_engine(logic(), log=log,
                           config=default_config().with_overrides(
                               {**base, "surge.replay.restore-on-start": True}))
        result = await e2.rebuild_from_events()
        assert result.num_events == 8  # tail only, not 32
        assert result.num_aggregates == 6
        await e2.start()
        r = await e2.aggregate_for("a-1").send_command(
            counter.Increment("a-1"))
        await e2.stop()

        # ground truth: a-1 saw increments at i∈{1,7,13,19} (head), {1,7}
        # (tail), +1 now
        assert r.state.count == 7, r.state

    asyncio.run(scenario())
