"""Alternative clustering backend: membership, external shard allocation, and the
cluster-sharding router — the enable-akka-cluster feature-flag path
(SurgePartitionRouterImpl.scala:85-121, KafkaClusterShardingRebalanceListener
.scala:17-183) re-derived without Akka.

Multi-node behavior runs as two engines on one loop sharing membership +
allocation + tracker + log — the multi-jvm spec analog (SURVEY.md §4.6)."""

import asyncio

import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.engine.cluster import (
    ClusterMembership,
    ClusterShardingRouter,
    ExternalShardAllocation,
)
from surge_tpu.engine.entity import Envelope
from surge_tpu.engine.partition import HostPort, PartitionTracker
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter

A = HostPort("node-a", 1)
B = HostPort("node-b", 2)

CLUSTER_CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 4,
    "surge.feature-flags.experimental.enable-cluster-sharding": True,
})


def make_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


# -- registries -------------------------------------------------------------------------


def test_membership_leader_is_lowest_address():
    m = ClusterMembership()
    assert m.leader is None
    m.join(B)
    assert m.leader == B
    m.join(A)
    assert m.leader == A  # lowest address bootstraps/leads
    m.join(A)  # idempotent
    assert m.members == [A, B]
    m.leave(A)
    assert m.leader == B


def test_shard_allocation_notifies_only_on_change():
    alloc = ExternalShardAllocation()
    seen = []
    alloc.subscribe(lambda locs: seen.append(dict(locs)))
    alloc.update_shard_locations({0: A, 1: B})
    alloc.update_shard_locations({0: A, 1: B})  # no change → no broadcast
    alloc.update_shard_locations({1: A})
    assert len(seen) == 2
    assert alloc.location_of(1) == A
    assert alloc.locations == {0: A, 1: A}


# -- router unit (probe regions) --------------------------------------------------------


class ProbeRegion:
    def __init__(self, partition):
        self.partition = partition
        self.delivered = []
        self.stopped = False

    def deliver(self, aggregate_id, env):
        self.delivered.append((aggregate_id, env))
        env.reply.set_result(f"probe-{self.partition}")

    async def stop(self):
        self.stopped = True


def test_router_buffers_until_allocated_and_moves_shards():
    async def scenario():
        tracker = PartitionTracker()
        membership = ClusterMembership()
        alloc = ExternalShardAllocation()
        regions = {}

        def creator(p):
            regions[p] = ProbeRegion(p)
            return regions[p]

        router = ClusterShardingRouter(
            num_partitions=4, tracker=tracker, local_host=A,
            region_creator=creator, membership=membership, allocation=alloc)
        await router.start()
        assert membership.members == [A]

        # unallocated shard: delivery buffers
        env = Envelope(message="m", reply=asyncio.get_running_loop().create_future())
        router.deliver("agg", env)
        assert not env.reply.done()

        # the leader (A) translates tracker assignments into allocations
        shard = router.partition_for("agg")
        tracker.update({A: list(range(4))})
        assert alloc.locations == {p: A for p in range(4)}
        assert await env.reply == f"probe-{shard}"

        # re-allocating the shard away stops the local region
        alloc.update_shard_locations({shard: B})
        await asyncio.sleep(0)
        assert regions[shard].stopped

        # deliveries to a remote shard without a transport fail fast
        env2 = Envelope(message="m", reply=asyncio.get_running_loop().create_future())
        router.deliver("agg", env2)
        with pytest.raises(Exception, match="no remote transport"):
            await env2.reply
        await router.stop()

    asyncio.run(scenario())


def test_non_leader_does_not_allocate():
    async def scenario():
        tracker = PartitionTracker()
        membership = ClusterMembership()
        membership.join(A)  # A exists and is the leader…
        alloc = ExternalShardAllocation()
        router_b = ClusterShardingRouter(
            num_partitions=4, tracker=tracker, local_host=B,
            region_creator=ProbeRegion, membership=membership, allocation=alloc)
        await router_b.start()  # …so B must not write allocations
        tracker.update({B: [0, 1, 2, 3]})
        assert alloc.locations == {}
        await router_b.stop()

    asyncio.run(scenario())


# -- two-engine cluster end-to-end ------------------------------------------------------


def test_two_node_cluster_routes_and_rebalances():
    async def scenario():
        log = InMemoryLog()
        tracker = PartitionTracker()
        membership = ClusterMembership()
        alloc = ExternalShardAllocation()
        engines = {}

        def remote_deliver(owner, partition, aggregate_id, env):
            engines[owner].router.deliver(aggregate_id, env)

        for host in (A, B):
            engines[host] = create_engine(
                make_logic(), log=log, config=CLUSTER_CFG, local_host=host,
                tracker=tracker, membership=membership, shard_allocation=alloc,
                remote_deliver=remote_deliver)
        await engines[A].start()
        await engines[B].start()
        tracker.update({A: [0, 1], B: [2, 3]})

        # drive 40 aggregates from node A; ids hash across all four shards, so some
        # forward to B over remote_deliver
        for i in range(40):
            r = await engines[A].aggregate_for(f"agg-{i}").send_command(
                counter.Increment(f"agg-{i}"))
            assert r.state.count == 1, (i, r)
        local_a = set(engines[A].router.local_partitions)
        local_b = set(engines[B].router.local_partitions)
        assert local_a <= {0, 1} and local_b <= {2, 3} and local_a and local_b

        # rebalance: all shards to B; A's regions stop, traffic still lands
        tracker.update({B: [0, 1, 2, 3]})
        await asyncio.sleep(0.02)
        assert engines[A].router.local_partitions == []
        r = await engines[A].aggregate_for("agg-7").send_command(
            counter.Increment("agg-7"))
        assert r.state.count == 2

        await engines[A].stop()
        await engines[B].stop()

    asyncio.run(scenario())


def test_member_departure_reallocates_shards():
    """Regression: when a member leaves, its shard allocations must not keep
    routing to the dead node — the leader drops them and re-derives placements
    from the live assignments."""
    async def scenario():
        tracker = PartitionTracker()
        membership = ClusterMembership()
        alloc = ExternalShardAllocation()

        routers = {}
        for host in (A, B):
            routers[host] = ClusterShardingRouter(
                num_partitions=4, tracker=tracker, local_host=host,
                region_creator=ProbeRegion, membership=membership, allocation=alloc)
            await routers[host].start()
        tracker.update({A: [0, 1], B: [2, 3]})
        assert alloc.locations == {0: A, 1: A, 2: B, 3: B}

        # B departs; the leader must drop B's shards and reassign what the tracker
        # still maps to live members
        tracker.update({A: [0, 1, 2, 3]})  # control plane reassigned first
        await routers[B].stop()
        assert all(owner == A for owner in alloc.locations.values())
        assert set(alloc.locations) == {0, 1, 2, 3}
        await routers[A].stop()

        # symmetric: leader departure leaves the survivor as leader who can allocate
        assert membership.members == []

    asyncio.run(scenario())
