"""Self-healing cluster plane (ISSUE 13): dynamic membership, per-partition
leadership spread, the partition router, the SLO-driven autobalancer, and the
3-seed fast variant of the sustained chaos soak."""

import json
import threading
import time

import pytest

from conftest import free_ports
from surge_tpu.cluster import Autobalancer, PartitionRouter
from surge_tpu.cluster.soak import run_soak
from surge_tpu.config import Config
from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.log.transport import NotLeaderError, ProducerFencedError

CLUSTER_CFG = Config(overrides={
    "surge.log.replication-ack-timeout-ms": 1_500,
    "surge.log.replication-isr-timeout-ms": 600,
    "surge.log.failover.probe-interval-ms": 150,
    "surge.log.failover.probe-failures": 2,
    "surge.log.quorum.vote-timeout-ms": 600,
    "surge.log.quorum.vote-rounds": 6,
    "surge.log.replication.min-insync-acks": 2,
    "surge.cluster.reassign-grace-ms": 1_000,
    "surge.cluster.balancer.hysteresis-ms": 100,
    "surge.cluster.balancer.move-budget": 8,
    "surge.cluster.balancer.window-ms": 30_000,
})


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


def _spread_trio(partitions=4, extra=None):
    """3 brokers, quorum peers everywhere, partition leadership spread
    round-robin — the ISSUE-13 baseline fleet."""
    cfg = CLUSTER_CFG
    if extra:
        cfg = Config(overrides={**CLUSTER_CFG.overrides, **extra})
    ports = free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    followers = []
    for i in (1, 2):
        f = LogServer(InMemoryLog(), port=ports[i], follower_of=addrs[0],
                      auto_promote=True, config=cfg, quorum_peers=addrs)
        f.start()
        followers.append(f)
    leader = LogServer(InMemoryLog(), port=ports[0],
                       replicate_to=[addrs[1], addrs[2]], config=cfg,
                       quorum_peers=addrs, auto_promote=True)
    leader.start()
    setup = GrpcLogTransport(addrs[0], config=cfg)
    setup.create_topic(TopicSpec("ev", partitions))
    view = setup.cluster_meta("spread", partitions=partitions)
    setup.close()
    return leader, followers, addrs, view, cfg


def _stop_all(*servers):
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 — already killed
            pass


def _commit_via(router_or_addr, cfg, txn, partition, payloads, timeout=30.0):
    """Retry-ladder commits (the publisher-protocol shape) through a router
    or a direct broker address; returns the acked payloads."""
    own = isinstance(router_or_addr, str)
    client = GrpcLogTransport(router_or_addr, config=cfg) if own \
        else router_or_addr
    producer = None
    acked = []
    try:
        for payload in payloads:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    if producer is None:
                        producer = client.transactional_producer(txn)
                    producer.begin()
                    producer.send(rec("ev", f"k{partition}", payload,
                                      partition))
                    producer.commit()
                    break
                except (ProducerFencedError, NotLeaderError):
                    producer = None
                except Exception:  # noqa: BLE001 — broker mid-move
                    if producer is not None and producer.in_transaction:
                        producer.abort()
                    time.sleep(0.05)
                if time.monotonic() > deadline:
                    raise TimeoutError(f"commit {payload!r} never acked")
            acked.append(payload)
    finally:
        if own:
            client.close()
    return acked


def _live_leaders_by_partition(servers, partitions):
    claims = {p: set() for p in range(partitions)}
    for s in servers:
        if s._dead:
            continue
        for p in s.broker_status()["partitions_led"]:
            claims[int(p)].add(s.advertised)
    return claims


# -- leadership spread & routing ------------------------------------------------------


def test_spread_assigns_every_partition_and_router_routes_writes():
    leader, (f1, f2), addrs, view, cfg = _spread_trio()
    router = PartitionRouter(",".join(addrs), config=cfg)
    try:
        assign = view["assignments"]
        # every partition assigned, each broker leads a slice
        assert sorted(assign) == ["0", "1", "2", "3"]
        assert set(assign.values()) == set(addrs)
        # exactly one leader per partition, agreed by status everywhere
        claims = _live_leaders_by_partition([leader, f1, f2], 4)
        for p, owners in claims.items():
            assert owners == {assign[str(p)]}, (p, owners)
        # the router lands every partition's writes on ITS leader
        acked = {}
        for p in range(4):
            acked[p] = _commit_via(router, cfg, f"t-route-{p}", p,
                                   [f"p{p}-{i}".encode() for i in range(5)])
        for p in range(4):
            owner = [s for s in (leader, f1, f2)
                     if s.advertised == assign[str(p)]][0]
            assert [r.value for r in owner.log.read("ev", p)] == acked[p]
        # a wrong-broker write is redirected with a PER-PARTITION hint
        wrong_p = [p for p in range(4) if assign[str(p)] != addrs[0]][0]
        direct = GrpcLogTransport(addrs[0], config=cfg)
        try:
            producer = direct.transactional_producer("t-wrong")
            producer.begin()
            producer.send(rec("ev", "k", b"x", wrong_p))
            with pytest.raises(ProducerFencedError) as exc:
                producer.commit()
            assert assign[str(wrong_p)] in str(exc.value)
        finally:
            direct.close()
        # spread replication: every broker converges on every partition
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(len(s.log.read("ev", p)) == 5
                   for s in (leader, f1, f2) for p in range(4)):
                break
            time.sleep(0.05)
        for s in (leader, f1, f2):
            for p in range(4):
                assert [r.value for r in s.log.read("ev", p)] == acked[p], \
                    (s.advertised, p)
        # non-leaders of a partition gate reads at the shipped hwm, never
        # serving past the quorum-acked frontier (spot check: gate present)
        non_leader = [s for s in (leader, f1, f2)
                      if s.advertised != assign["0"]][0]
        assert non_leader._read_gate("ev", 0) is not None
    finally:
        router.close()
        _stop_all(leader, f1, f2)


def test_partition_handoff_moves_one_slice_under_load():
    leader, (f1, f2), addrs, view, cfg = _spread_trio()
    router = PartitionRouter(",".join(addrs), config=cfg)
    try:
        assign = view["assignments"]
        # pick a partition led by a NON-coordinator, move it to the busiest
        src_addr = [a for a in set(assign.values()) if a != addrs[0]][0]
        moving = int([p for p, a in assign.items() if a == src_addr][0])
        dst_addr = [a for a in addrs if a != src_addr][0]
        acked = _commit_via(router, cfg, "t-ho", moving,
                            [f"pre-{i}".encode() for i in range(10)])
        stop = threading.Event()
        side = {"acked": [], "error": None}

        def writer():
            r2 = PartitionRouter(",".join(addrs), config=cfg)
            try:
                i = 0
                while not stop.is_set():
                    side["acked"] += _commit_via(
                        r2, cfg, "t-ho-live", moving,
                        [f"live-{i}".encode()])
                    i += 1
            except Exception as exc:  # noqa: BLE001
                side["error"] = exc
            finally:
                r2.close()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.3)
        src = GrpcLogTransport(src_addr, config=cfg)
        stats = src.cluster_handoff(dst_addr, moving)
        src.close()
        time.sleep(0.3)
        stop.set()
        t.join(30.0)
        assert side["error"] is None, f"live writer died: {side['error']!r}"
        assert stats["to"] == dst_addr and stats["fence_ms"] > 0
        # ONLY the moved partition changed hands; other slices untouched
        meta = GrpcLogTransport(addrs[0], config=cfg).cluster_meta()
        assert meta["assignments"][str(moving)] == dst_addr
        for p, owner in assign.items():
            if int(p) != moving:
                assert meta["assignments"][p] == owner
        # exactly-once across the move, on the new leader's log
        dst = [s for s in (leader, f1, f2) if s.advertised == dst_addr][0]
        values = [r.value for r in dst.log.read("ev", moving)]
        for payload in acked + side["acked"]:
            assert values.count(payload) == 1, payload
        # the handoff story is on the source's flight ring
        src_server = [s for s in (leader, f1, f2)
                      if s.advertised == src_addr][0]
        types = [e["type"] for e in src_server.flight.events()]
        assert "handoff.partition.start" in types
        assert "handoff.partition.done" in types
    finally:
        router.close()
        _stop_all(leader, f1, f2)


# -- dynamic membership ---------------------------------------------------------------


def test_add_broker_requires_catch_up_then_joins_quorum_and_leads():
    leader, (f1, f2), addrs, view, cfg = _spread_trio(
        extra={"surge.log.replication-auto-resync-max-records": 5})
    (jport,) = free_ports(1)
    jaddr = f"127.0.0.1:{jport}"
    joiner = None
    client = GrpcLogTransport(addrs[0], config=cfg)
    try:
        for p in range(4):
            _commit_via(view["assignments"][str(p)], cfg, f"t-seed-{p}", p,
                        [f"s{p}-{i}".encode() for i in range(8)])
        # an un-caught-up joiner is refused: it must never count toward a
        # quorum holding records it does not have
        joiner = LogServer(InMemoryLog(), port=jport, follower_of=addrs[0],
                           auto_promote=True, config=cfg)
        joiner.start()
        with pytest.raises(RuntimeError, match="catch_up"):
            client.add_broker(jaddr)
        # catch up through the PR-7 slice lane, then join
        copied = joiner.catch_up(addrs[0])
        assert copied >= 32
        view2 = client.add_broker(jaddr)
        assert jaddr in view2["members"]
        assert view2["member_epoch"] == 1
        # the membership rewrite reached the whole fleet (quorum resized)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(jaddr in s.broker_status()["membership"]["members"]
                   for s in (leader, f1, f2, joiner)):
                break
            time.sleep(0.05)
        status = client.broker_status()
        assert status["quorum"]["cluster_size"] == 4
        assert status["quorum"]["majority"] == 3
        # the joiner can take a slice via planned handoff and serve it
        src_addr = view["assignments"]["1"]
        src = GrpcLogTransport(src_addr, config=cfg)
        src.cluster_handoff(jaddr, 1)
        src.close()
        _commit_via(jaddr, cfg, "t-join", 1, [b"on-joiner"])
        # RemoveBroker: the slice fails over BEFORE the membership shrinks
        view3 = client.remove_broker(jaddr)
        assert jaddr not in view3["members"]
        assert jaddr not in view3["assignments"].values()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and joiner.partitions_led():
            time.sleep(0.05)
        # the removed broker leads nothing and REFUSES producer opens with
        # a redirect (a client that lands there bounces to the heir — the
        # per-partition hint — instead of forking the log)
        assert joiner.partitions_led() == []
        from surge_tpu.log import log_service_pb2 as pb
        refusal = joiner.OpenProducer(
            pb.OpenProducerRequest(transactional_id="t-removed"), None)
        assert refusal.error_kind == "not_leader"
        # everything the joiner acked survives, exactly once, on the heir
        heir = view3["assignments"]["1"]
        hc = GrpcLogTransport(heir, config=cfg)
        values = [r.value for r in hc.read("ev", 1)]
        hc.close()
        assert values.count(b"on-joiner") == 1
        for i in range(8):
            assert values.count(f"s1-{i}".encode()) == 1
    finally:
        client.close()
        _stop_all(leader, f1, f2, *(s for s in (joiner,) if s is not None))


def test_failed_member_partitions_reassign_and_relit_broker_stays_safe():
    leader, (f1, f2), addrs, view, cfg = _spread_trio()
    relit = None
    try:
        assign = view["assignments"]
        for p in range(4):
            _commit_via(assign[str(p)], cfg, f"t-{p}", p,
                        [f"p{p}-{i}".encode() for i in range(5)])
        victim = [s for s in (f1, f2)
                  if s.advertised in assign.values()][0]
        victim_addr = victim.advertised
        victim_led = victim.partitions_led()
        assert victim_led, "spread left a broker leading nothing"
        victim.kill()
        if victim.kill_done is not None:
            victim.kill_done.wait(10)
        # the coordinator's grace sweep moves the dead member's slice onto
        # survivors — per-partition failover, not whole-cluster
        client = GrpcLogTransport(addrs[0], config=cfg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            meta = client.cluster_meta()
            if victim_addr not in meta["assignments"].values():
                break
            time.sleep(0.2)
        assert victim_addr not in meta["assignments"].values(), meta
        # acked history survives on the heirs; new writes flow
        for p in victim_led:
            heir = meta["assignments"][str(p)]
            acked = _commit_via(heir, cfg, f"t-after-{p}", p,
                                [f"after-{p}".encode()])
            hs = [s for s in (leader, f1, f2)
                  if s.advertised == heir][0]
            values = [r.value for r in hs.log.read("ev", p)]
            for i in range(5):
                assert values.count(f"p{p}-{i}".encode()) == 1
            assert values.count(acked[0]) == 1
        # relight over the same log: the broker comes back SUSPENDED (its
        # recovered map is stale) and must not claim its old slice
        relit = LogServer(victim.log,
                          port=int(victim_addr.rsplit(":", 1)[1]),
                          follower_of=addrs[0], auto_promote=True,
                          config=cfg, quorum_peers=addrs,
                          flight=victim.flight)
        relit.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not relit.partitions_led():
                break
            time.sleep(0.1)
        assert relit.partitions_led() == []
        claims = _live_leaders_by_partition([leader, f1, f2, relit], 4)
        for p, owners in claims.items():
            assert len(owners) == 1, (p, owners)
        client.close()
    finally:
        _stop_all(leader, f1, f2, *(s for s in (relit,) if s is not None))


# -- autobalancer ---------------------------------------------------------------------


class _StubScraper:
    """Deterministic scraper stand-in for decision-logic tests."""

    def __init__(self):
        self.slo = None
        self.metrics = None

    def scrape_once(self):
        return {"targets": 0, "up": 0, "errors": {}}

    def last_merged(self):
        return []

    def instance_values(self, family, suffix="", merged=None):
        return {}


def test_autobalancer_brakes_hysteresis_budget_dry_run():
    cfg = Config(overrides={
        "surge.cluster.balancer.hysteresis-ms": 60_000,
        "surge.cluster.balancer.move-budget": 1,
        "surge.cluster.balancer.window-ms": 60_000,
        "surge.cluster.balancer.max-lead-skew": 1,
    })
    balancer = Autobalancer(_StubScraper(), [], config=cfg)
    rows = {"a": {"up": True, "leads": [0, 1, 2], "lag": 0.0},
            "b": {"up": True, "leads": [3], "lag": 0.0},
            "c": {"up": True, "leads": [], "lag": 0.0}}
    decision = balancer._decide(rows, [])
    assert decision["decision"] == "move"
    assert decision["source"] == "a" and decision["dest"] == "c"
    assert decision["partition"] == 0 and decision["reason"] == "lead-skew"
    # hysteresis: a just-moved partition stays put; the NEXT movable one goes
    balancer._last_move["0"] = balancer._clock()
    decision = balancer._decide(rows, [])
    assert decision["decision"] == "move" and decision["partition"] == 1
    # budget: one executed move exhausts the window
    balancer._moves.append(balancer._clock())
    decision = balancer._decide(rows, [])
    assert decision["decision"] == "skip"
    assert decision["reason"] == "move-budget"
    # within-skew: balanced fleets are left alone
    decision = balancer._decide(
        {"a": {"up": True, "leads": [0, 1], "lag": 0.0},
         "b": {"up": True, "leads": [2], "lag": 0.0}}, [])
    assert decision["decision"] == "skip"
    assert decision["reason"] == "within-skew"
    # SLO burn attribution: the worst-lag member sheds load even when the
    # lead counts are level (budget window cleared first)
    balancer._moves.clear()
    burn_rows = {"a": {"up": True, "leads": [0, 1], "lag": 900.0},
                 "b": {"up": True, "leads": [2, 3], "lag": 10.0}}
    decision = balancer._decide(burn_rows, ["quorum-hwm-lag"])
    assert decision["decision"] == "move"
    assert decision["source"] == "a" and decision["reason"] == "slo-burn"
    # dry-run: the decision is recorded but never executed
    cfg_dry = Config(overrides={**cfg.overrides,
                                "surge.cluster.balancer.dry-run": True})
    dry = Autobalancer(_StubScraper(), [], config=cfg_dry)
    decision = dry._decide(rows, [])
    assert decision["decision"] == "move" and decision["dry_run"] is True


def test_autobalancer_rebalances_relit_broker_and_flight_records_it():
    """The heal loop end to end: kill a partition leader, let the
    coordinator fail its slice over, relight it, and the autobalancer —
    consuming a real federated scrape + SLO pass per cycle — moves load
    back until the spread is within the skew bound."""
    from surge_tpu.observability import (SLO, FederatedScraper, ScrapeTarget,
                                         SLOEngine)

    leader, (f1, f2), addrs, view, cfg = _spread_trio(
        extra={"surge.slo.fast-window-ms": 1_000,
               "surge.slo.slow-window-ms": 2_500})
    servers = {s.advertised: s for s in (leader, f1, f2)}
    relit = None

    def target(addr):
        def fetch():
            server = servers[addr]
            if server._dead:
                raise RuntimeError(f"{addr} down")
            return server.metrics_text()

        return ScrapeTarget(instance=addr, role="broker", fetch=fetch)

    scraper = FederatedScraper([target(a) for a in addrs], config=cfg)
    scraper.slo = SLOEngine(
        [SLO("fleet-up", family="up", kind="bound", objective=0.99,
             threshold=1.0, op="lt")], config=cfg, metrics=scraper.metrics)
    balancer = Autobalancer(scraper, addrs, config=cfg)
    try:
        victim = [s for s in (f1, f2) if s.partitions_led()][0]
        victim_addr = victim.advertised
        victim.kill()
        if victim.kill_done is not None:
            victim.kill_done.wait(10)
        client = GrpcLogTransport(addrs[0], config=cfg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if victim_addr not in \
                    client.cluster_meta()["assignments"].values():
                break
            time.sleep(0.2)
        # the SLO page opens while the member is down
        for _ in range(3):
            balancer.cycle()
            time.sleep(0.3)
        assert scraper.slo.breached() == ["fleet-up"]
        relit = LogServer(victim.log,
                          port=int(victim_addr.rsplit(":", 1)[1]),
                          follower_of=addrs[0], auto_promote=True,
                          config=cfg, quorum_peers=addrs,
                          flight=victim.flight)
        relit.start()
        servers[victim_addr] = relit
        # cycles continue: the page clears and the balancer moves load back
        # onto the relit broker until the spread is within the skew bound
        moved = False
        deadline = time.monotonic() + 30
        decision = {}
        while time.monotonic() < deadline:
            decision = balancer.cycle()
            if decision.get("decision") == "move" and \
                    not decision.get("dry_run"):
                moved = True
            if (decision.get("decision") == "skip"
                    and decision.get("reason") == "within-skew"
                    and not scraper.slo.breached()):
                break
            time.sleep(0.3)
        assert moved, f"balancer never rebalanced: {decision}"
        assert decision.get("reason") == "within-skew"
        assert not scraper.slo.breached(), "page never cleared after heal"
        assert relit.partitions_led(), "relit broker got nothing back"
        # every decision is reconstructable from the balancer's flight ring
        types = [e["type"] for e in balancer.flight.events()]
        assert "balance.moved" in types
        assert any(t in ("balance.skip", "balance.move") for t in types)
        claims = _live_leaders_by_partition(
            [leader, f1, f2, relit], 4)
        for p, owners in claims.items():
            assert len(owners) == 1, (p, owners)
        client.close()
    finally:
        balancer.stop_sync()
        scraper.stop()
        _stop_all(leader, f1, f2, *(s for s in (relit,) if s is not None))


# -- the chaos soak: 3-seed deterministic fast variant in tier-1 ----------------------


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_selfheal_soak_fast_seeds(seed):
    """One seeded schedule per seed (odd seeds kill the coordinator, even a
    partition leader; all add/remove a member and run link faults + Zipf
    skew): 0 lost / 0 duplicated, exactly one leader per partition, every
    SLO page cleared after its heal, decisions on the merged timeline."""
    verdict = run_soak(seed, seconds=6.0)
    assert verdict["writer_errors"] == []
    assert verdict["acked_commits"] > 0
    assert verdict["lost"] == 0, verdict
    assert verdict["duplicated"] == 0, verdict
    assert verdict["leaders"]["ok"], verdict["leaders"]
    assert verdict["converged"], verdict
    assert verdict["slo_pages"]["raised"] >= 1
    assert verdict["slo_pages"]["cleared"], verdict["slo_pages"]
    assert verdict["membership_churn"]
    assert verdict["balancer_decisions"] > 0
    # the incident and its heal are reconstructable from the merged
    # timeline: the kill, the page, the recovery — plus whichever heal
    # mechanism this schedule exercised (an election, a grace reassignment,
    # a balancer handoff, or a safe leadership resumption after relight)
    heals = set(verdict["heal_events"])
    assert "broker.kill" in heals
    assert "slo.breach" in heals and "slo.recovered" in heals
    assert heals & {"quorum.win", "cluster.reassign",
                    "handoff.partition.done", "isr.rejoin",
                    "cluster.meta-apply"}, heals
    assert verdict["timeline_events"] > 0


@pytest.mark.slow
def test_selfheal_soak_long_randomized():
    """The minutes-long soak: more seeds, longer schedules, more writers."""
    for seed in range(50, 56):
        verdict = run_soak(seed, seconds=12.0, writers=4, partitions=6)
        assert verdict["lost"] == 0 and verdict["duplicated"] == 0, verdict
        assert verdict["leaders"]["ok"] and verdict["converged"], verdict
        assert verdict["slo_pages"]["cleared"], verdict["slo_pages"]


# -- the routed pipelined window (ROADMAP 4(b)) ---------------------------------------


def test_routed_producer_pipelined_window_exactly_once_across_handoff():
    """PR-3's bounded in-flight window must survive the router: a window of
    commit_pipelined dispatches ships WITHOUT awaiting earlier replies, and
    a handle failed by a partition move retries onto the new leader via
    retry_pipelined — exactly once, no window collapse to depth 1."""
    leader, (f1, f2), addrs, view, cfg = _spread_trio()
    router = PartitionRouter(",".join(addrs), config=cfg)
    try:
        assign = view["assignments"]
        src_addr = [a for a in set(assign.values()) if a != addrs[0]][0]
        moving = int([p for p, a in assign.items() if a == src_addr][0])
        dst_addr = [a for a in addrs if a != src_addr][0]

        producer = router.transactional_producer("t-window")
        # the whole window dispatches before ANY reply is awaited
        handles = []
        for i in range(6):
            producer.begin()
            producer.send(rec("ev", f"k{moving}", b"win-%d" % i, moving))
            handles.append(producer.commit_pipelined())
        for i, h in enumerate(handles):
            committed = h.future.result(timeout=15)
            assert [r.value for r in committed] == [b"win-%d" % i]

        # move the slice out from under the producer's cached leader
        src = GrpcLogTransport(src_addr, config=cfg)
        stats = src.cluster_handoff(dst_addr, moving)
        src.close()
        assert stats["to"] == dst_addr

        # the next pipelined dispatch fails on the old leader; the retry
        # ladder re-resolves and re-dispatches on the new one
        producer.begin()
        producer.send(rec("ev", f"k{moving}", b"post-move", moving))
        h = producer.commit_pipelined()
        deadline = time.monotonic() + 20
        while True:
            try:
                h.future.result(timeout=15)
                break
            except Exception:  # noqa: BLE001 — fenced/not-leader mid-move
                assert time.monotonic() < deadline, "retry never landed"
                time.sleep(0.05)
                h = producer.retry_pipelined(h)

        # exactly once on the NEW leader's log, window order preserved
        dst = [s for s in (leader, f1, f2) if s.advertised == dst_addr][0]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(dst.log.read("ev", moving)) >= 7:
                break
            time.sleep(0.05)
        values = [r.value for r in dst.log.read("ev", moving)]
        expected = [b"win-%d" % i for i in range(6)] + [b"post-move"]
        for payload in expected:
            assert values.count(payload) == 1, (payload, values)
        assert values[:6] == expected[:6]
    finally:
        router.close()
        _stop_all(leader, f1, f2)


def test_engine_over_router_keeps_pipelined_window():
    """The ROADMAP 4(b) regression guard at the engine layer: a publisher
    lane over a RoutedProducer must stay pipeline-capable (the old facade
    lacked commit_pipelined, silently degrading every routed lane to
    max-in-flight 1). Under a per-Transact broker delay, concurrent
    commands on one partition must overlap in flight — inflight_peak >= 2
    is impossible at depth 1."""
    import asyncio

    from surge_tpu import create_engine
    from surge_tpu.models import counter
    from surge_tpu.models.counter import CounterModel

    leader, (f1, f2), addrs, view, cfg0 = _spread_trio()
    cfg = Config(overrides={
        **cfg0.overrides,
        "surge.engine.num-partitions": 4,
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.producer.max-in-flight": 4,
    })
    router = PartitionRouter(",".join(addrs), config=cfg)
    try:
        # the unit-level regression check: the routed producer exposes the
        # pipelined protocol the publisher's capability probe looks for
        assert hasattr(router.transactional_producer("t-cap"),
                       "commit_pipelined")

        from surge_tpu import SurgeCommandBusinessLogic

        logic = SurgeCommandBusinessLogic(
            aggregate_name="counter", model=CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting())

        async def scenario():
            engine = create_engine(logic, log=router, config=cfg)
            await engine.start()
            try:
                # 12 aggregates all hashing to ONE partition → one lane
                part = engine.router.partition_for("w-0")
                aggs, i = [], 0
                while len(aggs) < 12:
                    if engine.router.partition_for(f"w-{i}") == part:
                        aggs.append(f"w-{i}")
                    i += 1
                # warm the lane, then slow every Transact on the slice
                # leader so dispatched batches provably overlap
                r = await engine.aggregate_for(aggs[0]).send_command(
                    counter.Increment(aggs[0]))
                assert type(r).__name__ == "CommandSuccess", r
                owner = view["assignments"][str(part)]
                tclient = GrpcLogTransport(owner, config=cfg)
                try:
                    tclient.arm_faults(json.dumps({"rules": [{
                        "site": "rpc.Transact", "action": "delay",
                        "p": 1.0, "times": 40, "delay_ms": 25.0}]}))

                    async def one(agg, delay):
                        await asyncio.sleep(delay)
                        return await engine.aggregate_for(agg).send_command(
                            counter.Increment(agg))

                    results = await asyncio.gather(
                        *(one(a, j * 0.008) for j, a in enumerate(aggs)))
                finally:
                    try:
                        tclient.disarm_faults()
                    finally:
                        tclient.close()
                for r in results:
                    assert type(r).__name__ == "CommandSuccess", r
                stats = engine.producer_stats()
                assert stats["lanes"] >= 1
                assert stats["inflight_peak"] >= 2, stats
            finally:
                await engine.stop()

        asyncio.run(scenario())
    finally:
        router.close()
        _stop_all(leader, f1, f2)


# -- spread-aware compaction barrier --------------------------------------------------


def test_spread_compaction_barrier_runs_on_slice_leader_under_live_load():
    """Under an ACTIVE leadership spread the compaction barrier belongs to
    the partition's SLICE leader — a broker whose whole-process role is
    "follower" (the legacy role gate would refuse it). The barrier bounds
    its pass to the led slice's in-sync frontier while OTHER partitions
    keep committing, and a non-owner broker is refused with the owner's
    address in the error."""
    leader, (f1, f2), addrs, view, cfg = _spread_trio()
    router = PartitionRouter(",".join(addrs), config=cfg)
    try:
        assign = view["assignments"]
        setup = GrpcLogTransport(addrs[0], config=cfg)
        setup.create_topic(TopicSpec("st", 4, compacted=True))
        setup.close()
        # a slice led by a follower-ROLE broker — the spread gate's point
        p = int([q for q, a in assign.items() if a != addrs[0]][0])
        servers = {s.advertised: s for s in (leader, f1, f2)}
        slice_leader = servers[assign[str(p)]]
        other = [s for a, s in servers.items() if a != assign[str(p)]][0]
        q = int([r for r, a in assign.items()
                 if a == other.advertised][0])

        # dirty the compacted slice: 4 keys overwritten 6 rounds each
        producer = router.transactional_producer("t-dirty")
        for rnd in range(6):
            for k in range(4):
                deadline = time.monotonic() + 15
                while True:
                    try:
                        producer.begin()
                        producer.send(rec("st", f"key-{k}",
                                          b"v%d-%d" % (rnd, k), p))
                        producer.commit()
                        break
                    except Exception:  # noqa: BLE001 — topic still shipping
                        assert time.monotonic() < deadline
                        if producer.in_transaction:
                            producer.abort()
                        time.sleep(0.05)

        # a live writer keeps ANOTHER partition committing through the
        # barrier — the spread means the barrier never fences the fleet
        stop = threading.Event()
        side = {"acked": [], "error": None}

        def writer():
            r2 = PartitionRouter(",".join(addrs), config=cfg)
            try:
                i = 0
                while not stop.is_set():
                    side["acked"] += _commit_via(
                        r2, cfg, "t-cb-live", q, [f"live-{i}".encode()])
                    i += 1
            except Exception as exc:  # noqa: BLE001
                side["error"] = exc
            finally:
                r2.close()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.2)
        before = len(side["acked"])

        # a non-owner (the coordinator included) is refused with the hint
        with pytest.raises(RuntimeError) as exc:
            other.compact_partition("st", p, tombstone_retention_s=0.0)
        assert assign[str(p)] in str(exc.value)

        # the slice leader compacts, barrier-bounded to its in-sync frontier
        stats = slice_leader.compact_partition("st", p,
                                               tombstone_retention_s=0.0)
        assert stats.records_dropped > 0, stats
        latest = {k: r.value
                  for k, r in slice_leader.log.latest_by_key("st", p).items()}
        assert latest == {f"key-{k}": b"v5-%d" % k for k in range(4)}
        barrier = [e for e in slice_leader.flight.events()
                   if e["type"] == "compaction.barrier"
                   and e["partition"] == p]
        assert barrier, "barrier leg missing from the slice leader's ring"
        assert barrier[-1]["upto"] <= slice_leader.log.end_offset("st", p)

        time.sleep(0.2)
        stop.set()
        t.join(30.0)
        assert side["error"] is None, f"live writer died: {side['error']!r}"
        assert len(side["acked"]) > before, \
            "other partitions stopped committing across the barrier"
    finally:
        router.close()
        _stop_all(leader, f1, f2)
