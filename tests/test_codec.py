"""Codec golden tests: scalar↔tensor round trips (SURVEY.md §7 step 1)."""

import numpy as np
import pytest

from surge_tpu.codec import (
    SchemaRegistry,
    Vocab,
    bucket_lengths,
    decode_events,
    decode_states,
    encode_events,
    encode_states,
    PAD_TYPE_ID,
)
from surge_tpu.models import counter, shopping_cart


def test_counter_event_round_trip():
    reg = counter.make_registry()
    logs = [
        [counter.CountIncremented("a", 1, 1), counter.CountDecremented("a", 2, 2)],
        [counter.NoOpEvent("b", 1)],
        [],
    ]
    # aggregate_id is excluded from the tensor path — it round-trips as the batch key
    enc = encode_events(reg, logs)
    assert enc.type_ids.shape == (3, 2)
    assert enc.lengths.tolist() == [2, 1, 0]
    assert enc.type_ids[2, 0] == PAD_TYPE_ID
    dec = decode_events(reg, enc)
    assert dec[0] == [counter.CountIncremented("", 1, 1), counter.CountDecremented("", 2, 2)]
    assert dec[1] == [counter.NoOpEvent("", 1)]
    assert dec[2] == []


def test_union_columns_promote_and_zero_fill():
    reg = shopping_cart.make_registry()
    union = {f.name: f.dtype for f in reg.union_columns()}
    assert set(union) == {"item_code", "quantity", "unit_price_cents", "sequence_number"}
    logs = [[shopping_cart.CheckedOut("c", 1)]]
    enc = encode_events(reg, logs)
    # CheckedOut carries no item fields: zero-filled
    assert enc.cols["item_code"][0, 0] == 0
    assert enc.cols["sequence_number"][0, 0] == 1


def test_state_round_trip():
    reg = counter.make_registry()
    states = [counter.State("x", 5, 3), counter.State("y", -2, 9)]
    tree = encode_states(reg.state, states)
    assert tree["count"].dtype == np.int32
    back = decode_states(reg.state, tree)
    # aggregate_id excluded → compare tensor fields
    assert [(s.count, s.version) for s in back] == [(5, 3), (-2, 9)]


def test_pad_to_shorter_than_longest_raises():
    reg = counter.make_registry()
    logs = [[counter.NoOpEvent("a", i) for i in range(5)]]
    with pytest.raises(ValueError):
        encode_events(reg, logs, pad_to=3)


def test_bucket_lengths():
    groups = bucket_lengths([3, 70, 0, 64, 5000], [64, 256, 1024, 4096])
    assert groups[64] == [0, 2, 3]
    assert groups[256] == [1]
    # over the largest bucket → rounded up to multiple of largest
    assert groups[8192] == [4]


def test_vocab():
    v = Vocab()
    a, b = v.encode("alice"), v.encode("bob")
    assert v.encode("alice") == a
    assert v.decode(a) == "alice" and v.decode(b) == "bob"
    assert v.decode(0) == ""


def test_duplicate_registration_rejected():
    reg = SchemaRegistry()
    reg.register_event(counter.NoOpEvent, type_id=0, exclude=("aggregate_id",))
    with pytest.raises(ValueError):
        reg.register_event(counter.NoOpEvent, type_id=1, exclude=("aggregate_id",))
    with pytest.raises(ValueError):
        reg.register_event(counter.CountIncremented, type_id=0, exclude=("aggregate_id",))
