"""Columnar event-log segments: round-trip, compression, topic conversion, and
chunked replay (SURVEY.md §7 hard-part 3 — bulk replay without per-event objects)."""

import numpy as np
import pytest

from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.log import segment as seg
from surge_tpu.log.columnar import (
    ColumnarSegmentWriter,
    build_segment_from_topic,
    read_segment,
    segment_info,
)
from surge_tpu.models import counter
from surge_tpu.replay.corpus import synth_counter_corpus
from surge_tpu.replay.engine import ReplayEngine


def _chunks_of(corpus, n_chunks):
    ev = corpus.events.sorted_by_aggregate()
    b = corpus.num_aggregates
    per = (b + n_chunks - 1) // n_chunks
    out = []
    for start in range(0, b, per):
        out.append(ev.slice_aggregates(start, min(start + per, b)))
    return out


def test_segment_round_trip_and_replay(tmp_path):
    corpus = synth_counter_corpus(500, 20_000, seed=13)
    path = str(tmp_path / "events.scol")
    with ColumnarSegmentWriter(path) as w:
        for chunk in _chunks_of(corpus, 4):
            w.append(chunk)

    info = segment_info(path)
    assert info["num_aggregates"] == 500
    assert info["num_events"] == corpus.num_events
    assert info["num_chunks"] == 4
    assert info["schema"]["derived"] == {"sequence_number": "ordinal"}

    # chunk round-trip is exact
    back = list(read_segment(path))
    ev = corpus.events.sorted_by_aggregate()
    merged_types = np.concatenate([c.type_ids for c in back])
    np.testing.assert_array_equal(merged_types, ev.type_ids)

    # replay straight off the file: identical to the in-memory corpus fold
    eng = ReplayEngine(counter.make_replay_spec())
    res = eng.replay_columnar_chunks(read_segment(path))
    np.testing.assert_array_equal(res.states["count"], corpus.expected_count)
    np.testing.assert_array_equal(res.states["version"], corpus.expected_version)
    assert res.num_events == corpus.num_events


def test_segment_compresses_event_columns(tmp_path):
    if not seg.native_codec_available():
        pytest.skip("native segment codec not built")
    corpus = synth_counter_corpus(2000, 200_000, seed=3)
    path = str(tmp_path / "events.scol")
    with ColumnarSegmentWriter(path) as w:
        w.append(corpus.events)
    import os

    raw_bytes = corpus.events.nbytes()
    assert os.path.getsize(path) < raw_bytes / 2  # narrow int columns compress well


def test_divergent_chunk_schema_round_trips_via_meta_overrides(tmp_path):
    """A chunk whose schema differs from the header (the delta-chunk case:
    stored vs derived columns) persists per-chunk overrides and reads back with
    its own dtypes, not the header's."""
    corpus = synth_counter_corpus(10, 100, seed=1)
    path = str(tmp_path / "mixed.scol")
    w = ColumnarSegmentWriter(path)
    w.append(corpus.events)
    other = ColumnarEvents(num_aggregates=1, agg_idx=np.zeros(1, np.int32),
                           type_ids=np.zeros(1, np.int32),
                           cols={"weird": np.full(1, 2.5, np.float32)})
    w.append(other)
    w.close()
    chunks = list(read_segment(path))
    assert set(chunks[0].cols) == set(corpus.events.cols)
    assert set(chunks[1].cols) == {"weird"}
    assert chunks[1].cols["weird"].dtype == np.float32
    assert float(chunks[1].cols["weird"][0]) == 2.5


def test_build_segment_from_topic(tmp_path):
    """The offline conversion job: a real events topic (JSON records written by the
    command path's formats) becomes a columnar segment, and replaying it matches
    the scalar fold of the same records."""
    from surge_tpu.engine.model import fold_events

    log = InMemoryLog()
    log.create_topic(TopicSpec("counter-events", 2))
    fmt = counter.event_formatting()
    model = counter.CounterModel()
    rng = np.random.default_rng(5)
    expected = {}
    prod = log.transactional_producer("seg-test")
    for i in range(60):
        agg = f"agg-{i}"
        n = int(rng.integers(1, 12))
        events = [counter.CountIncremented(agg, int(rng.integers(1, 4)), k + 1)
                  for k in range(n)]
        expected[agg] = fold_events(model, None, events)
        prod.begin()
        for e in events:
            m = fmt.write_event(e)
            prod.send(LogRecord(topic="counter-events", key=agg, value=m.value,
                                partition=i % 2))
        prod.commit()

    path = str(tmp_path / "converted.scol")
    info = build_segment_from_topic(
        log, "counter-events", counter.make_registry(), fmt.read_event, path,
        derived_cols={"sequence_number": "ordinal"}, chunk_aggregates=16)
    assert info["num_aggregates"] == 60
    order = info["aggregate_order"]

    eng = ReplayEngine(counter.make_replay_spec())
    res = eng.replay_columnar_chunks(read_segment(path))
    for i, agg in enumerate(order):
        st = expected[agg]
        assert int(res.states["count"][i]) == st.count, agg
        assert int(res.states["version"][i]) == st.version, agg


def test_extend_segment_appends_delta_and_restores_without_rebuild(tmp_path):
    """VERDICT r3 next #8: after post-build traffic, extend appends delta
    chunks (schema-overridden: ordinals stored, not derived), state-only delta
    snapshots, and a watermark override; a restore folds base chunks then
    CONTINUES each touched aggregate's fold through init_carry — states match
    the scalar ground truth exactly, and a second extend with no new data is a
    no-op."""
    import numpy as np

    from surge_tpu.engine.model import fold_events
    from surge_tpu.log.columnar import extend_segment_from_topic, segment_info
    from surge_tpu.store.kv import InMemoryKeyValueStore
    from surge_tpu.store.restore import restore_from_segment

    log = InMemoryLog()
    log.create_topic(TopicSpec("counter-events", 2))
    log.create_topic(TopicSpec("counter-state", 2, compacted=True))
    model = counter.CounterModel()
    fmt = counter.event_formatting()
    sfmt = counter.state_formatting()
    rng = np.random.default_rng(9)
    prod = log.transactional_producer("seg")
    logs: dict = {}

    def send_events(agg, events, partition):
        prod.begin()
        for e in events:
            prod.send(LogRecord(topic="counter-events", key=agg,
                                value=fmt.write_event(e).value,
                                partition=partition))
        st = fold_events(model, None, logs.get(agg, []) + list(events))
        prod.send(LogRecord(topic="counter-state", key=agg,
                            value=sfmt.write_state(st).value,
                            partition=partition))
        prod.commit()
        logs.setdefault(agg, []).extend(events)

    # base: 20 aggregates
    for i in range(20):
        agg = f"agg-{i}"
        n = int(rng.integers(1, 9))
        send_events(agg, [counter.CountIncremented(agg, int(rng.integers(1, 4)),
                                                   k + 1) for k in range(n)],
                    i % 2)
    # a state-only key at build time
    prod.begin()
    prod.send(LogRecord(topic="counter-state", key="lonely", value=b"OLD",
                        partition=0))
    prod.commit()

    path = str(tmp_path / "inc.scol")
    build_segment_from_topic(
        log, "counter-events", counter.make_registry(), fmt.read_event, path,
        derived_cols={"sequence_number": "ordinal"}, chunk_aggregates=8,
        state_topic="counter-state")
    base_chunks = segment_info(path)["num_chunks"]

    # post-build traffic: continuations, brand-new aggregates, a state-only
    # update, and an update to the snapshot-only key (demoted path)
    for i in range(0, 20, 3):
        agg = f"agg-{i}"
        start = len(logs[agg])
        send_events(agg, [counter.CountIncremented(agg, 2, start + k + 1)
                          for k in range(3)], i % 2)
    for i in range(20, 24):
        agg = f"agg-{i}"
        send_events(agg, [counter.CountIncremented(agg, 1, k + 1)
                          for k in range(2)], i % 2)
    prod.begin()
    prod.send(LogRecord(topic="counter-state", key="lonely", value=b"NEW",
                        partition=0))
    prod.commit()

    info = extend_segment_from_topic(
        log, "counter-events", counter.make_registry(), fmt.read_event, path,
        state_topic="counter-state")
    assert info["num_chunks"] > base_chunks  # delta chunks landed
    wm = info["schema"]["extra"]["watermarks"]
    assert all(int(wm[str(p)]) == log.end_offset("counter-events", p)
               for p in range(2))

    store = InMemoryKeyValueStore()
    res = restore_from_segment(
        path, store, replay_spec=counter.make_replay_spec(),
        serialize_state=lambda a, s: sfmt.write_state(s).value)
    for agg, events in logs.items():
        truth = fold_events(model, None, events)
        got = sfmt.read_state(store.get(agg))
        assert (got.count, got.version) == (truth.count, truth.version), agg
    assert store.get("lonely") == b"NEW"  # demoted snapshot superseded OLD
    assert res.watermarks == {p: log.end_offset("counter-state", p)
                              for p in range(2)}

    # nothing new: extend is a no-op (same chunk count, same watermarks)
    info2 = extend_segment_from_topic(
        log, "counter-events", counter.make_registry(), fmt.read_event, path,
        state_topic="counter-state")
    assert info2["num_chunks"] == info["num_chunks"]


def test_build_segment_refuses_false_ordinal_claim(tmp_path):
    """A noop-bearing log (seq != position) must be rejected when declared ordinal,
    not silently corrupted."""
    log = InMemoryLog()
    log.create_topic(TopicSpec("ev", 1))
    fmt = counter.event_formatting()
    prod = log.transactional_producer("t")
    prod.begin()
    # NoOp doesn't bump version, so the next event's seq != its position
    for e in [counter.CountIncremented("a", 1, 1), counter.NoOpEvent("a", 2),
              counter.CountIncremented("a", 1, 2)]:
        m = fmt.write_event(e)
        prod.send(LogRecord(topic="ev", key="a", value=m.value))
    prod.commit()
    with pytest.raises(ValueError, match="not positional"):
        build_segment_from_topic(
            log, "ev", counter.make_registry(), fmt.read_event,
            str(tmp_path / "x.scol"), derived_cols={"sequence_number": "ordinal"})


def test_segment_carries_ids_snapshots_and_watermarks(tmp_path):
    """Chunks persist aggregate ids, the snapshot section carries state-only
    aggregates, and the header records build-time watermarks — together a complete
    cold-start image (restore_from_segment consumes all three)."""
    from surge_tpu.log.columnar import read_segment_snapshots, segment_info
    from surge_tpu.store import InMemoryKeyValueStore, restore_from_segment

    log = InMemoryLog()
    log.create_topic(TopicSpec("counter-events", 2))
    log.create_topic(TopicSpec("counter-state", 2, compacted=True))
    fmt = counter.event_formatting()
    prod = log.transactional_producer("seed")
    expected = {}
    from surge_tpu.engine.model import fold_events
    model = counter.CounterModel()
    for i in range(10):
        agg = f"agg-{i}"
        events = [counter.CountIncremented(agg, 2, k + 1) for k in range(i + 1)]
        expected[agg] = fold_events(model, None, events)
        prod.begin()
        for e in events:
            prod.send(LogRecord(topic="counter-events", key=agg,
                                value=fmt.write_event(e).value, partition=i % 2))
        prod.commit()
    # a state-only snapshot (no events for this key)
    prod.begin()
    prod.send(LogRecord(topic="counter-state", key="lonely", value=b"SNAP",
                        partition=0))
    prod.commit()

    path = str(tmp_path / "full.scol")
    info = build_segment_from_topic(
        log, "counter-events", counter.make_registry(), fmt.read_event, path,
        derived_cols={"sequence_number": "ordinal"}, chunk_aggregates=4,
        state_topic="counter-state")
    assert info["num_snapshots"] == 1
    extra = info["schema"]["extra"]
    assert extra["watermarks"] == {str(p): log.end_offset("counter-events", p)
                                   for p in range(2)}
    assert extra["state_watermarks"] == {str(p): log.end_offset("counter-state", p)
                                         for p in range(2)}

    chunks = list(read_segment(path))
    assert all(c.aggregate_ids is not None for c in chunks)
    # chunks are per source partition (sorted within each), enabling
    # partition-scoped restore; the union covers every aggregate exactly once
    ids = [i for c in chunks for i in c.aggregate_ids]
    assert sorted(ids) == sorted(expected) and ids == info["aggregate_order"]
    evens = [f"agg-{i}" for i in range(0, 10, 2)]
    odds = [f"agg-{i}" for i in range(1, 10, 2)]
    assert ids == evens + odds  # partition 0 chunks first, then partition 1
    p0_ids = [i for c in read_segment(path, partitions={0})
              for i in c.aggregate_ids]
    assert p0_ids == evens
    assert list(read_segment_snapshots(path)) == [("lonely", b"SNAP")]
    assert list(read_segment_snapshots(path, partitions={1})) == []
    assert list(read_segment_snapshots(path, partitions={0})) == [("lonely", b"SNAP")]

    # restore writes every folded state + snapshot into the store
    store = InMemoryKeyValueStore()
    sfmt = counter.state_formatting()
    res = restore_from_segment(
        path, store, replay_spec=counter.make_replay_spec(),
        serialize_state=lambda a, s: sfmt.write_state(s).value)
    assert res.backend == "segment"
    assert res.num_aggregates == 11
    assert res.watermarks == {p: log.end_offset("counter-state", p) for p in range(2)}
    assert store.get("lonely") == b"SNAP"
    for agg, st in expected.items():
        got = sfmt.read_state(store.get(agg))
        assert (got.count, got.version) == (st.count, st.version), agg

    # the first restore left per-chunk wire caches beside the segment; a
    # second cold start must consume them WITHOUT re-packing
    import os
    import unittest.mock as mock

    from surge_tpu.replay.engine import ReplayEngine

    assert os.path.isdir(path + ".wires") and os.listdir(path + ".wires")
    store2 = InMemoryKeyValueStore()
    with mock.patch.object(ReplayEngine, "pack_resident",
                           side_effect=AssertionError("must hit wire cache")):
        res2 = restore_from_segment(
            path, store2, replay_spec=counter.make_replay_spec(),
            serialize_state=lambda a, s: sfmt.write_state(s).value)
    assert res2.num_aggregates == 11
    for agg, st in expected.items():
        got = sfmt.read_state(store2.get(agg))
        assert (got.count, got.version) == (st.count, st.version), agg


def test_rebuilt_segment_never_serves_stale_wires(tmp_path):
    """A segment REBUILT at the same path — same chunk ordinals, same event
    counts, different content — must restore the NEW states, not the previous
    build's cached wires (ADVICE r4): every fresh segment stamps a new
    build_id into its header and creation drops the sidecar cache outright."""
    import os

    from surge_tpu.engine.model import fold_events
    from surge_tpu.log.columnar import build_segment_from_topic, segment_info
    from surge_tpu.store import InMemoryKeyValueStore, restore_from_segment

    model = counter.CounterModel()
    fmt = counter.event_formatting()
    sfmt = counter.state_formatting()
    path = str(tmp_path / "events.scol")

    def build_and_restore(increment_by: int):
        log = InMemoryLog()
        log.create_topic(TopicSpec("ev", 1))
        prod = log.transactional_producer("seed")
        expected = {}
        for i in range(6):
            agg = f"agg-{i}"
            events = [counter.CountIncremented(agg, increment_by, k + 1)
                      for k in range(3)]  # SAME count every build
            expected[agg] = fold_events(model, None, events)
            prod.begin()
            for e in events:
                prod.send(LogRecord(topic="ev", key=agg,
                                    value=fmt.write_event(e).value))
            prod.commit()
        build_segment_from_topic(
            log, "ev", counter.make_registry(), fmt.read_event, path,
            derived_cols={"sequence_number": "ordinal"}, chunk_aggregates=6)
        store = InMemoryKeyValueStore()
        restore_from_segment(
            path, store, replay_spec=counter.make_replay_spec(),
            serialize_state=lambda a, s: sfmt.write_state(s).value)
        return expected, store

    exp1, store1 = build_and_restore(increment_by=2)
    build1_id = segment_info(path)["schema"]["extra"]["build_id"]
    assert os.path.isdir(path + ".wires") and os.listdir(path + ".wires")
    for agg, st in exp1.items():
        assert sfmt.read_state(store1.get(agg)).count == st.count

    exp2, store2 = build_and_restore(increment_by=3)  # rebuild, new content
    assert segment_info(path)["schema"]["extra"]["build_id"] != build1_id
    for agg, st in exp2.items():
        got = sfmt.read_state(store2.get(agg))
        assert (got.count, got.version) == (st.count, st.version), agg
