"""Command anatomy end to end (ISSUE 14): router-hop trace continuity under
A→B→A leadership moves, direct-lane rejoin span parenting (native on/off),
SLO breach → exemplar/anatomy wiring, and the acceptance path — a seeded
slow-fsync fault on a 3-broker spread cluster behind the PartitionRouter
whose breached command trace is tail-kept, assembled across engine+broker
dumps, and attributed to the journal-fsync leg by trace_anatomy.py."""

import asyncio
import json
import os
import sys
import time

import pytest

from conftest import free_ports
from surge_tpu import SurgeCommandBusinessLogic, create_engine
from surge_tpu.cluster import PartitionRouter
from surge_tpu.config import Config
from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.log.file import FileLog
from surge_tpu.models import counter
from surge_tpu.observability import SLO, SLOEngine, merge_dumps
from surge_tpu.observability.anatomy import assemble_traces, dominant_leg
from surge_tpu.tracing import InMemoryTracer, Tracer
from tests.test_native_gate import NATIVE_MODES

CLUSTER_CFG = Config(overrides={
    "surge.log.replication-ack-timeout-ms": 4_000,
    "surge.log.replication-isr-timeout-ms": 2_000,
    "surge.log.replication.min-insync-acks": 2,
    "surge.trace.tail.latency-ms": 200,
    "surge.trace.ring-capacity": 512,
})


def make_logic(name="anat"):
    return SurgeCommandBusinessLogic(
        aggregate_name=name, model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


def _spread_trio(cfg, tracers=(None, None, None), logs=None, partitions=4):
    """3 brokers, quorum peers everywhere, leadership spread round-robin."""
    ports = free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    logs = logs or [InMemoryLog() for _ in range(3)]
    followers = []
    for i in (1, 2):
        f = LogServer(logs[i], port=ports[i], follower_of=addrs[0],
                      auto_promote=True, config=cfg, quorum_peers=addrs,
                      tracer=tracers[i])
        f.start()
        followers.append(f)
    leader = LogServer(logs[0], port=ports[0],
                       replicate_to=[addrs[1], addrs[2]], config=cfg,
                       quorum_peers=addrs, auto_promote=True,
                       tracer=tracers[0])
    leader.start()
    setup = GrpcLogTransport(addrs[0], config=cfg)
    view = setup.cluster_meta("spread", partitions=partitions)
    return leader, followers, addrs, setup, view


def _stop_all(*servers):
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass


def _wait_applied(client, partition, addr, timeout=5.0):
    """Poll until the CONNECTED broker's applied assignment view moves
    ``partition`` to ``addr`` (the redirect trap is only armed then)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        view = client.cluster_meta("status")
        if (view.get("assignments") or {}).get(str(partition)) == addr:
            return
        time.sleep(0.05)
    raise AssertionError(f"assignment of {partition} -> {addr} never applied")


# -- satellite 1: router redirect hops are one contiguous trace ----------------------


def test_router_redirect_chain_a_b_a_one_contiguous_trace():
    """Move partition 0's leadership A→B→A under a RoutedProducer: every
    hop — router.commit spans, their redirect events, the broker-call spans
    under them, and the broker-side spans on BOTH brokers — lands in ONE
    trace, chained under the caller's root span."""
    cfg = Config(overrides={**CLUSTER_CFG.overrides,
                            "surge.trace.tail.latency-ms": 1e9})
    broker_tracers = [InMemoryTracer() for _ in range(3)]
    leader, followers, addrs, setup, view = _spread_trio(
        cfg, tracers=broker_tracers)
    tracer = InMemoryTracer()
    router = PartitionRouter(addrs, config=cfg, tracer=tracer)
    try:
        home = view["assignments"]["0"]
        away = next(a for a in addrs if a != home)
        home_client = GrpcLogTransport(home, config=cfg)
        producer = router.transactional_producer("t-aba")

        def commit(payload):
            producer.begin()
            producer.send(LogRecord(topic="ev", key="k0", value=payload,
                                    partition=0))
            producer.commit()

        root = tracer.start_span("test.root")
        with root:
            router.create_topic(TopicSpec("ev", 4))
            commit(b"v0")                                   # on A
            setup.cluster_meta("assign", partition=0, to=away)
            _wait_applied(home_client, 0, away)
            commit(b"v1")                                   # redirect → B
            setup.cluster_meta("assign", partition=0, to=home)
            _wait_applied(GrpcLogTransport(away, config=cfg), 0, home)
            commit(b"v2")                                   # redirect → A
        home_client.close()

        tid = root.context.trace_id
        mine = [s for s in tracer.finished if s.context.trace_id == tid]
        commits = [s for s in mine if s.name == "router.commit"]
        assert len(commits) == 3
        # the two rerouted commits recorded their redirect hops
        redirected = [s for s in commits
                      if any(ev[1] == "redirect" for ev in s.events)]
        assert len(redirected) == 2
        assert all(s.attributes["attempts"] >= 2 for s in redirected)
        # broker-call spans chain UNDER the router spans, same trace
        commit_ids = {s.context.span_id for s in commits}
        transacts = [s for s in mine if s.name == "log.Transact"]
        assert transacts and all(s.parent_id in commit_ids
                                 for s in transacts)
        # and the trace crossed the wire: BOTH brokers saw it
        seen_on = [t for t, a in zip(broker_tracers, addrs)
                   if any(s.context.trace_id == tid
                          and s.name == "log.server.transact"
                          for s in t.finished)]
        assert len(seen_on) >= 2
        # contiguity: every router.commit chains directly under the root
        assert all(s.parent_id == root.context.span_id for s in commits)
    finally:
        router.close()
        setup.close()
        _stop_all(leader, *followers)


# -- satellite 2: direct-lane rejoin keeps the originating command's trace -----------


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_direct_lane_rejoin_parents_broker_span_under_command(tmp_path,
                                                              native):
    """A caller that times out and rejoins its queued write by request id
    (command-lane=direct) must still chain the broker log.server.transact
    span under the ORIGINATING command's trace — the queued pending carries
    the first publish attempt's span context, and the flush parents on it.
    Regression over native on/off (the broker-side path differs)."""
    etracer = InMemoryTracer()
    btracer = InMemoryTracer()
    cfg = Config(overrides={
        "surge.producer.command-lane": "direct",
        # linger is clamped to the flush tick, so raise BOTH: the 300ms hold
        # vs the 100ms publish timeout forces the timed-out-then-rejoin path
        "surge.producer.linger-ms": 300,
        "surge.producer.flush-interval-ms": 300,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.aggregate.publish-timeout-ms": 100,
        "surge.aggregate.publish-max-retries": 8,
        "surge.engine.num-partitions": 1,
        "surge.log.native.enabled": native,
        "surge.trace.tail.latency-ms": 1e9,
    })
    server = LogServer(FileLog(str(tmp_path / "log"), fsync="commit",
                               config=cfg),
                       config=cfg, tracer=btracer)
    port = server.start()
    log = GrpcLogTransport(f"127.0.0.1:{port}", config=cfg, tracer=etracer)

    async def scenario():
        engine = create_engine(make_logic("rejoin"), log=log, config=cfg,
                               tracer=etracer)
        await engine.start()
        r = await engine.aggregate_for("a1").send_command(
            counter.Increment("a1"))
        assert type(r).__name__ == "CommandSuccess", r
        # the 300ms linger vs the 100ms publish timeout forces at least one
        # timed-out attempt that REJOINED the queued write by request id
        stats = [reg.publisher.stats
                 for _p, reg in engine.router.regions()]
        assert sum(s.dedup_hits for s in stats) >= 1, \
            "no rejoin happened — timing knobs no longer force the timeout"
        await engine.stop()

    try:
        asyncio.run(scenario())
    finally:
        log.close()
        server.stop()

    # the command trace: ref root → … → >=2 publish attempts → flush →
    # broker call, all one trace id
    roots = [s for s in etracer.finished
             if s.name == "aggregate-ref.ProcessMessage"]
    assert roots
    tid = roots[0].context.trace_id
    mine = {s.name: s for s in etracer.finished
            if s.context.trace_id == tid}
    publishes = [s for s in etracer.finished
                 if s.context.trace_id == tid
                 and s.name == "publisher.publish"]
    assert len(publishes) >= 2  # the original + the rejoining retry
    flush = mine["publisher.flush"]
    # the flush parents on the ORIGINAL (first) publish attempt's span
    assert flush.parent_id == publishes[0].context.span_id
    # and the broker-side span rides the SAME originating trace
    broker_spans = [s for s in btracer.finished
                    if s.name == "log.server.transact"
                    and s.context.trace_id == tid]
    assert broker_spans, "broker span did not chain under the command trace"
    client_call = [s for s in etracer.finished
                   if s.context.trace_id == tid and s.name == "log.Transact"]
    assert client_call and broker_spans[0].parent_id == \
        client_call[0].context.span_id


# -- SLO wiring: breach → exemplars + breach window + trace.anatomy ------------------


def test_slo_breach_opens_tail_window_cites_exemplars_fires_anatomy():
    from surge_tpu.observability import FlightRecorder
    from surge_tpu.tracing.tail import TailSampler, TraceRing

    ring = TraceRing(name="engine:t", role="engine")
    now = [0.0]
    tail = TailSampler(ring, latency_ms=1e9, clock=lambda: now[0])
    ring.keep("c" * 32, "latency", [{"name": "s", "trace_id": "c" * 32}])
    flight = FlightRecorder(role="engine")
    eng = SLOEngine(
        [SLO("lag", family="g", kind="bound", objective=0.99,
             threshold=5.0, op="gt")],
        config=Config(overrides={"surge.slo.fast-window-ms": 10_000,
                                 "surge.slo.slow-window-ms": 40_000,
                                 "surge.slo.burn-threshold": 2.0}),
        flight=flight, tail=tail,
        anatomy=lambda: {"dominant": "journal-fsync",
                         "dominant_share": 0.71, "traces": 4})
    from surge_tpu.metrics.exposition import Family, Sample

    def fams(value):
        fam = Family(name="g", mtype="gauge", help="")
        fam.samples.append(Sample("", (("instance", "i"),), value))
        return {"g": fam}

    eng.evaluate(fams(9.0), now=0.0)
    eng.evaluate(fams(9.0), now=5.0)
    assert eng.breached() == ["lag"]
    events = flight.events()
    breach = next(e for e in events if e["type"] == "slo.breach")
    assert breach["exemplar_trace_ids"] == ["c" * 32]
    anatomy = next(e for e in events if e["type"] == "trace.anatomy")
    assert anatomy["dominant_leg"] == "journal-fsync"
    assert anatomy["share"] == 0.71 and anatomy["traces"] == 4
    # the breach opened the tail keep-window: a fast trace completing now
    # is kept as breach evidence
    assert tail.stats()["breach_window_open"]


# -- acceptance: seeded slow-fsync → journal-fsync named dominant --------------------


def test_e2e_slow_fsync_anatomy_names_journal_leg(tmp_path, capsys):
    """ISSUE 14 acceptance: fsync.journal stall (fault plane) on a 3-broker
    spread cluster behind the PartitionRouter → the breached command's
    trace is tail-kept on BOTH sides of the process boundary, assembled
    across engine+broker DumpTraces dumps, and trace_anatomy.py names the
    journal-fsync leg dominant (>50% of the critical path); the SLO engine
    stamps `trace.anatomy` onto the merged flight timeline."""
    cfg = Config(overrides={
        **CLUSTER_CFG.overrides,
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 4,
    })
    broker_tracers = [Tracer(service=f"b{i}") for i in range(3)]
    logs = [FileLog(str(tmp_path / f"b{i}"), fsync="commit", config=cfg)
            for i in range(3)]
    leader, followers, addrs, setup, _view = _spread_trio(
        cfg, tracers=broker_tracers, logs=logs)
    etracer = Tracer(service="engine")
    router = PartitionRouter(addrs, config=cfg, tracer=etracer)
    engine = None
    dumps = []
    try:
        async def scenario():
            nonlocal engine
            engine = create_engine(make_logic(), log=router, config=cfg,
                                   tracer=etracer)
            await engine.start()
            agg = "anat-0"
            part = engine.router.partition_for(agg)
            target = setup.cluster_meta("status")["assignments"][str(part)]
            # warm the entity/producer path so the stall lands on the
            # command's commit alone
            r = await engine.aggregate_for(agg).send_command(
                counter.Increment(agg))
            assert type(r).__name__ == "CommandSuccess", r
            tclient = GrpcLogTransport(target, config=cfg)
            try:
                tclient.arm_faults(json.dumps({"rules": [{
                    "site": "fsync.journal", "action": "stall",
                    "delay_ms": 800, "times": 1}]}))
                t0 = time.perf_counter()
                r = await engine.aggregate_for(agg).send_command(
                    counter.Increment(agg))
                stalled_ms = (time.perf_counter() - t0) * 1000.0
                assert type(r).__name__ == "CommandSuccess", r
                assert stalled_ms >= 500.0  # the seeded stall was paid
            finally:
                tclient.disarm_faults()
                tclient.close()
            await asyncio.sleep(0.4)  # flush spans + tail decisions settle
            await engine.stop()

        asyncio.run(scenario())

        # pull the rings: engine (in-process; the admin RPC round-trip is
        # covered in test_admin) + every broker over DumpTraces
        dumps.append(engine.trace_ring.dump())
        for a in addrs:
            c = GrpcLogTransport(a, config=cfg)
            dumps.append(c.trace_dump())
            c.close()
        paths = []
        for i, d in enumerate(dumps):
            p = tmp_path / f"trace-dump{i}.json"
            p.write_text(json.dumps(d))
            paths.append(str(p))

        # the breached command assembled WHOLE across the process boundary
        traces = assemble_traces(dumps)
        whole = [spans for spans in traces.values()
                 if {"aggregate-ref.ProcessMessage", "publisher.flush",
                     "log.server.transact"} <= {s["name"] for s in spans}]
        assert whole, "no cross-process command trace was tail-kept"
        assert any(s["keep_reason"] == "latency" for s in whole[0])
        assert {s["lane"] for s in whole[0]} == {"engine", "broker"}

        # the acceptance verdict comes from trace_anatomy.py's JSON output
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import trace_anatomy

        rc = trace_anatomy.main(paths + ["--once", "--format=json"])
        assert rc == 0
        table = json.loads(capsys.readouterr().out)
        assert table["traces"] >= 1
        assert table["dominant"] == "journal-fsync", table
        assert table["dominant_share"] > 0.5, table
        assert table["legs"]["journal-fsync"]["p99"] >= 500.0

        # SLO plane: a breach cites the kept trace and stamps trace.anatomy
        # onto the engine flight ring, which merges with broker flight dumps
        # into one incident timeline
        tail = etracer.tail
        slo = SLOEngine(
            [SLO("cmd-lat", family="g", kind="bound", objective=0.99,
                 threshold=5.0, op="gt")],
            config=Config(overrides={"surge.slo.fast-window-ms": 10_000,
                                     "surge.slo.slow-window-ms": 40_000,
                                     "surge.slo.burn-threshold": 2.0}),
            flight=engine.flight, tail=tail,
            anatomy=lambda: dominant_leg(dumps))
        from surge_tpu.metrics.exposition import Family, Sample

        def fams(value):
            fam = Family(name="g", mtype="gauge", help="")
            fam.samples.append(Sample("", (("instance", "i"),), value))
            return {"g": fam}

        slo.evaluate(fams(9.0), now=0.0)
        slo.evaluate(fams(9.0), now=5.0)
        assert slo.breached() == ["cmd-lat"]
        flight_dumps = [engine.flight.dump()]
        for a in addrs:
            c = GrpcLogTransport(a, config=cfg)
            flight_dumps.append(c.flight_dump())
            c.close()
        merged = merge_dumps(flight_dumps)
        anatomy_ev = [e for e in merged if e["type"] == "trace.anatomy"]
        assert anatomy_ev, "trace.anatomy missing from the merged timeline"
        assert anatomy_ev[0]["dominant_leg"] == "journal-fsync"
        breach_ev = next(e for e in merged if e["type"] == "slo.breach")
        assert breach_ev["exemplar_trace_ids"]
    finally:
        router.close()
        setup.close()
        _stop_all(leader, *followers)
