"""The de-asyncio'd engine command lane (ISSUE 12).

Batteries:

- the DIRECT lane's mechanics: batch-level ack futures shared across a
  forming batch and rotated at batch-max boundaries, queued-request joins
  (a timed-out caller's retry rides the queued write), slim timer waits;
- cancellation / fencing over the direct lane: caller-timeout rejoin
  (queued AND mid-commit AND in-limbo), revoke-mid-dispatch, fence-mid-lane
  with pipelined FileLog commits, publisher not-owner self-stop;
- the PR-3/4 exactly-once battery parametrized over BOTH lanes and over
  native-on/native-off — the lane change must be invisible to the
  exactly-once contract.
"""

from __future__ import annotations

import asyncio

import pytest

from surge_tpu.common import wait_future
from surge_tpu.config import default_config
from surge_tpu.engine.publisher import (
    PartitionPublisher,
    PublishFailedError,
    PublisherNotReadyError,
)
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.log import native_gate as ng
from surge_tpu.store import StateStoreIndexer

from tests.test_native_gate import NATIVE_MODES

LANES = ["direct", "classic"]


def _cfg(lane: str, **extra):
    over = {
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.producer.command-lane": lane,
    }
    over.update(extra)
    return default_config().with_overrides(over)


def make_log():
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 1))
    log.create_topic(TopicSpec("state", 1, compacted=True))
    return log


def event_rec(agg, value):
    return LogRecord(topic="events", key=agg, value=value, partition=0)


async def start_stack(log, cfg, **pub_kwargs):
    indexer = StateStoreIndexer(log, "state", config=cfg)
    await indexer.start()
    pub = PartitionPublisher(log, "state", "events", 0, indexer, config=cfg,
                             **pub_kwargs)
    await pub.start()
    await pub.wait_ready(5.0)
    return indexer, pub


# -- direct-lane mechanics ---------------------------------------------------


def test_direct_lane_shares_one_ack_per_forming_batch():
    """The tentpole shape itself: pendings of one forming batch share ONE
    future object; the ack rotates at the batch-max-records boundary so a
    drained batch never shares its ack with still-queued pendings."""
    async def scenario():
        log = make_log()
        cfg = _cfg("direct", **{"surge.producer.linger-ms": 50,
                                "surge.producer.flush-interval-ms": 50,
                                "surge.producer.batch-max-records": 3})
        indexer, pub = await start_stack(log, cfg)
        acks = [pub.publish("a", [event_rec("a", b"%d" % i)], f"r{i}")
                for i in range(5)]
        assert all(isinstance(a, asyncio.Future) for a in acks)
        # 3-record batch boundary: r0-r2 share one ack, r3-r4 the next
        assert acks[0] is acks[1] is acks[2]
        assert acks[3] is acks[4]
        assert acks[0] is not acks[3]
        await asyncio.gather(*set(acks))
        assert [r.value for r in log.read("events", 0)] == \
            [b"0", b"1", b"2", b"3", b"4"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_classic_lane_keeps_per_command_futures():
    async def scenario():
        log = make_log()
        cfg = _cfg("classic", **{"surge.producer.linger-ms": 50})
        indexer, pub = await start_stack(log, cfg)
        a1 = pub.publish("a", [event_rec("a", b"x")], "r1")
        a2 = pub.publish("a", [event_rec("a", b"y")], "r2")
        assert a1 is not a2
        await pub.flush_now()
        await asyncio.gather(a1, a2)
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_direct_caller_timeout_rejoins_queued_write_exactly_once():
    """A caller whose slim timer wait times out leaves its records QUEUED;
    the same-request_id retry gets the SAME batch ack (a join, counted as a
    dedup hit) and the write commits exactly once."""
    async def scenario():
        log = make_log()
        cfg = _cfg("direct", **{"surge.producer.linger-ms": 200,
                                "surge.producer.flush-interval-ms": 200})
        indexer, pub = await start_stack(log, cfg)
        ack = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        with pytest.raises(asyncio.TimeoutError):
            await wait_future(ack, 0.01, owned=False)  # entity-style timeout
        assert not ack.cancelled()  # the shared ack survives the timeout
        rejoin = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        assert rejoin is ack
        assert pub.stats.dedup_hits == 1
        await pub.flush_now()
        await wait_future(ack, 5.0, owned=False)
        assert [r.value for r in log.read("events", 0)] == [b"e1"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_direct_cancelled_ack_is_refreshed_for_rejoiners():
    """A caller that CANCELS the shared ack outright (the classic reflex)
    must not poison later rejoiners: the retry gets a fresh future wired to
    the same queued write, which still commits exactly once."""
    async def scenario():
        log = make_log()
        cfg = _cfg("direct", **{"surge.producer.linger-ms": 200,
                                "surge.producer.flush-interval-ms": 200})
        indexer, pub = await start_stack(log, cfg)
        ack = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        ack.cancel()
        rejoin = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        assert rejoin is not ack and not rejoin.done()
        await pub.flush_now()
        await wait_future(rejoin, 5.0, owned=False)
        assert [r.value for r in log.read("events", 0)] == [b"e1"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


@pytest.mark.parametrize("lane", LANES)
def test_caller_timeout_rejoins_mid_commit(lane):
    """Retry arriving while the batch is MID-COMMIT joins the commit outcome
    (the _committing registry) on both lanes."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, _cfg(lane))
        outcome = asyncio.get_running_loop().create_future()
        pub._committing["req-1"] = outcome
        join = asyncio.ensure_future(
            pub.publish("a", [event_rec("a", b"dup")], "req-1"))
        await asyncio.sleep(0.02)
        assert not join.done() and pub._pending == []
        outcome.set_result(None)
        await join
        assert log.end_offset("events", 0) == 0  # nothing re-queued
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


@pytest.mark.parametrize("lane", LANES)
def test_caller_timeout_rejoins_in_limbo_batch(lane):
    """Retry of a request whose batch is stashed for verbatim retry rides
    the in-limbo batch on both lanes — exactly once when it heals."""
    import unittest.mock as mock

    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, _cfg(lane))
        real_commit = pub._producer.commit
        fail = {"n": 2}

        def flaky_commit():
            if fail["n"] > 0:
                fail["n"] -= 1
                raise ConnectionError("transport flapping")
            return real_commit()

        with mock.patch.object(pub._producer, "commit", flaky_commit):
            t1 = asyncio.ensure_future(
                pub.publish("a", [event_rec("a", b"e1")], "req-1"))
            for _ in range(200):
                await asyncio.sleep(0.005)
                if pub._retry_batches:
                    break
            assert pub._retry_batches
            t1.cancel()
            try:
                await t1
            except asyncio.CancelledError:
                pass
            rejoin = asyncio.ensure_future(
                pub.publish("a", [event_rec("a", b"e1")], "req-1"))
            await asyncio.wait_for(rejoin, 5.0)
        assert [r.value for r in log.read("events", 0)] == [b"e1"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


# -- fencing over the direct lane --------------------------------------------


@pytest.mark.parametrize("lane", LANES)
def test_revoke_mid_dispatch_not_owner_self_stops(lane):
    """Fenced while NOT the partition owner: the lane fails the held batch
    and self-stops; nothing half-writes."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, _cfg(lane),
                                         still_owner=lambda: False)
        before = log.end_offset("events", 0)
        log.transactional_producer(pub.transactional_id)  # impostor fences
        with pytest.raises((PublishFailedError, PublisherNotReadyError)):
            await pub.publish("a", [event_rec("a", b"zombie")], "r1")
        assert pub.stats.fences == 1
        assert pub.state == "stopped"
        assert log.end_offset("events", 0) == before
        await indexer.stop()

    asyncio.run(scenario())


@pytest.mark.parametrize("lane", LANES)
def test_fence_mid_lane_still_owner_transparent(lane):
    """Fenced while still the owner: the in-flight batch rides the verbatim
    retry across re-init and commits exactly once, invisibly to callers."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, _cfg(lane),
                                         still_owner=lambda: True)
        log.transactional_producer(pub.transactional_id)  # fence it once
        await pub.publish("a", [event_rec("a", b"held")], "r1")
        await pub.wait_ready(5.0)
        assert pub.stats.reinitializations == 1
        assert [r.value for r in log.read("events", 0)] == [b"held"]
        # a late same-request retry of the held batch is absorbed
        await pub.publish("a", [event_rec("a", b"held")], "r1")
        assert [r.value for r in log.read("events", 0)] == [b"held"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


@pytest.mark.parametrize("native", NATIVE_MODES)
@pytest.mark.parametrize("lane", LANES)
def test_fence_mid_lane_pipelined_filelog(tmp_path, lane, native):
    """Fencing between pipelined FileLog dispatches: stash, re-init, commit
    exactly once — over both lanes AND both gates."""
    from surge_tpu.log.file import FileLog

    async def scenario():
        cfg = _cfg(lane, **{"surge.log.native.enabled": native})
        log = FileLog(str(tmp_path / "log"), config=cfg)
        log.create_topic(TopicSpec("events", 1))
        log.create_topic(TopicSpec("state", 1, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=cfg)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer,
                                 config=cfg, still_owner=lambda: True)
        await pub.start()
        await pub.wait_ready(5.0)
        assert pub._pipeline_capable()
        await pub.publish("a", [event_rec("a", b"before")], "r0")
        log.transactional_producer(pub.transactional_id)  # fence mid-lane
        await asyncio.wait_for(
            pub.publish("a", [event_rec("a", b"held")], "r1"), 10.0)
        await pub.wait_ready(5.0)
        assert pub.stats.reinitializations == 1
        await pub.publish("a", [event_rec("a", b"held")], "r1")  # absorbed
        assert [r.value for r in log.read("events", 0)] == \
            [b"before", b"held"]
        await pub.stop()
        await indexer.stop()
        log.close()

    asyncio.run(scenario())


# -- exactly-once stream battery over lane x native --------------------------


@pytest.mark.parametrize("native", NATIVE_MODES)
@pytest.mark.parametrize("lane", LANES)
def test_exactly_once_stream_battery(tmp_path, lane, native):
    """Concurrent per-aggregate streams through pipelined FileLog commits:
    every record lands exactly once, in order within its aggregate — the
    PR-3/4 contract, unchanged by the lane mode and the native gate."""
    from surge_tpu.log.file import FileLog

    async def scenario():
        cfg = _cfg(lane, **{"surge.log.native.enabled": native,
                            "surge.producer.linger-ms": 0,
                            "surge.producer.max-in-flight": 4})
        log = FileLog(str(tmp_path / "log"), config=cfg)
        log.create_topic(TopicSpec("events", 1))
        log.create_topic(TopicSpec("state", 1, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=cfg)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer,
                                 config=cfg)
        await pub.start()
        await pub.wait_ready(5.0)

        async def stream(agg, n):
            for i in range(n):
                await pub.publish(agg, [event_rec(agg, b"%s-%d" % (
                    agg.encode(), i))], f"{agg}-{i}")

        await asyncio.gather(*(stream(f"agg{j}", 8) for j in range(5)))
        values = [r.value for r in log.read("events", 0)]
        assert len(values) == 40 and len(set(values)) == 40
        for j in range(5):
            seq = [v for v in values if v.startswith(b"agg%d-" % j)]
            assert seq == sorted(seq, key=lambda v: int(v.split(b"-")[-1]))
        await pub.stop()
        await indexer.stop()
        log.close()

    asyncio.run(scenario())


# -- the slim wait primitive --------------------------------------------------


def test_wait_future_owned_timeout_cancels_and_raises():
    async def scenario():
        fut = asyncio.get_running_loop().create_future()
        with pytest.raises(asyncio.TimeoutError):
            await wait_future(fut, 0.01)
        assert fut.cancelled()

    asyncio.run(scenario())


def test_wait_future_shared_timeout_leaves_future_alone():
    async def scenario():
        fut = asyncio.get_running_loop().create_future()
        with pytest.raises(asyncio.TimeoutError):
            await wait_future(fut, 0.01, owned=False)
        assert not fut.done()
        fut.set_result("late")
        assert await wait_future(fut, 1.0, owned=False) == "late"

    asyncio.run(scenario())


def test_wait_future_outer_cancel_not_swallowed():
    """An outer task cancellation must surface as CancelledError — never be
    misread as a timeout (the py3.10 wait_for swallow class)."""
    async def scenario():
        loop = asyncio.get_running_loop()
        for owned in (True, False):
            fut = loop.create_future()
            state = {}

            async def waiter():
                try:
                    await wait_future(fut, 5.0, owned=owned)
                except asyncio.CancelledError:
                    state["outcome"] = "cancelled"
                    raise
                except asyncio.TimeoutError:  # pragma: no cover — the bug
                    state["outcome"] = "timeout"

            t = asyncio.ensure_future(waiter())
            await asyncio.sleep(0.01)
            t.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t
            assert state["outcome"] == "cancelled", owned
            if not owned:
                assert not fut.done()  # shared future untouched

    asyncio.run(scenario())


def test_wait_future_propagates_result_and_exception():
    async def scenario():
        loop = asyncio.get_running_loop()
        f1 = loop.create_future()
        loop.call_later(0.01, f1.set_result, 42)
        assert await wait_future(f1, 5.0) == 42
        f2 = loop.create_future()
        loop.call_later(0.01, f2.set_exception, ValueError("boom"))
        with pytest.raises(ValueError):
            await wait_future(f2, 5.0, owned=False)

    asyncio.run(scenario())


def test_direct_slow_path_cancel_does_not_kill_shared_ack():
    """A slow-path publish (coroutine, cancel-on-timeout wrapper) whose task
    is cancelled must NOT cancel the shared batch ack its siblings ride —
    the slow-path tail awaits the ack shielded."""
    async def scenario():
        log = make_log()
        cfg = _cfg("direct", **{"surge.producer.linger-ms": 200,
                                "surge.producer.flush-interval-ms": 200})
        indexer, pub = await start_stack(log, cfg)
        # a sibling on the fast path shares the forming batch's ack
        sibling = pub.publish("a", [event_rec("a", b"sib")], "r-sib")
        slow = asyncio.ensure_future(
            pub._publish_slow("b", [event_rec("b", b"slow")], "r-slow"))
        await asyncio.sleep(0.01)
        slow.cancel()
        try:
            await slow
        except asyncio.CancelledError:
            pass
        assert not sibling.cancelled()  # the shared ack survived
        await pub.flush_now()
        await wait_future(sibling, 5.0, owned=False)
        assert sorted(r.value for r in log.read("events", 0)) == \
            [b"sib", b"slow"]  # both queued writes committed exactly once
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_wait_future_shared_inner_cancel_surfaces_as_retryable():
    """A shared future cancelled by ANOTHER holder surfaces to innocent
    waiters as a plain retryable RuntimeError, never CancelledError (which
    would blow through the entity retry ladder)."""
    async def scenario():
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        loop.call_later(0.01, fut.cancel)
        with pytest.raises(RuntimeError):
            await wait_future(fut, 5.0, owned=False)

    asyncio.run(scenario())


def test_wait_future_shared_already_cancelled_fast_path():
    """The done-fast-path honors the shared contract too: an ALREADY
    cancelled shared future raises the retryable RuntimeError, never
    CancelledError."""
    async def scenario():
        fut = asyncio.get_running_loop().create_future()
        fut.cancel()
        with pytest.raises(RuntimeError):
            await wait_future(fut, 1.0, owned=False)

    asyncio.run(scenario())


def test_slow_path_join_converts_coholder_cancel_to_retryable():
    """A co-holder cancelling the shared ack while a slow-path rejoiner is
    parked on it surfaces as retryable PublishFailedError to the rejoiner
    (the retry ladder rejoins by request id) — never CancelledError."""
    async def scenario():
        log = make_log()
        cfg = _cfg("direct", **{"surge.producer.linger-ms": 200,
                                "surge.producer.flush-interval-ms": 200})
        indexer, pub = await start_stack(log, cfg)
        ack = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        join = asyncio.ensure_future(
            pub._publish_slow("a", [event_rec("a", b"e1")], "req-1"))
        await asyncio.sleep(0.01)
        ack.cancel()  # the co-holder's classic reflex
        with pytest.raises(PublishFailedError):
            await join
        # the records are still queued; the retry commits exactly once
        rejoin = pub.publish("a", [event_rec("a", b"e1")], "req-1")
        await pub.flush_now()
        await wait_future(rejoin, 5.0, owned=False)
        assert [r.value for r in log.read("events", 0)] == [b"e1"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())
