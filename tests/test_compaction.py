"""Log compaction subsystem: retained-set policy, backend rewrites (in-memory +
file with the crash-safe generational swap), dirty-ratio scheduling, indexer
behavior over compaction holes, and the operator surfaces (admin RPC, CLI).

The crash test is the tentpole's safety contract: a compactor killed between
the ``.tmp`` write and the manifest update must leave recovery reading the OLD
segment, never a torn or half-swapped one.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import grpc
import pytest

from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
from surge_tpu.admin import AdminClient, AdminServer
from surge_tpu.log import FileLog, InMemoryLog, LogRecord, TopicSpec
from surge_tpu.log.compactor import (
    LogCompactor,
    dirty_ratio,
    select_retained,
)
from surge_tpu.models import counter
from surge_tpu.store import StateStoreIndexer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fill(log, topic="state", keys=5, records=40, partition=0, tombstone=None):
    prod = log.transactional_producer(f"fill-{topic}-{partition}-{time.time()}")
    for i in range(records):
        prod.begin()
        prod.send(LogRecord(topic=topic, key=f"k{i % keys}",
                            value=f"v{i}".encode(), partition=partition))
        prod.commit()
    if tombstone is not None:
        prod.begin()
        prod.send(LogRecord(topic=topic, key=tombstone, value=None,
                            partition=partition))
        prod.commit()


# -- policy -----------------------------------------------------------------------------


def test_select_retained_latest_per_key_and_tombstone_gc():
    now = time.time()
    recs = [
        LogRecord(topic="t", key="a", value=b"1", offset=0, timestamp=now - 100),
        LogRecord(topic="t", key=None, value=b"", offset=1, timestamp=now),  # marker
        LogRecord(topic="t", key="b", value=b"2", offset=2, timestamp=now - 100),
        LogRecord(topic="t", key="a", value=b"3", offset=3, timestamp=now - 50),
        LogRecord(topic="t", key="b", value=None, offset=4, timestamp=now - 90),
        LogRecord(topic="t", key="c", value=b"4", offset=5, timestamp=now - 10),
    ]
    # young tombstone retained
    retained, dropped = select_retained(recs, now=now, tombstone_retention_s=3600)
    assert [r.offset for r in retained] == [3, 4, 5]
    assert dropped == 0
    # expired tombstone GC'd; keyless marker always dropped
    retained, dropped = select_retained(recs, now=now, tombstone_retention_s=10)
    assert [r.offset for r in retained] == [3, 5]
    assert dropped == 1
    # the final record survives even as an expired tombstone (keep-tail)
    tail = recs + [LogRecord(topic="t", key="c", value=None, offset=6,
                             timestamp=now - 90)]
    retained, dropped = select_retained(tail, now=now, tombstone_retention_s=10)
    assert retained[-1].offset == 6
    assert dropped == 1  # only b's tombstone; c's was resurrected by keep-tail


# -- in-memory backend ------------------------------------------------------------------


def test_inmemory_compaction_preserves_log_contract():
    log = InMemoryLog()
    log.create_topic(TopicSpec("state", 1, compacted=True))
    _fill(log, records=40, keys=5, tombstone="k0")
    end = log.end_offset("state", 0)
    latest = {k: (r.offset, r.value) for k, r in log.latest_by_key("state", 0).items()}

    stats = log.compact_partition("state", 0, tombstone_retention_s=0.0)
    assert stats.records_dropped > 0 and stats.bytes_reclaimed > 0
    # offsets, end_offset and the compacted view are all preserved
    assert log.end_offset("state", 0) == end
    assert {k: (r.offset, r.value)
            for k, r in log.latest_by_key("state", 0).items()} == latest
    offsets = [r.offset for r in log.read("state", 0)]
    assert offsets == sorted(offsets) and offsets[-1] == end - 1
    # reads from inside a hole land on the next surviving record
    assert log.read("state", 0, from_offset=1)[0].offset >= 1
    # appends continue at the preserved end offset
    prod = log.transactional_producer("after")
    prod.begin()
    prod.send(LogRecord(topic="state", key="k9", value=b"post"))
    rec = prod.commit()[0]
    assert rec.offset == end
    assert dirty_ratio(log, "state", 0) > 0


def test_inmemory_latest_by_key_is_incremental_index():
    log = InMemoryLog()
    log.create_topic(TopicSpec("state", 1, compacted=True))
    _fill(log, records=30, keys=3, tombstone="k1")
    # the index answers without a partition scan: mutate the backing list to
    # prove reads don't re-derive it (white-box, but that is the point)
    view = log.latest_by_key("state", 0)
    assert set(view) == {"k0", "k2"}
    log._partitions[("state", 0)].clear()
    assert set(log.latest_by_key("state", 0)) == {"k0", "k2"}


# -- file backend -----------------------------------------------------------------------


def test_file_compaction_survives_reopen(tmp_path):
    root = str(tmp_path / "log")
    log = FileLog(root)
    log.create_topic(TopicSpec("state", 2, compacted=True))
    for p in (0, 1):
        _fill(log, records=30, keys=4, partition=p, tombstone="k0")
    views = {p: {k: (r.offset, r.value)
                 for k, r in log.latest_by_key("state", p).items()}
             for p in (0, 1)}
    ends = {p: log.end_offset("state", p) for p in (0, 1)}
    st = log.compact_partition("state", 0, tombstone_retention_s=1e9)
    assert st.bytes_reclaimed > 0
    log.close()

    log2 = FileLog(root)
    for p in (0, 1):
        assert log2.end_offset("state", p) == ends[p]
        assert {k: (r.offset, r.value)
                for k, r in log2.latest_by_key("state", p).items()} == views[p]
    # appends after reopen continue the preserved offset space, and a second
    # compaction (new generation) still round-trips
    prod = log2.transactional_producer("again")
    prod.begin()
    prod.send(LogRecord(topic="state", key="k1", value=b"post", partition=0))
    assert prod.commit()[0].offset == ends[0]
    log2.compact_partition("state", 0, tombstone_retention_s=0.0)
    log2.close()
    log3 = FileLog(root)
    assert log3.end_offset("state", 0) == ends[0] + 1
    assert log3.latest_by_key("state", 0)["k1"].value == b"post"
    log3.close()
    # exactly one live segment per partition remains in data/
    segs = [n for n in os.listdir(os.path.join(root, "data"))
            if n.startswith("state-0")]
    assert len(segs) == 1, segs


def test_file_compaction_crash_between_tmp_and_manifest(tmp_path, monkeypatch):
    """Kill the compactor after the .tmp write but before the swap commits:
    recovery must read the OLD segment bit-for-bit and sweep the orphan."""
    root = str(tmp_path / "log")
    log = FileLog(root)
    log.create_topic(TopicSpec("state", 1, compacted=True))
    _fill(log, records=25, keys=3)
    before_recs = [(r.offset, r.key, r.value) for r in log.read("state", 0)]
    before_view = {k: (r.offset, r.value)
                   for k, r in log.latest_by_key("state", 0).items()}

    real_replace = os.replace

    def crash_replace(src, dst):
        if src.endswith(".seg.tmp"):  # the compactor's rename — "crash" here
            raise OSError("injected crash between tmp write and rename")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", crash_replace)
    with pytest.raises(OSError, match="injected crash"):
        log.compact_partition("state", 0, tombstone_retention_s=0.0)
    monkeypatch.undo()
    log.close()  # no clean shutdown help: recovery does the work

    log2 = FileLog(root)
    assert [(r.offset, r.key, r.value)
            for r in log2.read("state", 0)] == before_recs
    assert {k: (r.offset, r.value)
            for k, r in log2.latest_by_key("state", 0).items()} == before_view
    # the interrupted swap left no .tmp / orphan generation behind
    leftovers = [n for n in os.listdir(os.path.join(root, "data"))
                 if ".tmp" in n or ".g" in n]
    assert leftovers == [], leftovers
    # and a re-run of the compaction completes normally
    st = log2.compact_partition("state", 0, tombstone_retention_s=0.0)
    assert st.bytes_reclaimed > 0
    assert {k: (r.offset, r.value)
            for k, r in log2.latest_by_key("state", 0).items()} == before_view
    log2.close()


def test_file_compaction_crash_after_rename_before_manifest(tmp_path, monkeypatch):
    """The other half of the swap window: the generational file is renamed into
    place but the manifest write dies. The manifest still names the old file,
    so recovery reads it and sweeps the newer orphan generation."""
    root = str(tmp_path / "log")
    log = FileLog(root)
    log.create_topic(TopicSpec("state", 1, compacted=True))
    _fill(log, records=25, keys=3)
    before_recs = [(r.offset, r.key, r.value) for r in log.read("state", 0)]

    real_persist = FileLog._persist_json

    def crash_persist(self, name, obj):
        if name == "compaction.json":
            raise OSError("injected crash before manifest update")
        return real_persist(self, name, obj)

    monkeypatch.setattr(FileLog, "_persist_json", crash_persist)
    with pytest.raises(OSError, match="injected crash"):
        log.compact_partition("state", 0, tombstone_retention_s=0.0)
    monkeypatch.undo()
    log.close()

    log2 = FileLog(root)
    assert [(r.offset, r.key, r.value)
            for r in log2.read("state", 0)] == before_recs
    leftovers = [n for n in os.listdir(os.path.join(root, "data"))
                 if ".tmp" in n or ".g" in n]
    assert leftovers == [], leftovers
    log2.close()


# -- indexer over holes -----------------------------------------------------------------


def test_indexer_fast_forwards_over_compaction_hole():
    async def scenario():
        log = InMemoryLog()
        log.create_topic(TopicSpec("state", 1, compacted=True))
        _fill(log, records=30, keys=3)
        idx = StateStoreIndexer(log, "state", config=default_config().with_overrides(
            {"surge.state-store.commit-interval-ms": 10}))
        await idx.start()
        for _ in range(200):
            if idx.total_lag() == 0:
                break
            await asyncio.sleep(0.01)
        assert idx.total_lag() == 0

        # wind the indexer back (a restart analog), compact the log so its
        # resume offset now points into a hole — the tail loop must
        # fast-forward to end_offset instead of stalling forever
        idx._watermarks[0] = 5
        log.compact_partition("state", 0, tombstone_retention_s=0.0)
        for _ in range(200):
            if idx.indexed_watermark("state", 0) >= log.end_offset("state", 0):
                break
            await asyncio.sleep(0.01)
        assert idx.indexed_watermark("state", 0) == log.end_offset("state", 0)
        assert idx.total_lag() == 0
        await idx.stop()

    asyncio.run(scenario())


# -- scheduler --------------------------------------------------------------------------


def test_compactor_dirty_ratio_scheduling():
    async def scenario():
        log = InMemoryLog()
        log.create_topic(TopicSpec("state", 1, compacted=True))
        log.create_topic(TopicSpec("events", 1))  # non-compacted: never touched
        _fill(log, records=50, keys=5)
        _fill(log, topic="events", records=10, keys=10)
        cfg = default_config().with_overrides({
            "surge.log.compaction.min-dirty-records": 10,
            "surge.log.compaction.min-dirty-ratio": 0.5,
            "surge.log.compaction.tombstone-retention-ms": 0,
        })
        comp = LogCompactor(log, config=cfg)
        assert dirty_ratio(log, "state", 0) == 1.0
        stats = await comp.compact_once()
        assert [s.topic for s in stats] == ["state"]
        assert dirty_ratio(log, "state", 0) == 0.0
        # below both gates now: a second pass is a no-op…
        assert await comp.compact_once() == []
        # …until enough new dirt accumulates
        _fill(log, records=9, keys=1)
        assert await comp.compact_once() == []  # 9 < min-dirty-records
        _fill(log, records=20, keys=1)
        stats = await comp.compact_once()
        assert len(stats) == 1 and stats[0].records_dropped > 0
        # forced pass (the admin path) ignores the gates
        assert len(await comp.compact_once(force=True)) == 1
        assert log.end_offset("events", 0) == 10  # untouched

    asyncio.run(scenario())


# -- admin RPC --------------------------------------------------------------------------


def test_admin_compact_rpc_and_background_compactor():
    async def scenario():
        cfg = default_config().with_overrides({
            "surge.producer.flush-interval-ms": 5,
            "surge.producer.ktable-check-interval-ms": 5,
            "surge.state-store.commit-interval-ms": 20,
            "surge.engine.num-partitions": 2,
            "surge.log.compaction.enabled": True,
            "surge.log.compaction.interval-ms": 60_000,  # RPC does the work
        })
        engine = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), config=cfg)
        await engine.start()
        for i in range(30):
            await engine.aggregate_for(f"a-{i % 4}").send_command(
                counter.Increment(f"a-{i % 4}"))
        assert "log-compactor" in engine.health_supervisor.registered()

        admin = AdminServer(engine)
        port = await admin.start()
        client = AdminClient(grpc.aio.insecure_channel(f"127.0.0.1:{port}"))
        stats = await client.compact_log()
        assert stats and all(s["topic"] == "counter-state" for s in stats)
        assert sum(s["bytes_reclaimed"] for s in stats) > 0
        values = engine.metrics_registry.get_metrics()
        assert values["surge.log.compaction.runs"] >= len(stats)
        # no checkpoint path configured: the RPC reports that, not a crash
        ok, detail = await client.write_checkpoint()
        assert not ok and "checkpoint" in detail
        # the engine still serves and the store survives a post-compaction read
        r = await engine.aggregate_for("a-1").send_command(
            counter.Increment("a-1"))
        assert r.state.count > 1
        await admin.stop()
        await engine.stop()

    asyncio.run(scenario())


# -- CLI --------------------------------------------------------------------------------


def test_compact_log_cli_smoke(tmp_path):
    root = str(tmp_path / "log")
    log = FileLog(root)
    log.create_topic(TopicSpec("state", 2, compacted=True))
    for p in (0, 1):
        _fill(log, records=25, keys=3, partition=p)
    log.close()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "compact_log.py"),
         root, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout)
    assert out["bytes_reclaimed"] > 0
    assert {s["partition"] for s in out["partitions"]} == {0, 1}
    # the compacted root reopens clean and serves the compacted view
    log2 = FileLog(root)
    assert set(log2.latest_by_key("state", 0)) == {"k0", "k1", "k2"}
    log2.close()
