"""Control plane: epoch-CAS allocation updates (dual-leader closure), heartbeat
expiry, auto-rebalance, and the remote mirror wiring (VERDICT r2 weak #5)."""

import asyncio

from surge_tpu.engine.partition import HostPort
from surge_tpu.remote.control_plane import ControlPlaneClient, ControlPlaneServer
from surge_tpu.remote import control_plane_pb2 as pb

A = pb.Member(host="a", port=1)
B = pb.Member(host="b", port=2)


def test_stale_epoch_and_non_leader_allocations_rejected():
    """The dual-leader window: during churn two nodes may both believe they are
    the lowest-address leader; the server's CAS + leader check lets only one win."""
    async def scenario():
        server = ControlPlaneServer(num_partitions=4)
        state_a = await server.Join(pb.JoinRequest(member=A), None)
        state_b = await server.Join(pb.JoinRequest(member=B), None)
        assert state_b.epoch > state_a.epoch

        # B (not leader — A is lower) tries to allocate: rejected
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=B, observed_epoch=state_b.epoch, locations={0: "b:2"}), None)
        assert not ack.ok and "not leader" in ack.error

        # A with a STALE epoch (the one from before B joined): rejected, told now
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=state_a.epoch, locations={0: "a:1"}), None)
        assert not ack.ok and "stale epoch" in ack.error
        current = ack.epoch

        # A at the current epoch: accepted, epoch advances
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=current, locations={0: "a:1"}), None)
        assert ack.ok and ack.epoch == current + 1

    asyncio.run(scenario())


def test_auto_balance_and_departure_pruning():
    async def scenario():
        server = ControlPlaneServer(num_partitions=4)
        await server.Join(pb.JoinRequest(member=A), None)
        state = await server.Join(pb.JoinRequest(member=B), None)
        parts = {m: list(pl.partitions) for m, pl in state.assignments.items()}
        assert sorted(p for ps in parts.values() for p in ps) == [0, 1, 2, 3]
        assert all(len(ps) == 2 for ps in parts.values())

        # allocations for the departed member are pruned server-side
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=state.epoch,
            locations={0: "a:1", 1: "b:2", 2: "a:1", 3: "b:2"}), None)
        assert ack.ok
        await server.Leave(pb.MemberRequest(member=B), None)
        state = server._state_msg()
        assert set(state.shard_locations.values()) == {"a:1"}
        assert list(state.assignments) == ["a:1"]
        assert list(state.assignments["a:1"].partitions) == [0, 1, 2, 3]

    asyncio.run(scenario())


def test_heartbeat_expiry_removes_member():
    async def scenario():
        server = ControlPlaneServer(num_partitions=2, member_timeout_s=0.3)
        await server.start()
        try:
            await server.Join(pb.JoinRequest(member=A), None)
            await server.Join(pb.JoinRequest(member=B), None)

            async def keepalive():
                for _ in range(12):
                    await server.Ping(pb.MemberRequest(member=A), None)
                    await asyncio.sleep(0.1)

            await keepalive()  # B never pings; A stays
            members = [(m.host, m.port) for m in server._state_msg().members]
            assert members == [("a", 1)]
            # expired member's ping is told to re-join
            ack = await server.Ping(pb.MemberRequest(member=B), None)
            assert not ack.ok
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_client_mirrors_apply_epoch_ordered_state():
    async def scenario():
        server = ControlPlaneServer(num_partitions=4, member_timeout_s=5.0)
        port = await server.start()
        try:
            peers_seen = []
            client = ControlPlaneClient(
                f"127.0.0.1:{port}", HostPort("node-x", 0),
                transport_target="127.0.0.1:9999",
                on_peers=lambda t: peers_seen.append(dict(t)))
            await client.start()
            try:
                assert client.membership.members == [HostPort("node-x", 0)]
                assert (client.tracker.assignments.assignments
                        [HostPort("node-x", 0)] == [0, 1, 2, 3])
                assert peers_seen[-1][HostPort("node-x", 0)] == "127.0.0.1:9999"

                # a second member joins directly; the watch stream applies it
                await server.Join(pb.JoinRequest(member=pb.Member(
                    host="node-y", port=0, transport_target="127.0.0.1:8888")), None)
                for _ in range(50):
                    if len(client.membership.members) == 2:
                        break
                    await asyncio.sleep(0.05)
                assert len(client.membership.members) == 2
                assert peers_seen[-1][HostPort("node-y", 0)] == "127.0.0.1:8888"
                # rebalance split the partitions
                assign = client.tracker.assignments.assignments
                assert sorted(p for ps in assign.values() for p in ps) == [0, 1, 2, 3]
            finally:
                await client.stop()
        finally:
            await server.stop()

    asyncio.run(scenario())
