"""Control plane: epoch-CAS allocation updates (dual-leader closure), heartbeat
expiry, auto-rebalance, and the remote mirror wiring (VERDICT r2 weak #5)."""

import asyncio

from surge_tpu.engine.partition import HostPort
from surge_tpu.remote.control_plane import ControlPlaneClient, ControlPlaneServer
from surge_tpu.remote import control_plane_pb2 as pb

A = pb.Member(host="a", port=1)
B = pb.Member(host="b", port=2)


def test_stale_epoch_and_non_leader_allocations_rejected():
    """The dual-leader window: during churn two nodes may both believe they are
    the lowest-address leader; the server's CAS + leader check lets only one win."""
    async def scenario():
        server = ControlPlaneServer(num_partitions=4)
        state_a = await server.Join(pb.JoinRequest(member=A), None)
        state_b = await server.Join(pb.JoinRequest(member=B), None)
        assert state_b.epoch > state_a.epoch

        # B (not leader — A is lower) tries to allocate: rejected
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=B, observed_epoch=state_b.epoch, locations={0: "b:2"}), None)
        assert not ack.ok and "not leader" in ack.error

        # A with a STALE epoch (the one from before B joined): rejected, told now
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=state_a.epoch, locations={0: "a:1"}), None)
        assert not ack.ok and "stale epoch" in ack.error
        current = ack.epoch

        # A at the current epoch: accepted, epoch advances
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=current, locations={0: "a:1"}), None)
        assert ack.ok and ack.epoch == current + 1

    asyncio.run(scenario())


def test_auto_balance_and_departure_pruning():
    async def scenario():
        server = ControlPlaneServer(num_partitions=4)
        await server.Join(pb.JoinRequest(member=A), None)
        state = await server.Join(pb.JoinRequest(member=B), None)
        parts = {m: list(pl.partitions) for m, pl in state.assignments.items()}
        assert sorted(p for ps in parts.values() for p in ps) == [0, 1, 2, 3]
        assert all(len(ps) == 2 for ps in parts.values())

        # allocations for the departed member are pruned server-side
        ack = await server.UpdateShardLocations(pb.AllocateRequest(
            member=A, observed_epoch=state.epoch,
            locations={0: "a:1", 1: "b:2", 2: "a:1", 3: "b:2"}), None)
        assert ack.ok
        await server.Leave(pb.MemberRequest(member=B), None)
        state = server._state_msg()
        assert set(state.shard_locations.values()) == {"a:1"}
        assert list(state.assignments) == ["a:1"]
        assert list(state.assignments["a:1"].partitions) == [0, 1, 2, 3]

    asyncio.run(scenario())


def test_heartbeat_expiry_removes_member():
    async def scenario():
        server = ControlPlaneServer(num_partitions=2, member_timeout_s=0.3)
        await server.start()
        try:
            await server.Join(pb.JoinRequest(member=A), None)
            await server.Join(pb.JoinRequest(member=B), None)

            async def keepalive():
                for _ in range(12):
                    await server.Ping(pb.MemberRequest(member=A), None)
                    await asyncio.sleep(0.1)

            await keepalive()  # B never pings; A stays
            members = [(m.host, m.port) for m in server._state_msg().members]
            assert members == [("a", 1)]
            # expired member's ping is told to re-join
            ack = await server.Ping(pb.MemberRequest(member=B), None)
            assert not ack.ok
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_client_mirrors_apply_epoch_ordered_state():
    async def scenario():
        server = ControlPlaneServer(num_partitions=4, member_timeout_s=5.0)
        port = await server.start()
        try:
            peers_seen = []
            client = ControlPlaneClient(
                f"127.0.0.1:{port}", HostPort("node-x", 0),
                transport_target="127.0.0.1:9999",
                on_peers=lambda t: peers_seen.append(dict(t)))
            await client.start()
            try:
                assert client.membership.members == [HostPort("node-x", 0)]
                assert (client.tracker.assignments.assignments
                        [HostPort("node-x", 0)] == [0, 1, 2, 3])
                assert peers_seen[-1][HostPort("node-x", 0)] == "127.0.0.1:9999"

                # a second member joins directly; the watch stream applies it
                await server.Join(pb.JoinRequest(member=pb.Member(
                    host="node-y", port=0, transport_target="127.0.0.1:8888")), None)
                for _ in range(50):
                    if len(client.membership.members) == 2:
                        break
                    await asyncio.sleep(0.05)
                assert len(client.membership.members) == 2
                assert peers_seen[-1][HostPort("node-y", 0)] == "127.0.0.1:8888"
                # rebalance split the partitions
                assign = client.tracker.assignments.assignments
                assert sorted(p for ps in assign.values() for p in ps) == [0, 1, 2, 3]
            finally:
                await client.stop()
        finally:
            await server.stop()

    asyncio.run(scenario())


def test_seed_restart_under_traffic_preserves_state_and_routing(tmp_path):
    """VERDICT r3 next #7: the seed persists (epoch, members, assignments,
    allocations) to disk; killing and restarting it under command traffic loses
    no commands — the restarted seed resumes with a CONTINUED epoch and the
    restored member/assignment state, and a post-restart rebalance (node kill)
    still converges."""
    from surge_tpu import SurgeCommandBusinessLogic, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.log import InMemoryLog, LogServer, GrpcLogTransport
    from surge_tpu.models import counter
    from surge_tpu.remote.node import EngineNode

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 4,
        "surge.control-plane.ping-interval-ms": 100,
        "surge.control-plane.member-timeout-ms": 1_000,
        "surge.state-store.num-standby-replicas": 1,
    })
    persist = str(tmp_path / "seed.json")

    def logic():
        return SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting(),
            command_format=counter.command_formatting())

    async def send_retrying(node, agg, deadline_s=20.0):
        loop = asyncio.get_running_loop()
        end = loop.time() + deadline_s
        last = None
        while loop.time() < end:
            try:
                r = await node.aggregate_for(agg).send_command(
                    counter.Increment(agg))
            except Exception as exc:  # noqa: BLE001 — routing churn window
                last = exc
                await asyncio.sleep(0.2)
                continue
            if isinstance(r, CommandSuccess):
                return r
            last = r
            await asyncio.sleep(0.2)
        raise AssertionError(f"command to {agg} never succeeded: {last}")

    async def scenario():
        broker = LogServer(InMemoryLog())
        lport = broker.start()
        seed = ControlPlaneServer(num_partitions=4, persist_path=persist,
                                  config=cfg)
        cport = await seed.start()

        nodes = {}
        for name in ("alpha", "beta"):
            nodes[name] = EngineNode(
                logic(), f"127.0.0.1:{cport}",
                GrpcLogTransport(f"127.0.0.1:{lport}"), node_name=name,
                config=cfg)
            await nodes[name].start()
        for _ in range(100):
            if all(len(n.client.membership.members) >= 2
                   for n in nodes.values()):
                break
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)

        aggs = [f"s{i}" for i in range(8)]
        for agg in aggs:
            r = await send_retrying(nodes["alpha"], agg)
            assert r.state.count == 1
        epoch_before = seed.epoch
        assert epoch_before > 0

        # SEED DIES under traffic; routing keeps working off local state
        await seed.stop(grace=0.2)
        for agg in aggs:
            r = await send_retrying(nodes["alpha"], agg)
            assert r.state.count == 2

        # restart from disk on the same port: epoch continues, members restored
        seed2 = ControlPlaneServer(num_partitions=4, port=cport,
                                   persist_path=persist, config=cfg)
        await seed2.start()
        assert seed2.epoch >= epoch_before
        assert len(seed2._members) == 2  # restored, not re-learned
        await asyncio.sleep(0.5)  # ping loops re-attach

        # post-restart rebalance still converges: kill beta, alpha takes over
        await nodes["beta"].stop()
        for _ in range(100):
            if len(nodes["alpha"].client.membership.members) == 1:
                break
            await asyncio.sleep(0.05)
        for agg in aggs:
            r = await send_retrying(nodes["alpha"], agg)
            assert r.state.count == 3, (agg, r.state)

        await nodes["alpha"].stop()
        await seed2.stop()
        broker.stop()

    asyncio.run(scenario())
