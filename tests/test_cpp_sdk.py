"""Second-language SDK: the C++ BankAccount app against the Python sidecar.

The reference proves its sidecar protocol is language-neutral with a C# SDK
(multilanguage-csharp-sdk/SurgeEngine.cs:12-80); here a NATIVE C++ app
(sdk/cpp — gRPC over the system libnghttp2 + libprotobuf, no Python anywhere
in the app process) hosts the BusinessLogic service and drives commands
through the MultilanguageGateway. The app's payloads are opaque to the engine
(its own pipe-delimited format), so the whole loop — command processing,
event folds, rejections, state reads — crosses a real language boundary."""

import asyncio
import os
import shutil
import subprocess
import sys

import grpc
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SDK_DIR = os.path.join(REPO, "sdk", "cpp")
BINARY = os.path.join(SDK_DIR, "build", "bank_account")


def _toolchain_missing() -> str:
    if not shutil.which("g++") or not shutil.which("protoc"):
        return "g++/protoc not in this image"
    if not os.path.exists("/lib/x86_64-linux-gnu/libnghttp2.so.14"):
        return "system libnghttp2 not present"
    return ""


def _build() -> None:
    """Lazy (test-time, not collection-time) cached build of the sample app."""
    sources = ["surge_sdk.cc", "surge_sdk.h", "bank_account_main.cc",
               "nghttp2_api.h"]
    newest = max(os.path.getmtime(os.path.join(SDK_DIR, s)) for s in sources)
    if os.path.exists(BINARY) and os.path.getmtime(BINARY) >= newest:
        return
    proc = subprocess.run(["sh", os.path.join(SDK_DIR, "build.sh")],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise AssertionError(f"C++ SDK build failed:\n{proc.stderr}")


def test_cpp_bank_account_round_trip():
    missing = _toolchain_missing()
    if missing:
        pytest.skip(missing)
    _build()
    from surge_tpu import default_config
    from surge_tpu.dsl import create_engine
    from surge_tpu.multilanguage import (
        MultilanguageGatewayServer,
        generic_business_logic,
    )

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 2,
    })

    async def scenario():
        # 1. spawn the C++ app: it binds its BusinessLogic service (ephemeral),
        #    prints READY <port>, and retries connecting to the gateway address
        #    it was given until the sidecar (started below, wired to the app's
        #    port) comes up.
        from conftest import free_ports

        (gateway_port,) = free_ports(1)

        app = subprocess.Popen(
            [BINARY, "127.0.0.1", str(gateway_port), "0", "scenario"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            ready = await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(
                    None, app.stdout.readline), 10.0)
            assert ready.startswith("READY "), ready
            app_port = int(ready.split()[1])

            # 2. the sidecar: engine whose model is gRPC calls into the C++ app
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{app_port}")
            engine = create_engine(
                generic_business_logic("cppbank", channel), config=cfg)
            await engine.start()
            gateway = MultilanguageGatewayServer(engine, port=gateway_port)
            await gateway.start()

            # 3. the app runs its scenario (create/credit/debit/rejection/
            #    get_state) and exits 0 only if every step behaved
            out, err = await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(
                    None, app.communicate), 60.0)
            assert app.returncode == 0, f"stdout={ready}{out}\nstderr={err}"
            assert "SCENARIO PASS" in out

            # 4. the engine really persisted the C++ app's folds: read the
            #    state back through the engine (payloads are the app's own
            #    pipe format, opaque to Python until here)
            st = await engine.aggregate_for("acct-cpp-1").get_state()
            assert st == b"ada|50", st
            st = await engine.aggregate_for("acct-cpp-2").get_state()
            assert st == b"bob|5", st

            await gateway.stop()
            await engine.stop()
            await channel.close()
        finally:
            if app.poll() is None:
                app.kill()
                app.wait(5)

    asyncio.run(scenario())
