"""The device observatory (ISSUE 16): the refresh-round ledger's recording
sites and roofline rollup, the padding-waste accounting reproducing the
BENCH_NOTES round-9 ~9x over-dispatch on a steady ragged round, cause-split
fallback counters, the federation round-trip of every new device instrument
into surgetop rows, the fold anatomy's device legs off a seeded
device-dispatch stall (trace_anatomy names `device-dispatch` dominant), the
`resident-fold-efficiency` burn page firing and clearing on the merged
flight+ledger timeline, the DumpReplayLedger admin RPC + chaos CLI, and the
roofline recorder's append-only JSONL trajectory."""

import asyncio
import json
import os
import sys

import pytest

from surge_tpu.config import Config, default_config
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.metrics import Metrics, engine_metrics
from surge_tpu.models import counter
from surge_tpu.observability import (
    DEFAULT_SLOS,
    FlightRecorder,
    RooflineRecorder,
    against_reference,
    merge_dumps,
    roofline_row,
)
from surge_tpu.observability.slo import SLOEngine
from surge_tpu.replay.ledger import ReplayLedger, shard_skew, waste_ratio
from surge_tpu.replay.profiler import ReplayProfiler
from surge_tpu.replay.resident_state import ResidentStatePlane
from surge_tpu.serialization import SerializedMessage
from surge_tpu.testing.faults import FaultPlane, FaultRule
from surge_tpu.tracing import Tracer
from surge_tpu.tracing.tail import install_tail

EVT = counter.event_formatting()
STATE = counter.state_formatting()
TOPIC = "counter-events"
NPART = 4


def part_of(agg: str) -> int:
    return int(agg.rsplit("-", 1)[1]) % NPART


def append_events(log, events):
    prod = log.transactional_producer("seed")
    prod.begin()
    for ev in events:
        msg = EVT.write_event(ev)
        prod.send(LogRecord(topic=TOPIC, partition=part_of(ev.aggregate_id),
                            key=msg.key, value=msg.value))
    prod.commit()


def make_log():
    log = InMemoryLog()
    log.create_topic(TopicSpec(TOPIC, NPART))
    return log


def make_plane(log, *, metrics=None, profiler=None, flight=None, ledger=None,
               tracer=None, faults=None, overrides=None):
    cfg = default_config().with_overrides({
        "surge.replay.resident.capacity": 64,
        "surge.replay.resident.refresh-interval-ms": 10,
        "surge.replay.batch-size": 16,
        "surge.replay.time-chunk": 8,
        **(overrides or {}),
    })
    return ResidentStatePlane(
        log, TOPIC, counter.make_replay_spec(), config=cfg,
        deserialize_event=lambda raw: EVT.read_event(
            SerializedMessage(key="", value=raw)),
        serialize_state=lambda a, s: STATE.write_state(s).value,
        metrics=metrics, profiler=profiler, flight=flight, ledger=ledger,
        tracer=tracer, faults=faults)


def events_for(aggs, per_agg, seqs=None):
    seqs = seqs if seqs is not None else {}
    out = []
    for agg in aggs:
        for _ in range(per_agg):
            seqs[agg] = seqs.get(agg, 0) + 1
            out.append(counter.CountIncremented(agg, 1, seqs[agg]))
    return out


# -- the ledger itself ----------------------------------------------------------------


def test_waste_and_skew_helpers():
    assert waste_ratio(512, 50) == pytest.approx(10.24)
    assert waste_ratio(0, 0) == 0.0  # no work, not "perfectly packed"
    assert waste_ratio(64, 0) == 0.0
    assert shard_skew(None) == 1.0
    assert shard_skew([]) == 1.0
    assert shard_skew([0, 0]) == 1.0
    assert shard_skew([4, 4, 4, 4]) == 1.0
    assert shard_skew([8, 2, 2, 4]) == pytest.approx(2.0)


def test_ledger_records_rounds_and_rolls_up_the_roofline():
    led = ReplayLedger(capacity=8, name="engine:t")
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=100.0,
                     encode_us=40.0, dispatch_us=400.0,
                     deal_sizes=[4, 2, 2, 2], causes={"lag-exceeded": 2},
                     evictions=1)
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=120.0,
                     encode_us=40.0, dispatch_us=400.0)
    led.record_gather(reads=8, rows=8, wait_us=30.0, dispatch_us=200.0,
                      fetch_us=50.0, decode_us=20.0)
    led.record_query(rows=3, scanned=100, matched=40, elapsed_us=900.0)
    led.record_evict(2, resident=60, cause="capacity")

    s = led.summary()
    assert s["rounds"] == 2 and s["events"] == 100
    assert s["dispatched_slots"] == 1024 and s["occupied_slots"] == 100
    assert s["waste_ratio"] == pytest.approx(10.24)
    assert s["us_per_slot"] == pytest.approx(800.0 / 1024, rel=1e-3)
    assert s["us_per_event"] == pytest.approx(8.0)
    assert s["fold_events_per_sec"] == pytest.approx(100 / (800.0 / 1e6))
    assert s["gathers"] == 1 and s["gathered_rows"] == 8
    assert s["queries"] == 1 and s["query_rows"] == 3

    stages = led.round_stages_us()
    assert stages["feed_us"] == [100.0, 120.0]
    assert stages["dispatch_us"] == [400.0, 400.0]
    assert stages["waste"] == [10.24, 10.24]

    by_type = {}
    for ev in led.events():
        by_type.setdefault(ev["type"], []).append(ev)
    assert set(by_type) == {"round", "gather", "query", "evict"}
    rd = by_type["round"][0]
    assert rd["waste"] == 10.24 and rd["skew"] == 1.6  # max 4 / mean 2.5
    assert rd["causes"] == {"lag-exceeded": 2} and rd["evictions"] == 1
    assert by_type["query"][0]["selectivity"] == pytest.approx(0.4)


def test_ledger_dump_is_a_merge_ready_flight_envelope():
    """The dump interleaves with engine flight dumps on one timeline (the
    acceptance criterion: a stalled round is visible next to the burn page
    that named it) and carries the roofline summary alongside."""
    flight = FlightRecorder(name="engine:t", role="engine")
    led = ReplayLedger(name="engine:t")
    flight.record("slo.breach", objective="resident-fold-efficiency")
    led.record_round(events=5, lanes=1, windows=1, dispatched=64, occupied=5,
                     batch=8, width=8, feed_us=1.0, encode_us=1.0,
                     dispatch_us=9.0)
    flight.record("slo.recovered", objective="resident-fold-efficiency")

    dump = led.dump()
    assert dump["role"] == "ledger" and isinstance(dump["summary"], dict)
    assert dump["summary"]["waste_ratio"] == pytest.approx(12.8)
    merged = merge_dumps([flight.dump(), dump])
    assert [e["type"] for e in merged] == ["slo.breach", "round",
                                           "slo.recovered"]
    assert merged[1]["lane"] == "ledger"
    # bounded ring: the ledger never grows past its capacity
    small = ReplayLedger(capacity=8)
    for i in range(20):
        small.record_round(events=1, lanes=1, windows=1, dispatched=8,
                           occupied=1, batch=8, width=1, feed_us=0.0,
                           encode_us=0.0, dispatch_us=1.0)
    assert len(list(small.events())) == 8
    assert small.totals["rounds"] == 20  # totals survive ring eviction


def test_ledger_ring_wrap_around_keeps_newest_and_counts_dropped():
    """Wrap-around semantics an operator relies on mid-incident: the ring
    keeps the NEWEST capacity rounds, the envelope's dropped counter says
    how many fell off, and the per-round sequence stays monotonic across
    the wrap (merge_dumps ordering survives eviction)."""
    led = ReplayLedger(capacity=8, name="engine:t")
    for i in range(30):
        led.record_round(events=i, lanes=1, windows=1, dispatched=8,
                         occupied=1, batch=8, width=1, feed_us=0.0,
                         encode_us=0.0, dispatch_us=1.0)
    events = led.events()
    assert len(events) == 8
    # newest survive, oldest dropped: rounds 22..29 by the events payload
    assert [e["events"] for e in events] == list(range(22, 30))
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    dump = led.dump()
    assert dump["stats"]["dropped"] == 22
    assert dump["stats"]["capacity"] == 8
    assert len(dump["events"]) == 8
    # a sub-minimum capacity clamps to the floor instead of losing rounds
    tiny = ReplayLedger(capacity=1)
    for i in range(10):
        tiny.record_round(events=i, lanes=1, windows=1, dispatched=8,
                          occupied=1, batch=8, width=1, feed_us=0.0,
                          encode_us=0.0, dispatch_us=1.0)
    assert len(tiny.events()) == 8  # deque floor: max(capacity, 8)


def test_ledger_dump_last_n_truncation_bounds():
    """The dump's tail truncation (the DumpReplayLedger ``last:N``
    convention): N below the count keeps the newest N, N at/beyond the
    count is the whole ring, and 0 is empty — never an error."""
    led = ReplayLedger(capacity=16, name="engine:t")
    for i in range(10):
        led.record_round(events=i, lanes=1, windows=1, dispatched=8,
                         occupied=1, batch=8, width=1, feed_us=0.0,
                         encode_us=0.0, dispatch_us=1.0)
    assert [e["events"] for e in led.dump(last=3)["events"]] == [7, 8, 9]
    assert len(led.dump(last=10)["events"]) == 10
    assert len(led.dump(last=500)["events"]) == 10  # beyond count: all
    assert led.dump(last=0)["events"] == []
    assert len(led.dump()["events"]) == 10  # no tail: everything


def test_admin_dump_replay_ledger_last_n_truncates_over_the_wire():
    """The ``last:N`` tail rides ComponentRequest.name through the REAL
    DumpReplayLedger RPC: the reply's events are truncated server-side to
    the newest N (an incident dump must not ship the whole ring)."""
    import grpc
    from types import SimpleNamespace

    from surge_tpu.admin import AdminClient, AdminServer

    led = ReplayLedger(capacity=64, name="engine:t")
    for i in range(12):
        led.record_round(events=i, lanes=1, windows=1, dispatched=8,
                         occupied=1, batch=8, width=1, feed_us=0.0,
                         encode_us=0.0, dispatch_us=1.0)

    async def scenario():
        admin = AdminServer(SimpleNamespace(replay_ledger=led))
        port = await admin.start()
        try:
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            client = AdminClient(channel)
            payload = await client.replay_ledger_dump(last=4)
            assert [e["events"] for e in payload["events"]] == [8, 9, 10, 11]
            payload = await client.replay_ledger_dump(last=500)
            assert len(payload["events"]) == 12  # beyond count: everything
            payload = await client.replay_ledger_dump()
            assert len(payload["events"]) == 12
            await channel.close()
        finally:
            await admin.stop()

    asyncio.run(scenario())


# -- padding-waste accounting on a REAL refresh round ---------------------------------


def test_steady_ragged_round_reproduces_roofline_overdispatch():
    """The acceptance anchor: a synthetic steady-ragged round (10 aggregates
    x 5 events) must reproduce the BENCH_NOTES round-9 over-dispatch within
    tolerance — pow8(10)=64 lanes x pow2(5)=8 slots dispatched for 50 real
    events is ~10.2x, squarely in the published ~9x regime's band. Pinned to
    the DENSE dispatch arm: the bucketing PR (ROADMAP item 2 / ISSUE 18)
    moved the default below this band, which is its acceptance criterion —
    tests/test_ragged_refresh.py asserts the bucketed side."""
    async def scenario():
        log = make_log()
        registry = Metrics()
        led = ReplayLedger(name="engine:t")
        plane = make_plane(log, metrics=engine_metrics(registry), ledger=led,
                           overrides={
                               "surge.replay.resident.refresh-dispatch":
                               "dense"})
        plane._ensure_device_state()
        plane.seed_from_log()  # empty log: anchors watermarks, folds nothing
        append_events(log, events_for([f"agg-{i}" for i in range(10)], 5))
        assert await plane._refresh_once()

        s = led.summary()
        assert s["rounds"] == 1 and s["events"] == 50
        assert s["occupied_slots"] == 50
        # the exact grid is pow8(lanes) x pow2(events-per-lane) per window;
        # assert the published band rather than the literal 512 so a better
        # bucketing PR moves this test, not breaks it silently
        assert 6.0 <= s["waste_ratio"] <= 16.0, s
        (rd,) = [e for e in led.events() if e["type"] == "round"]
        assert rd["dispatched"] == rd["batch"] * rd["width"] * rd["windows"]
        assert rd["dispatch_us"] > 0 and rd["feed_us"] > 0

        snap = registry.get_metrics()
        assert 6.0 <= snap["surge.replay.resident.padding-waste-ratio"] <= 16.0
        assert snap["surge.replay.resident.round-events"] == 50
        assert snap["surge.replay.resident.dispatch-occupancy"] == \
            pytest.approx(1.0 / snap["surge.replay.resident.padding-waste-ratio"])
        assert snap["surge.replay.resident.shard-skew"] == 1.0  # single-device
        assert snap["surge.replay.resident.events-per-dispatch-us"] > 0
        await plane.stop()

    asyncio.run(scenario())


def test_gather_lane_records_legs_and_read_path_still_serves():
    async def scenario():
        log = make_log()
        aggs = [f"agg-{i}" for i in range(12)]
        append_events(log, events_for(aggs, 3))
        led = ReplayLedger(name="engine:t")
        plane = make_plane(log, ledger=led)
        await plane.start()
        try:
            results = await asyncio.gather(
                *(plane.read_state(a) for a in aggs))
            assert all(hit for hit, _ in results)
            gathers = [e for e in led.events() if e["type"] == "gather"]
            assert gathers and sum(g["rows"] for g in gathers) == 12
            for g in gathers:
                assert g["wait_us"] >= 0 and g["dispatch_us"] > 0
            assert led.summary()["gathered_rows"] == 12
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- cause-split fallback counters ----------------------------------------------------


def test_fallback_causes_split_and_sum_to_the_flat_counter():
    async def scenario():
        log = make_log()
        registry = Metrics()
        plane = make_plane(log, metrics=engine_metrics(registry),
                           overrides={
                               "surge.replay.resident.max-lag-records": 4})
        plane._ensure_device_state()
        append_events(log, events_for(["agg-0"], 4))
        plane.seed_from_log()
        # untracked: a ghost aggregate the plane never admitted
        hit, _ = await plane.read_state("ghost-1")
        assert not hit
        # lag-exceeded: the log moves past the bound with no refresh loop
        append_events(log, events_for(["agg-0"], 8, seqs={"agg-0": 4}))
        hit, _ = await plane.read_state("agg-0")
        assert not hit
        assert (await plane.read_many(["agg-0"])) == {}

        assert plane.fallback_causes == {"untracked": 1, "lag-exceeded": 2}
        assert plane.stats["fallbacks"] == 3
        snap = registry.get_metrics()
        flat = snap["surge.replay.resident.fallback-reads"]
        causes = {
            c: snap[f"surge.replay.resident.fallback-reads.{c}"]
            for c in ("lag-exceeded", "lane-error", "unschema-poison",
                      "untracked")}
        assert causes == {"lag-exceeded": 2.0, "lane-error": 0.0,
                          "unschema-poison": 0.0, "untracked": 1.0}
        assert sum(causes.values()) == flat == 3.0
        await plane.stop()

    asyncio.run(scenario())


def test_unschema_poison_fallbacks_carry_their_own_cause():
    async def scenario():
        log = make_log()
        registry = Metrics()
        append_events(log, events_for(["agg-0"], 2))
        prod = log.transactional_producer("poison")
        prod.begin()
        msg = EVT.write_event(
            counter.ExceptionThrowingEvent("agg-0", 3, "boom"))
        prod.send(LogRecord(topic=TOPIC, partition=part_of("agg-0"),
                            key=msg.key, value=msg.value))
        prod.commit()
        plane = make_plane(log, metrics=engine_metrics(registry))
        await plane.start()
        try:
            hit, _ = await plane.read_state("agg-0")
            assert not hit  # poisoned off the tensor path
            assert plane.fallback_causes.get("unschema-poison", 0) >= 1
            snap = registry.get_metrics()
            assert snap[
                "surge.replay.resident.fallback-reads.unschema-poison"] >= 1
        finally:
            await plane.stop()

    asyncio.run(scenario())


# -- federation round-trip + surgetop row extraction ----------------------------------


def test_device_instruments_federate_into_surgetop_rows():
    """Engine quiver -> merged fleet exposition -> surgetop row: every new
    device instrument survives the round-trip with its recorded value (the
    golden fleet scrape records the steady-ragged shape)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import surgetop

    from tests.test_federation import golden_fleet_scrape

    scraper = golden_fleet_scrape()
    text = scraper.render()
    for family in ("surge_replay_resident_padding_waste_ratio",
                   "surge_replay_resident_dispatch_occupancy",
                   "surge_replay_resident_events_per_dispatch_us",
                   "surge_replay_resident_round_events",
                   "surge_replay_resident_shard_skew",
                   "surge_replay_resident_fallback_reads_lag_exceeded_total",
                   "surge_replay_resident_fallback_reads_unschema_poison_total",
                   "surge_query_scan_rows_total",
                   "surge_query_pushdown_selectivity"):
        assert f'{family}{{instance="engine-0"' in text, family

    rows = surgetop.fleet_rows(scraper, anatomy=False)
    row = next(r for r in rows if r["instance"] == "engine-0")
    assert row["waste"] == 9.0
    assert row["ev/us"] == 0.125
    assert row["skew"] == 1.25
    broker = next(r for r in rows if r["instance"] == "broker-0")
    assert broker["waste"] is None  # no slab on a broker: renders "-"
    frame = surgetop.render_table(rows, [], {"up": 2, "targets": 2,
                                             "errors": {}})
    assert "waste" in frame and "9.0" in frame


# -- fold anatomy: seeded device-dispatch stall ---------------------------------------


def test_seeded_dispatch_stall_dominates_trace_anatomy(tmp_path, capsys):
    """The acceptance e2e: a fault-plane delay on the refresh executor's
    `resident.refresh.dispatch` site lands inside the measured dispatch
    stage, the stage span breaches the tail sampler's latency bound and is
    kept, and `trace_anatomy.py --format=json` names `device-dispatch` the
    dominant leg of the assembled dump."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace_anatomy

    async def scenario():
        log = make_log()
        tracer = Tracer(service="engine")
        ring = install_tail(tracer, Config(overrides={
            "surge.trace.tail.latency-ms": 150,
        }), name="engine:t", role="engine")
        faults = FaultPlane()
        faults.arm([FaultRule(site="resident.refresh.dispatch",
                              action="delay", delay_ms=250.0, times=1)])
        plane = make_plane(
            log, profiler=ReplayProfiler.counters(tracer=tracer),
            tracer=tracer, faults=faults)
        plane._ensure_device_state()
        plane.seed_from_log()
        append_events(log, events_for([f"agg-{i}" for i in range(4)], 3))
        assert await plane._refresh_once()
        await plane.stop()
        assert faults.stats()["injected"] == 1
        return ring.dump()

    dump = asyncio.run(scenario())
    assert dump["traces"], "the stalled round's trace was not tail-kept"
    path = tmp_path / "engine_traces.json"
    path.write_text(json.dumps(dump))
    assert trace_anatomy.main([str(path), "--format=json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["traces"] >= 1
    assert verdict["dominant"] == "device-dispatch", verdict
    assert verdict["legs"]["device-dispatch"]["total_ms"] >= 200.0


# -- the resident-fold-efficiency burn page -------------------------------------------


def test_fold_efficiency_burn_page_fires_and_clears_on_the_timeline():
    """Sustained waste past the bound pages (both windows), the breach and
    the offending rounds interleave on one merged flight+ledger timeline,
    and steady-ragged waste (~9x) recovers the objective."""
    from surge_tpu.metrics.exposition import Family, Sample

    slo = [s for s in DEFAULT_SLOS if s.name == "resident-fold-efficiency"]
    assert slo, "resident-fold-efficiency missing from DEFAULT_SLOS"
    flight = FlightRecorder(name="engine:t", role="engine")
    led = ReplayLedger(name="engine:t")
    eng = SLOEngine(slo, config=Config(overrides={
        "surge.slo.fast-window-ms": 10_000,
        "surge.slo.slow-window-ms": 40_000,
        "surge.slo.burn-threshold": 2.0,
    }), flight=flight)

    def fams(waste):
        fam = Family(name="surge_replay_resident_padding_waste_ratio",
                     mtype="gauge", help="")
        fam.samples.append(Sample("", (("instance", "engine-0"),), waste))
        return {fam.name: fam}

    def round_at(waste):
        occupied = 50
        led.record_round(events=occupied, lanes=10, windows=1,
                         dispatched=int(waste * occupied), occupied=occupied,
                         batch=64, width=8, feed_us=100.0, encode_us=40.0,
                         dispatch_us=400.0)

    # steady ragged (~9x): under the 16x bound, never pages
    for t in range(0, 41, 5):
        round_at(9.0)
        eng.evaluate(fams(9.0), now=float(t))
    assert eng.breached() == []
    # the lane mix degenerates: sustained 24x burns BOTH windows -> one page
    for t in range(45, 100, 5):
        round_at(24.0)
        eng.evaluate(fams(24.0), now=float(t))
    assert eng.breached() == ["resident-fold-efficiency"]
    round_at(24.0)  # one degenerate round strictly after the page fired
    # the stall clears: healthy rounds age the burn out of both windows
    for t in range(100, 200, 5):
        round_at(9.0)
        eng.evaluate(fams(9.0), now=float(t))
    assert eng.breached() == []
    assert [e["type"] for e in flight.events()] == ["slo.breach",
                                                    "slo.recovered"]

    merged = merge_dumps([flight.dump(), led.dump()])
    types = [e["type"] for e in merged]
    assert "slo.breach" in types and "slo.recovered" in types
    # the degenerate rounds are ON the timeline, between page and clear
    breach_i = types.index("slo.breach")
    recover_i = types.index("slo.recovered")
    bad_lanes = [e for e in merged[breach_i:recover_i]
                 if e.get("type") == "round" and e.get("waste", 0) > 16.0]
    assert bad_lanes and all(e["lane"] == "ledger" for e in bad_lanes)


# -- DumpReplayLedger RPC + chaos CLI -------------------------------------------------


def test_admin_dump_replay_ledger_round_trip():
    """The DumpReplayLedger admin RPC serves the merge-ready envelope (with
    the roofline summary and last:N tail); an engine without the observatory
    is a clean client-side error."""
    from types import SimpleNamespace

    import grpc

    from surge_tpu.admin import AdminClient, AdminServer

    led = ReplayLedger(name="engine:t")
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=100.0,
                     encode_us=40.0, dispatch_us=400.0)

    async def scenario():
        admin = AdminServer(SimpleNamespace(replay_ledger=led))
        port = await admin.start()
        try:
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            payload = await AdminClient(channel).replay_ledger_dump()
            assert payload["role"] == "ledger"
            assert payload["summary"]["waste_ratio"] == pytest.approx(10.24)
            assert [e["type"] for e in payload["events"]] == ["round"]
            # last:N plumbs through
            led.record_round(events=1, lanes=1, windows=1, dispatched=8,
                             occupied=1, batch=8, width=1, feed_us=0.0,
                             encode_us=0.0, dispatch_us=1.0)
            payload = await AdminClient(channel).replay_ledger_dump(last=1)
            assert len(payload["events"]) == 1
            await channel.close()
        finally:
            await admin.stop()

        # the observatory-less engine: error payload, client raises
        bare = AdminServer(SimpleNamespace())
        bare_port = await bare.start()
        try:
            ch2 = grpc.aio.insecure_channel(f"127.0.0.1:{bare_port}")
            with pytest.raises(RuntimeError, match="no replay ledger"):
                await AdminClient(ch2).replay_ledger_dump()
            await ch2.close()
        finally:
            await bare.stop()

    asyncio.run(scenario())


def test_chaos_replay_ledger_subcommand(capsys):
    """`chaos.py replay-ledger` prints the envelope as JSON (the tier-1 CLI
    smoke); a down engine is a reported finding, exit 1. The admin server
    runs on a background-thread loop because the subcommand spins its own
    asyncio.run."""
    import threading
    from types import SimpleNamespace

    from surge_tpu.admin import AdminServer

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import chaos

    led = ReplayLedger(name="engine:t")
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=100.0,
                     encode_us=40.0, dispatch_us=400.0)
    admin = AdminServer(SimpleNamespace(replay_ledger=led))
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    try:
        port = asyncio.run_coroutine_threadsafe(
            admin.start(), loop).result(timeout=10)
        rc = chaos.main(["replay-ledger", f"127.0.0.1:{port}", "--last", "8"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["role"] == "ledger" and "summary" in out
        assert all(e["type"] == "round" for e in out["events"])
        asyncio.run_coroutine_threadsafe(admin.stop(), loop).result(timeout=10)
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
        loop.close()
    # a dead endpoint is a reported finding, exit 1
    rc = chaos.main(["replay-ledger", "127.0.0.1:1"])
    err = json.loads(capsys.readouterr().out)
    assert rc == 1 and "error" in err


# -- roofline recorder ----------------------------------------------------------------


def test_roofline_recorder_appends_rows_and_compares(tmp_path):
    led = ReplayLedger(name="engine:t")
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=100.0,
                     encode_us=40.0, dispatch_us=400.0)
    path = str(tmp_path / "nested" / "roofline.jsonl")
    rec = RooflineRecorder(path)
    assert rec.latest() is None and list(rec.rows()) == []

    row = rec.record(led.summary(), source="test", note="r1", wall=1000.0)
    assert row["waste_ratio"] == pytest.approx(10.24)
    assert row["us_per_slot"] == pytest.approx(400.0 / 512, rel=1e-3)
    assert row["wall"] == 1000.0 and row["source"] == "test"
    rec.record(led.summary(), source="test", note="r2", wall=2000.0)
    rows = list(rec.rows())
    assert [r["note"] for r in rows] == ["r1", "r2"]
    assert rec.latest()["note"] == "r2"
    # a torn tail line (crashed writer) is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"torn": ')
    assert len(list(rec.rows())) == 2

    ratios = against_reference(rows[0], "steady-ragged-cpu")
    assert ratios["waste_ratio"] == pytest.approx(10.24 / 9.0, rel=1e-3)
    assert ratios["us_per_slot"] == pytest.approx((400.0 / 512) / 8.0,
                                                  rel=1e-2)
    with pytest.raises(KeyError):
        against_reference(rows[0], "no-such-anchor")
    # roofline_row survives a summary missing optional keys
    assert roofline_row({"waste_ratio": 2.0}, wall=1.0)["waste_ratio"] == 2.0


def test_roofline_record_cli_reads_dumps_and_compares(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import roofline_record

    led = ReplayLedger(name="engine:t")
    led.record_round(events=50, lanes=10, windows=1, dispatched=512,
                     occupied=50, batch=64, width=8, feed_us=100.0,
                     encode_us=40.0, dispatch_us=400.0)
    dump = tmp_path / "ledger_dump.json"
    dump.write_text(json.dumps(led.dump()))
    out = tmp_path / "roofline.jsonl"

    rc = roofline_record.main([str(dump), "--out", str(out),
                               "--compare", "steady-ragged-cpu"])
    printed = capsys.readouterr().out.strip().splitlines()
    assert rc == 0 and len(printed) == 2
    row = json.loads(printed[0])
    assert row["waste_ratio"] == pytest.approx(10.24)
    assert row["source"] == "ledger_dump.json"
    cmp_row = json.loads(printed[1])
    assert cmp_row["anchor"] == "steady-ragged-cpu"
    assert cmp_row["ratios"]["waste_ratio"] == pytest.approx(10.24 / 9.0,
                                                             rel=1e-3)
    assert len(list(RooflineRecorder(str(out)).rows())) == 1

    # bad inputs: both/neither source, no-summary dump, unknown anchor
    capsys.readouterr()
    assert roofline_record.main(["--out", str(out)]) == 2
    bare = tmp_path / "bare.json"
    bare.write_text("{}")
    assert roofline_record.main([str(bare), "--out", str(out)]) == 2
    assert roofline_record.main([str(dump), "--out", str(out),
                                 "--compare", "nope"]) == 2
    capsys.readouterr()
