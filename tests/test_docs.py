"""Executable documentation: every complete ```python block in docs/*.md runs
as a spec (the reference executes its BankAccount docs sample the same way —
BankAccountCommandEngineSpec.scala:19-35). A snippet that rots fails CI.

Rules:
- blocks within one file execute in order, in one shared namespace, inside one
  async context (so top-level ``await`` works exactly as written);
- blocks containing ``...`` are illustrative fragments and are skipped;
- the documented durable path ``/var/lib/surge`` is redirected to a tmp dir.
"""

import asyncio
import os
import re
import textwrap

import pytest

DOCS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "docs")
BLOCK_RE = re.compile(r"```python\n(.*?)```", re.DOTALL)

# files whose python blocks are full programs (the rest are prose-only or
# intentionally fragmentary, filtered by the `...` rule anyway)
EXECUTABLE_DOCS = ["getting-started.md", "replay.md", "event-engine.md",
                   "multilanguage.md", "testing.md"]


def extract_blocks(name: str) -> list:
    with open(os.path.join(DOCS, name)) as f:
        text = f.read()
    return [b for b in BLOCK_RE.findall(text) if "..." not in b]


@pytest.mark.parametrize("doc", EXECUTABLE_DOCS)
def test_doc_snippets_execute(doc, tmp_path):
    from conftest import free_ports

    blocks = extract_blocks(doc)
    assert blocks, f"{doc} has no executable python blocks"
    source = "\n".join(blocks)
    source = source.replace("/var/lib/surge", str(tmp_path / "surge"))
    # the docs use fixed narrative ports; isolate concurrent test runs by
    # substituting distinct free ephemeral ones
    for narrative_port, port in zip(("16000", "17000"), free_ports(2)):
        source = source.replace(narrative_port, str(port))
    program = ("async def __doc_main__():\n"
               + textwrap.indent(source, "    ")
               + "\n")
    namespace: dict = {}
    code = compile(program, f"docs/{doc}", "exec")
    exec(code, namespace)  # noqa: S102 — executing our own documentation

    async def run():
        await asyncio.wait_for(namespace["__doc_main__"](), timeout=60.0)

    asyncio.run(run())
