"""End-to-end engine: create_engine → start → send_command/get_state → stop.

The SurgeMessagePipelineSpec / docs BankAccountCommandEngineSpec analog (SURVEY.md §4):
full wiring (tracker → router → regions → publisher → indexer) over the in-memory log,
multi-partition routing, engine restart resuming state from the log, and the TPU
events-topic rebuild wired into engine cold start."""

import asyncio

import pytest

from surge_tpu import (
    CommandRejected,
    CommandSuccess,
    SurgeCommandBusinessLogic,
    SurgeEngineBuilder,
    create_engine,
    default_config,
)
from surge_tpu.engine.pipeline import EngineNotRunningError, EngineStatus
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 4,
    "surge.replay.batch-size": 16,
    "surge.replay.time-chunk": 8,
})


def make_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


def test_engine_lifecycle_and_commands_across_partitions():
    async def scenario():
        engine = create_engine(make_logic(), config=CFG)
        assert engine.status == EngineStatus.STOPPED
        await engine.start()
        assert engine.status == EngineStatus.RUNNING

        # aggregates spread over partitions; all must route correctly
        agg_ids = [f"agg{i}" for i in range(12)]
        partitions = {engine.router.partition_for(a) for a in agg_ids}
        assert len(partitions) > 1
        for agg in agg_ids:
            r = await engine.aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess), r
        r = await engine.aggregate_for("agg0").send_command(counter.Increment("agg0"))
        assert r.state.count == 2

        rej = await engine.aggregate_for("agg1").send_command(
            counter.FailCommandProcessing("agg1", "no"))
        assert isinstance(rej, CommandRejected)

        await engine.stop()
        assert engine.status == EngineStatus.STOPPED
        with pytest.raises(EngineNotRunningError):
            engine._deliver_checked("agg0", None)

    asyncio.run(scenario())


def test_engine_restart_resumes_from_log():
    async def scenario():
        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for _ in range(3):
            r = await engine.aggregate_for("agg7").send_command(counter.Increment("agg7"))
        assert r.state.count == 3
        await engine.stop()

        # a brand-new engine over the same log: state survives (the log IS the store)
        engine2 = create_engine(make_logic(), log=log, config=CFG)
        await engine2.start()
        state = None
        for _ in range(100):
            r = await engine2.aggregate_for("agg7").send_command(counter.Increment("agg7"))
            if isinstance(r, CommandSuccess):
                state = r.state
                break
            await asyncio.sleep(0.02)
        assert state is not None and state.count == 4 and state.version == 4
        await engine2.stop()

    asyncio.run(scenario())


def test_builder_surface():
    async def scenario():
        engine = (SurgeEngineBuilder()
                  .with_business_logic(make_logic())
                  .with_config(CFG)
                  .with_log(InMemoryLog())
                  .build())
        await engine.start()
        r = await engine.aggregate_for("a").send_command(counter.Increment("a"))
        assert isinstance(r, CommandSuccess)
        await engine.stop()

    asyncio.run(scenario())

    with pytest.raises(ValueError):
        SurgeEngineBuilder().build()


def test_rebuild_from_events_on_cold_start():
    async def scenario():
        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for i in range(10):
            agg = f"agg{i}"
            for _ in range(i % 4 + 1):
                await engine.aggregate_for(agg).send_command(counter.Increment(agg))
        await engine.stop()

        # cold start with restore-on-start: store is rebuilt by folding the events
        # topic through the TPU replay backend before serving
        cfg = CFG.with_overrides({"surge.replay.restore-on-start": True,
                                  "surge.replay.backend": "tpu"})
        engine2 = create_engine(make_logic(), log=log, config=cfg)
        await engine2.start()
        # the store already holds every aggregate before any command arrives
        assert engine2.indexer.store.approximate_num_entries() == 10
        state = engine2.logic.state_format.read_state(
            engine2.indexer.get_aggregate_bytes("agg3"))
        assert state.count == 4  # 3 % 4 + 1
        r = await engine2.aggregate_for("agg3").send_command(counter.Increment("agg3"))
        assert isinstance(r, CommandSuccess) and r.state.count == 5
        await engine2.stop()

    asyncio.run(scenario())


def test_rebalance_listener_sees_assignments():
    async def scenario():
        seen = []
        engine = create_engine(make_logic(), config=CFG)
        engine.register_rebalance_listener(lambda a, c: seen.append(dict(a.assignments)))
        await engine.start()
        assert seen and list(seen[-1].values())[0] == [0, 1, 2, 3]
        await engine.stop()

    asyncio.run(scenario())


def test_resident_plane_serves_reads_end_to_end():
    """surge.replay.resident.enabled: the engine wires the device-resident
    state plane — entity init consults it first (DecodedState, no byte
    round-trip), project_states batches hits into one gather, a tracker
    rebalance retargets plane partitions with the indexer, and the health
    tree grows a resident-plane component."""
    async def scenario():
        cfg = CFG.with_overrides({
            "surge.replay.resident.enabled": True,
            "surge.replay.resident.refresh-interval-ms": 10,
            "surge.aggregate.idle-passivation-ms": 40,
        })
        engine = create_engine(make_logic(), config=cfg)
        await engine.start()
        plane = engine.resident_plane
        assert plane is not None and plane.running
        assert plane.partitions == [0, 1, 2, 3]
        aggs = [f"agg{i}" for i in range(8)]
        for agg in aggs:
            r = await engine.aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess), r
        for _ in range(300):
            if plane.lag_records() == 0 and plane.occupancy() == len(aggs):
                break
            await asyncio.sleep(0.02)
        assert plane.occupancy() == len(aggs)

        # read-side projection: every hit rides the batched gather lane
        proj = await engine.project_states(aggs + ["never-seen"])
        assert set(proj) == set(aggs)
        assert all(proj[a].count == 1 for a in aggs)
        assert plane.stats["gathers"] >= 1

        # passivate, then re-init: the entity state comes from the PLANE
        # (require_current) and the next command folds on top of it
        await asyncio.sleep(0.15)
        gathered = plane.stats["gathered_rows"]
        r = await engine.aggregate_for("agg0").send_command(counter.Increment("agg0"))
        assert isinstance(r, CommandSuccess) and r.state.count == 2
        assert plane.stats["gathered_rows"] > gathered

        hc = engine.health_check()
        assert any(c.name == "resident-plane" and c.status == "up"
                   for c in hc.components)

        # rebalance: the plane follows the indexer's partition view
        engine.tracker.update({engine.local_host: [0, 1]})
        assert set(plane.partitions) >= {0, 1}
        assert set(plane.partitions) == set(engine.indexer.partitions)
        await engine.stop()
        assert not plane.running

    asyncio.run(scenario())


def test_resident_plane_disabled_by_default():
    async def scenario():
        engine = create_engine(make_logic(), config=CFG)
        assert engine.resident_plane is None
        await engine.start()
        r = await engine.aggregate_for("a").send_command(counter.Increment("a"))
        assert isinstance(r, CommandSuccess)
        # no plane: projections come straight from the host KV store
        proj = await engine.project_states(["a", "ghost"])
        assert set(proj) == {"a"} and proj["a"].count == 1
        await engine.stop()

    asyncio.run(scenario())


def test_mesh_sharding_flag_builds_replay_mesh():
    """The enable-mesh-sharding flag must have a real consumer: without an explicit
    mesh, engine replay builds a 1-D data mesh over all visible devices (8 on the
    test CPU backend) and the rebuild still matches."""
    async def scenario():
        import jax

        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for i in range(10):
            await engine.aggregate_for(f"m-{i}").send_command(counter.Increment(f"m-{i}"))
        await engine.stop()

        cfg = CFG.with_overrides({
            "surge.feature-flags.experimental.enable-mesh-sharding": True,
            "surge.replay.batch-size": 16,
        })
        engine2 = create_engine(make_logic(), log=log, config=cfg)
        await engine2.start()
        res = await engine2.rebuild_from_events()
        assert res.num_aggregates == 10
        assert engine2.mesh is not None
        assert engine2.mesh.devices.size == len(jax.devices())
        st = await engine2.aggregate_for("m-3").get_state()
        assert st.count == 1
        await engine2.stop()

    asyncio.run(scenario())


def test_mesh_axis_name_config_is_consistent():
    """Regression: surge.replay.mesh-axes must name the axis in BOTH the engine's
    auto-built mesh and the ReplayEngine shardings."""
    async def scenario():
        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for i in range(6):
            await engine.aggregate_for(f"x-{i}").send_command(counter.Increment(f"x-{i}"))
        await engine.stop()

        cfg = CFG.with_overrides({
            "surge.feature-flags.experimental.enable-mesh-sharding": True,
            "surge.replay.mesh-axes": "batch",
            "surge.replay.batch-size": 16,
        })
        engine2 = create_engine(make_logic(), log=log, config=cfg)
        await engine2.start()
        res = await engine2.rebuild_from_events()
        assert res.num_aggregates == 6
        assert engine2.mesh.axis_names == ("batch",)
        await engine2.stop()

    asyncio.run(scenario())


def test_rebuild_from_segment_cold_start(tmp_path):
    """VERDICT r2 #3: the columnar segment path is wired into the engine's rebuild.
    A cold engine with surge.replay.segment-path builds the segment once (events +
    state-only snapshot carry), streams it through the batched replay, and ends up
    byte-identical to the object-based scalar rebuild — including an aggregate that
    only ever saw apply_events (state-only publish) and post-build deltas picked up
    by indexer tailing from the segment's build watermarks."""
    async def scenario():
        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for i in range(12):
            agg = f"agg{i}"
            for _ in range(i % 4 + 1):
                await engine.aggregate_for(agg).send_command(counter.Increment(agg))
        # a state-only aggregate: apply_events publishes a snapshot but no events
        r = await engine.aggregate_for("state-only").apply_events(
            [counter.CountIncremented("state-only", 7, 1)])
        assert isinstance(r, CommandSuccess) and r.state.count == 7
        await engine.stop()

        seg_path = str(tmp_path / "counter.scol")
        seg_cfg = CFG.with_overrides({"surge.replay.segment-path": seg_path,
                                      "surge.replay.restore-on-start": True})
        engine2 = create_engine(make_logic(), log=log, config=seg_cfg)
        await engine2.start()
        import os
        assert os.path.exists(seg_path)  # built on first rebuild
        assert engine2.indexer.store.approximate_num_entries() == 13
        # the predeclared replay instruments recorded the rebuild (SURVEY §5.5)
        snap = engine2.metrics_registry.get_metrics()
        assert snap["surge.replay.rebuild-events-per-sec"] > 0
        assert snap["surge.replay.rebuild-timer"] > 0
        segment_bytes = {f"agg{i}": engine2.indexer.get_aggregate_bytes(f"agg{i}")
                         for i in range(12)}
        # the state-only aggregate came from the snapshot section
        st = engine2.logic.state_format.read_state(
            engine2.indexer.get_aggregate_bytes("state-only"))
        assert st.count == 7
        # post-build delta: a new command after the segment exists (stale for the
        # NEXT cold start)
        r = await engine2.aggregate_for("agg0").send_command(counter.Increment("agg0"))
        assert isinstance(r, CommandSuccess), r
        expected = r.state.count
        await engine2.stop()

        # byte-identical to the object-based scalar rebuild (engines run
        # sequentially — concurrent ones would fence each other's publishers)
        ref = create_engine(make_logic(), log=log,
                            config=CFG.with_overrides({"surge.replay.backend": "cpu"}))
        await ref.start()
        await ref.rebuild_from_events()
        for i in range(1, 12):  # agg0 has the post-segment delta; compare the rest
            agg = f"agg{i}"
            assert segment_bytes[agg] == ref.indexer.get_aggregate_bytes(agg), agg
        await ref.stop()

        engine3 = create_engine(make_logic(), log=log, config=seg_cfg)
        await engine3.start()  # stale segment: auto-extended with delta chunks
        st = await engine3.aggregate_for("agg0").get_state()
        assert st.count == expected
        await engine3.stop()
        # the second cold start extended the segment in place: its recorded
        # watermarks now cover the post-build traffic (VERDICT r3 next #8)
        from surge_tpu.log.columnar import segment_info
        wm = segment_info(seg_path)["schema"]["extra"]["watermarks"]
        n = seg_cfg.get_int("surge.engine.num-partitions")
        assert {int(p): int(o) for p, o in wm.items()} == {
            p: log.end_offset("counter-events", p) for p in range(n)}

    asyncio.run(scenario())


@pytest.mark.parametrize("use_segment", [False, True])
def test_two_node_cold_restore_is_partition_scoped(tmp_path, use_segment):
    """VERDICT r3 next #3: a multi-node cold start with restore-on-start must do
    1/N of the work — each node's store holds ONLY its owned partitions'
    aggregates, through both the object path and the columnar segment path, and
    the live indexer tails only owned partitions afterward."""
    from surge_tpu.engine.partition import HostPort, PartitionTracker

    host_a, host_b = HostPort("node-a", 1), HostPort("node-b", 2)

    async def scenario():
        log = InMemoryLog()
        seed = create_engine(make_logic(), log=log, config=CFG)
        await seed.start()
        for i in range(24):
            agg = f"agg{i}"
            await seed.aggregate_for(agg).send_command(counter.Increment(agg))
        # a state-only aggregate exercises the snapshot path's scoping too
        await seed.aggregate_for("state-only").apply_events(
            [counter.CountIncremented("state-only", 7, 1)])
        part_of = {f"agg{i}": seed.router.partition_for(f"agg{i}")
                   for i in range(24)}
        part_of["state-only"] = seed.router.partition_for("state-only")
        await seed.stop()

        cfg = CFG.with_overrides({"surge.replay.restore-on-start": True})
        if use_segment:
            cfg = cfg.with_overrides(
                {"surge.replay.segment-path": str(tmp_path / "two.scol")})
        # external tracker: A owns even partitions, B owns odd
        n = cfg.get_int("surge.engine.num-partitions")
        owned = {host_a: [p for p in range(n) if p % 2 == 0],
                 host_b: [p for p in range(n) if p % 2 == 1]}
        stores = {}
        for host in (host_a, host_b):
            tracker = PartitionTracker()
            tracker.update(owned)
            eng = create_engine(make_logic(), log=log, config=cfg,
                                local_host=host, tracker=tracker)
            await eng.start()
            assert sorted(eng.indexer.partitions) == owned[host]
            stores[host] = {k for k, _ in eng.indexer.store.items()} \
                if hasattr(eng.indexer.store, "items") else None
            if stores[host] is None:  # fall back to probing known keys
                stores[host] = {k for k in part_of
                                if eng.indexer.get_aggregate_bytes(k) is not None}
            await eng.stop()

        for host in (host_a, host_b):
            expect = {k for k, p in part_of.items() if p in owned[host]}
            got = {k for k in part_of if k in stores[host]}
            assert got == expect, (host, got ^ expect)

    asyncio.run(scenario())


def test_standby_replica_tails_and_promotes_without_rescan():
    """VERDICT r3 next #4: with num-standby-replicas=1, a node tails the
    partitions it is ring-standby for (watermarks advance while the owner is
    live), exposes the standby-lag gauge, and a rebalance promotion starts from
    the standby watermark — the state-topic is NOT re-read from offset 0."""
    from surge_tpu.engine.partition import HostPort, PartitionTracker

    host_a, host_b = HostPort("node-a", 1), HostPort("node-b", 2)
    cfg = CFG.with_overrides({"surge.state-store.num-standby-replicas": 1})

    class CountingLog(InMemoryLog):
        def __init__(self):
            super().__init__()
            self.reads_from_zero = []

        def read(self, topic, partition, from_offset=0, max_records=None,
                 isolation="read_committed"):
            if from_offset == 0 and "state" in topic:
                self.reads_from_zero.append(partition)
            return super().read(topic, partition, from_offset, max_records,
                                isolation)

    async def scenario():
        log = CountingLog()
        tracker = PartitionTracker()
        owned = {host_a: [0, 1], host_b: [2, 3]}
        tracker.update(owned)
        # node A: standby for B's partitions (2 hosts, ring-next = the peer)
        eng = create_engine(make_logic(), log=log, config=cfg,
                            local_host=host_a, tracker=tracker)
        await eng.start()
        assert eng.standby_partitions() == [2, 3]
        assert sorted(eng.indexer.partitions) == [0, 1, 2, 3]

        # writes landing on B's partitions get tailed by A's standby loops
        bwriter = create_engine(make_logic(), log=log, config=cfg,
                                local_host=host_b, tracker=tracker)
        await bwriter.start()
        b_aggs = [f"b{i}" for i in range(12)
                  if bwriter.router.partition_for(f"b{i}") in (2, 3)][:4]
        assert b_aggs, "need aggregates on B's partitions"
        for agg in b_aggs:
            r = await bwriter.aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess)
        for _ in range(300):
            if all(eng.indexer.indexed_watermark("counter-state", p) > 0
                   for p in (2, 3)):
                break
            await asyncio.sleep(0.01)
        wm_before = {p: eng.indexer.indexed_watermark("counter-state", p)
                     for p in (2, 3)}
        assert all(w > 0 for w in wm_before.values()), wm_before
        # standby store already warm: B's aggregates readable from A's store
        for agg in b_aggs:
            assert eng.indexer.get_aggregate_bytes(agg) is not None
        eng.health_check()
        (lag_metric,) = [m for n, m in eng.metrics_registry.get_metrics().items()
                         if "standby-lag" in n]
        assert lag_metric == 0.0
        await bwriter.stop()

        # promotion: B dies, A gains everything — tail loops resume from the
        # standby watermarks; the state topic is never re-read from offset 0
        log.reads_from_zero.clear()
        tracker.update({host_a: [0, 1, 2, 3]})
        await asyncio.sleep(0.05)
        for p in (2, 3):
            assert eng.indexer.indexed_watermark("counter-state", p) >= wm_before[p]
        for agg in b_aggs:
            st = await eng.aggregate_for(agg).get_state()
            assert st is not None and st.count == 1
        assert not any(p in (2, 3) for p in log.reads_from_zero), \
            log.reads_from_zero
        await eng.stop()

    asyncio.run(scenario())


def test_warm_rebuild_from_stale_segment_does_not_regress_store(tmp_path):
    """Advisor r3 #2: a WARM rebuild through the segment path (indexer watermark
    already past the segment's build watermark) must not revert aggregates to
    their build-time states — the post-build state window is re-applied before
    priming."""
    async def scenario():
        log = InMemoryLog()
        engine = create_engine(make_logic(), log=log, config=CFG)
        await engine.start()
        for _ in range(3):
            await engine.aggregate_for("warm").send_command(counter.Increment("warm"))
        await engine.stop()

        seg_path = str(tmp_path / "counter.scol")
        seg_cfg = CFG.with_overrides({"surge.replay.segment-path": seg_path,
                                      "surge.replay.restore-on-start": True})
        # cold start builds the segment at watermark "count=3"
        engine2 = create_engine(make_logic(), log=log, config=seg_cfg)
        await engine2.start()
        # post-build traffic: the live indexer advances past the build watermark
        for _ in range(2):
            r = await engine2.aggregate_for("warm").send_command(
                counter.Increment("warm"))
        assert r.state.count == 5
        # wait until the tail indexer has actually indexed the new snapshot
        for _ in range(200):
            if engine2.indexer.total_lag() == 0:
                break
            await asyncio.sleep(0.01)
        # WARM rebuild from the now-stale segment (explicit call on the running
        # engine): without the state-window replay the store reverts to count=3
        # and the tail loop never re-reads the already-indexed snapshot
        await engine2.rebuild_from_events()
        st = engine2.logic.state_format.read_state(
            engine2.indexer.get_aggregate_bytes("warm"))
        assert st.count == 5
        st = await engine2.aggregate_for("warm").get_state()
        assert st.count == 5
        await engine2.stop()

    asyncio.run(scenario())
