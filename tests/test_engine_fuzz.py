"""Randomized fault-injection fuzz of the exactly-once command path.

Concurrent workers drive commands through the FULL engine while the log's
transaction commits randomly fail BEFORE the append lands (clean abort — the
entity's retry ladder re-publishes with the same request id).

The transport contract is: ``commit()`` raising means the transaction did NOT
land. In-process transports satisfy it trivially (commit is atomic); the
networked broker transport satisfies it by retrying the SAME ``txn_seq``
against the broker's replicated dedup cache until the outcome is known
(``log/client.py``; exercised in test_log_server/test_log_replication) — so
ambiguous "reply lost" commits never reach the publisher as errors.

Invariants checked at the end against the COMMITTED events topic:

1. exactly one event per acknowledged command — no lost acks, no doubled
   retries (the publisher's request-id dedup + retry-joins-commit machinery);
2. per-aggregate sequence numbers are exactly 1..n with no gaps or duplicates;
3. the final queryable state equals the scalar fold of the committed log.
"""

import asyncio
import random

import pytest

from surge_tpu import (
    CommandSuccess,
    SurgeCommandBusinessLogic,
    create_engine,
    default_config,
)
from surge_tpu.engine.model import fold_events
from surge_tpu.log import InMemoryLog
from surge_tpu.log.memory import InMemoryTxnProducer
from surge_tpu.models import counter

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 10,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.aggregate.publish-max-retries": 10,
    "surge.engine.num-partitions": 2,
})


class _FlakyProducer:
    """Delegates to a real producer; commit() randomly aborts-and-raises
    (the transport-contract-legal failure: raising ⇒ nothing landed)."""

    def __init__(self, inner: InMemoryTxnProducer, rng: random.Random,
                 p_fail: float) -> None:
        self._inner = inner
        self._rng = rng
        self._p_fail = p_fail

    def commit(self):
        if self._rng.random() < self._p_fail:
            self._inner.abort()
            raise RuntimeError("injected: commit failed (nothing landed)")
        return self._inner.commit()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _FlakyLog(InMemoryLog):
    def __init__(self, rng: random.Random, p_fail: float):
        super().__init__()
        self._rng = rng
        self._p_fail = p_fail

    def transactional_producer(self, transactional_id: str):
        inner = super().transactional_producer(transactional_id)
        return _FlakyProducer(inner, self._rng, self._p_fail)


def _logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())


@pytest.mark.parametrize("seed", [11, 29, 47])
def test_fuzz_exactly_once_under_flaky_commits(seed):
    rng = random.Random(seed)
    # injection draws interleave with worker draws on wall-clock flush timing;
    # a SEPARATE stream keeps the workload reproducible per seed
    inject_rng = random.Random(seed ^ 0x5EED)

    async def scenario():
        log = _FlakyLog(inject_rng, p_fail=0.20)
        engine = create_engine(_logic(), log=log, config=CFG)
        await engine.start()

        aggs = [f"agg-{i}" for i in range(8)]
        acked: dict[str, int] = {a: 0 for a in aggs}

        async def worker(agg: str) -> None:
            ref = engine.aggregate_for(agg)
            for _ in range(rng.randrange(6, 14)):
                cmd = (counter.Increment(agg) if rng.random() < 0.8
                       else counter.Decrement(agg))
                r = await ref.send_command(cmd)
                if isinstance(r, CommandSuccess):
                    acked[agg] += 1
                # failures are legal under injection; retries happen inside
                # the entity — the invariants below are what matter

        await asyncio.gather(*(worker(a) for a in aggs))

        # settle outstanding flushes/indexing, then stop cleanly
        await asyncio.sleep(0.1)
        final = {a: await engine.aggregate_for(a).get_state() for a in aggs}
        await engine.stop()
        return log, acked, final

    log, acked, final = asyncio.run(scenario())

    fmt = counter.event_formatting()
    model = counter.CounterModel()
    per_agg: dict[str, list] = {}
    for p in range(2):
        for rec in log.read("counter-events", p):  # read_committed view
            ev = fmt.read_event(rec)
            per_agg.setdefault(ev.aggregate_id, []).append(ev)

    for agg in acked:
        events = per_agg.get(agg, [])
        seqs = [e.sequence_number for e in events]
        # invariant 2: a gapless, duplicate-free fold history
        assert seqs == list(range(1, len(seqs) + 1)), (agg, seqs)
        # invariant 1: exactly one committed event per acknowledged command
        assert len(seqs) == acked[agg], (agg, len(seqs), acked[agg])
        # invariant 3: queryable state equals the scalar fold of the log
        want = fold_events(model, None, events)
        got = final[agg]
        if want is None:
            assert got is None or got.version == 0, agg
        else:
            assert got is not None
            assert (got.count, got.version) == (want.count, want.version), agg
