"""Aggregate entity FSM + shard + AggregateRef — the PersistentActorSpec analog.

Drives a real entity against the real publisher/store stack (no mocks below the model),
covering the reference spec's hardest paths (PersistentActorSpec, SURVEY.md §4):
happy-path command fold+persist+reply, rejections, command/fold/serialization failures,
publish retry-then-crash with recreate-from-store, init gating, passivation buffering."""

import asyncio

import pytest

from surge_tpu.config import default_config
from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic, SurgeModel
from surge_tpu.engine.entity import (
    AggregateEntity,
    CommandFailure,
    CommandRejected,
    CommandSuccess,
)
from surge_tpu.engine.publisher import PartitionPublisher, PublishFailedError
from surge_tpu.engine.ref import AggregateRef
from surge_tpu.engine.shard import Shard
from surge_tpu.log import InMemoryLog, TopicSpec
from surge_tpu.models import counter
from surge_tpu.store import StateStoreIndexer

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.aggregate.init-fetch-retry-ms": 5,
    "surge.aggregate.publish-timeout-ms": 2_000,
    "surge.aggregate.ask-timeout-ms": 2_000,
    "surge.serialization.thread-pool-size": 2,
})


def make_logic():
    return SurgeCommandBusinessLogic(
        aggregate_name="counter", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting(),
        state_topic="state", events_topic="events")


class Stack:
    """log + indexer + publisher + shard wired like the pipeline will wire them."""

    def __init__(self, config=CFG, publisher_cls=PartitionPublisher):
        self.config = config
        self.log = InMemoryLog()
        self.log.create_topic(TopicSpec("events", 1))
        self.log.create_topic(TopicSpec("state", 1, compacted=True))
        self.logic = make_logic()
        self.surge_model = SurgeModel(self.logic, config)
        self.indexer = StateStoreIndexer(self.log, "state", config=config)
        self.publisher = publisher_cls(self.log, "state", "events", 0, self.indexer,
                                       config=config)
        self.shard = Shard("p0", self._entity_factory)

    def _entity_factory(self, aggregate_id, on_passivate, on_stopped):
        return AggregateEntity(
            aggregate_id, self.surge_model, self.publisher,
            fetch_state=self.indexer.get_aggregate_bytes, partition=0,
            config=self.config, on_passivate=on_passivate, on_stopped=on_stopped)

    async def start(self):
        await self.indexer.start()
        await self.publisher.start()
        await self.publisher.wait_ready(5.0)
        return self

    async def stop(self):
        await self.shard.stop()
        await self.publisher.stop()
        await self.indexer.stop()
        self.surge_model.close()

    def ref(self, aggregate_id) -> AggregateRef:
        return AggregateRef(aggregate_id, self.shard.deliver, self.config)


def run(coro):
    asyncio.run(coro)


def test_send_command_fold_persist_reply():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        r1 = await ref.send_command(counter.Increment("agg1"))
        assert isinstance(r1, CommandSuccess)
        assert r1.state.count == 1 and r1.state.version == 1
        r2 = await ref.send_command(counter.Increment("agg1"))
        r3 = await ref.send_command(counter.Decrement("agg1"))
        assert r3.state.count == 1 and r3.state.version == 3
        assert await ref.get_state() == r3.state

        # events topic carries the three events; state topic the three snapshots
        events = [r for r in s.log.read("events", 0)]
        assert len(events) == 3
        assert s.log.latest_by_key("state", 0)["agg1"].value == \
            counter.state_formatting().write_state(r3.state).value
        await s.stop()

    run(scenario())


def test_rejection_leaves_state_unchanged():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        await ref.send_command(counter.Increment("agg1"))
        r = await ref.send_command(counter.FailCommandProcessing("agg1", "nope"))
        assert isinstance(r, CommandRejected)
        assert str(r.reason) == "nope"
        assert (await ref.get_state()).count == 1  # unchanged, entity alive
        await s.stop()

    run(scenario())


def test_fold_exception_errors_but_entity_survives():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        await ref.send_command(counter.Increment("agg1"))
        r = await ref.send_command(counter.CreateExceptionThrowingEvent("agg1", "boom"))
        assert isinstance(r, CommandFailure)
        assert isinstance(r.error, counter.ExceptionThrowingError)
        events_before = s.log.end_offset("events", 0)
        rr = await ref.send_command(counter.Increment("agg1"))  # still serving
        assert isinstance(rr, CommandSuccess) and rr.state.count == 2
        assert s.log.end_offset("events", 0) == events_before + 1
        await s.stop()

    run(scenario())


def test_serialization_failure_publishes_nothing():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        await ref.send_command(counter.Increment("agg1"))
        ev_before = s.log.end_offset("events", 0)
        st_before = s.log.end_offset("state", 0)
        r = await ref.send_command(counter.CreateUnserializableEvent("agg1", "bad"))
        assert isinstance(r, CommandFailure)
        assert "unserializable" in str(r.error)
        assert s.log.end_offset("events", 0) == ev_before
        assert s.log.end_offset("state", 0) == st_before
        # in-memory state must NOT have advanced past what was persisted
        assert (await ref.get_state()).version == 1
        await s.stop()

    run(scenario())


def test_entity_initializes_from_store_snapshot():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg9")
        r = await ref.send_command(counter.Increment("agg9"))
        # wait until the snapshot is both indexed and no longer in flight
        for _ in range(200):
            s.publisher._refresh_watermark()
            if s.publisher.is_aggregate_state_current("agg9"):
                break
            await asyncio.sleep(0.01)
        entity = s.shard.live_entity("agg9")
        await entity.stop()  # simulate passivation/eviction

        r2 = await s.ref("agg9").send_command(counter.Increment("agg9"))
        assert isinstance(r2, CommandSuccess)
        assert r2.state.count == 2 and r2.state.version == 2  # resumed from snapshot
        await s.stop()

    run(scenario())


def test_publish_retry_exhaustion_crashes_then_recreates():
    class AlwaysFailingPublisher(PartitionPublisher):
        async def publish(self, aggregate_id, records, request_id):
            raise PublishFailedError("injected transport failure")

    async def scenario():
        cfg = CFG.with_overrides({"surge.aggregate.publish-max-retries": 1})
        s = Stack(config=cfg, publisher_cls=AlwaysFailingPublisher)
        await s.indexer.start()
        await s.publisher.start()
        await s.publisher.wait_ready(5.0)
        ref = s.ref("agg1")
        r = await ref.send_command(counter.Increment("agg1"))
        assert isinstance(r, CommandFailure)
        await asyncio.sleep(0.01)
        dead = s.shard.live_entity("agg1")
        assert dead is None or dead.state_name == "stopped"  # crashed

        # heal the transport: next command gets a fresh entity that works
        s.publisher.__class__ = PartitionPublisher
        r2 = await ref.send_command(counter.Increment("agg1"))
        assert isinstance(r2, CommandSuccess) and r2.state.count == 1
        await s.stop()

    run(scenario())


def test_idle_passivation_and_buffered_redelivery():
    async def scenario():
        cfg = CFG.with_overrides({"surge.aggregate.idle-passivation-ms": 30})
        s = await Stack(config=cfg).start()
        ref = s.ref("agg1")
        await ref.send_command(counter.Increment("agg1"))
        assert s.shard.num_live_entities == 1
        # wait for idle passivation + snapshot indexing
        for _ in range(300):
            if s.shard.num_live_entities == 0 and \
                    s.publisher.is_aggregate_state_current("agg1"):
                break
            s.publisher._refresh_watermark()
            await asyncio.sleep(0.01)
        assert s.shard.num_live_entities == 0

        r = await ref.send_command(counter.Increment("agg1"))  # revives from store
        assert isinstance(r, CommandSuccess) and r.state.count == 2
        await s.stop()

    run(scenario())


def test_passivation_window_buffering():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        await ref.send_command(counter.Increment("agg1"))
        # simulate the passivation window: parent marked, entity not yet stopped
        s.shard._on_passivate("agg1")
        ask = asyncio.ensure_future(ref.send_command(counter.Increment("agg1")))
        await asyncio.sleep(0.02)
        assert not ask.done()  # buffered, not delivered
        entity = s.shard.live_entity("agg1")
        await entity.stop()
        s.shard._on_stopped("agg1", [], False)  # triggers redelivery to fresh entity
        r = await ask
        assert isinstance(r, CommandSuccess) and r.state.count == 2
        await s.stop()

    run(scenario())


def test_apply_events_publishes_state_only():
    async def scenario():
        s = await Stack().start()
        ref = s.ref("agg1")
        ev_before = s.log.end_offset("events", 0)
        r = await ref.apply_events([counter.CountIncremented("agg1", 5, 1)])
        assert isinstance(r, CommandSuccess) and r.state.count == 5
        assert s.log.end_offset("events", 0) == ev_before  # no events published
        assert s.log.latest_by_key("state", 0)["agg1"].value is not None
        await s.stop()

    run(scenario())


def test_ask_timeout_maps_to_command_failure():
    async def scenario():
        cfg = CFG.with_overrides({"surge.aggregate.ask-timeout-ms": 50})
        dropped = AggregateRef("agg1", deliver=lambda agg_id, env: None, config=cfg)
        r = await dropped.send_command(counter.Increment("agg1"))
        assert isinstance(r, CommandFailure)
        assert isinstance(r.error, asyncio.TimeoutError)

    run(scenario())
