"""Event-only engine: apply_events/get_state surface, no command side, state-only
publishing (scaladsl/event parity — SurgeEvent.scala:19-59, AggregateEventModel
.scala:10-38)."""

import asyncio

import pytest

from surge_tpu import default_config
from surge_tpu.engine.entity import CommandFailure, CommandSuccess
from surge_tpu.engine.event_dsl import create_event_engine
from surge_tpu.log import InMemoryLog
from surge_tpu.models import counter

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 2,
})


class CounterEventModel:
    """Event-side-only model: just the fold (AggregateEventModel analog)."""

    def initial_state(self, aggregate_id):
        return None

    def handle_event(self, state, event):
        return counter.CounterModel().handle_event(state, event)


def test_apply_events_and_get_state():
    async def scenario():
        log = InMemoryLog()
        engine = create_event_engine(
            "counter-events", CounterEventModel(), counter.state_formatting(),
            log=log, config=CFG)
        await engine.start()
        ref = engine.aggregate_for("agg-1")
        r = await ref.apply_events([
            counter.CountIncremented("agg-1", 2, 1),
            counter.CountIncremented("agg-1", 3, 2),
        ])
        assert isinstance(r, CommandSuccess) and r.state.count == 5
        st = await ref.get_state()
        assert st.count == 5 and st.version == 2
        # the surface has no send_command at all
        assert not hasattr(ref, "send_command")

        # state-only publishing: a state topic exists, no events topic was created
        assert log.end_offset("counter-events-state",
                              engine.engine.router.partition_for("agg-1")) >= 1
        assert "counter-events-events" not in log._topics
        await engine.stop()

        # restart resumes the snapshot from the compacted state topic
        engine2 = create_event_engine(
            "counter-events", CounterEventModel(), counter.state_formatting(),
            log=log, config=CFG)
        await engine2.start()
        st = await engine2.aggregate_for("agg-1").get_state()
        assert st.count == 5
        await engine2.stop()

    asyncio.run(scenario())


def test_event_model_requires_a_fold():
    class NoFold:
        pass

    with pytest.raises(TypeError, match="handle_event"):
        create_event_engine("x", NoFold(), counter.state_formatting())


def test_commands_are_rejected_at_the_model():
    async def scenario():
        engine = create_event_engine(
            "counter-events", CounterEventModel(), counter.state_formatting(),
            config=CFG)
        await engine.start()
        # the inner engine surface still exists, but the model's command side throws
        r = await engine.engine.aggregate_for("agg-9").send_command(
            counter.Increment("agg-9"))
        assert isinstance(r, CommandFailure)
        assert "do not process commands" in str(r.error)
        await engine.stop()

    asyncio.run(scenario())
