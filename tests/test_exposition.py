"""OpenMetrics exposition: grammar validation, golden payloads (engine AND
broker registries), exemplars, scrape endpoints, and the instrument-catalog
contract (every Sensor registered in any Metrics registry appears in the
export AND in the docs metric catalog)."""

import os
import re
import urllib.request

import pytest

from surge_tpu.health import HealthSignalBus, HealthSupervisor
from surge_tpu.metrics import MetricInfo, Metrics, engine_metrics
from surge_tpu.metrics.broker import broker_metrics
from surge_tpu.metrics.fleet import fleet_metrics
from surge_tpu.metrics.exposition import (
    MetricsHTTPServer,
    health_collector,
    render_openmetrics,
    sanitize_name,
)

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "metrics.om")
BROKER_GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden",
                                  "metrics_broker.om")
# the fleet golden is the MERGED federated payload (rendered by
# test_federation.golden_fleet_scrape); the fleet quiver's own families are
# part of it, so the catalog-completeness parametrization below can hold the
# fleet registry to the same golden/docs coupling as engine and broker
FLEET_GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden",
                                 "metrics_fleet.om")

_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(gauge|counter|histogram)$")
_VALUE = r"-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|[+-]Inf|NaN"
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"            # sample name
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"  # labels
    rf" ({_VALUE})"                                                   # value
    # optional OpenMetrics exemplar: # {trace_id="..."} value timestamp
    rf"( # \{{trace_id=\"[0-9a-f]{{32}}\"\}} (?:{_VALUE}) [0-9.]+)?$")


def validate_openmetrics(text: str) -> dict:
    """Minimal OpenMetrics grammar check; returns {family: (type, samples)}.

    Enforces the parts a scraper depends on: EOF terminator, every sample under
    a declared TYPE, counter samples suffixed ``_total``, histogram series
    limited to ``_bucket``/``_sum``/``_count`` with cumulative buckets ending
    in a ``+Inf`` bucket that equals ``_count``.
    """
    assert text.endswith("# EOF\n"), "payload must end with # EOF"
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    families: dict = {}
    for ln in lines[:-1]:
        if ln.startswith("# HELP "):
            m = _HELP_RE.match(ln)
            assert m, f"bad HELP line: {ln!r}"
            continue
        if ln.startswith("# TYPE "):
            m = _TYPE_RE.match(ln)
            assert m, f"bad TYPE line: {ln!r}"
            name, mtype = m.group(1), m.group(2)
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = (mtype, [])
            continue
        assert not ln.startswith("#"), f"unknown comment line: {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"bad sample line: {ln!r}"
        sample_name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        if m.group(4):  # exemplars only make sense on histogram buckets
            assert sample_name.endswith("_bucket"), \
                f"exemplar on a non-bucket sample: {ln!r}"
        fam_name = None
        for suffix in ("", "_total", "_bucket", "_sum", "_count"):
            cand = sample_name[: len(sample_name) - len(suffix)] \
                if suffix and sample_name.endswith(suffix) else (
                    sample_name if not suffix else None)
            if cand in families:
                fam_name = cand
                break
        assert fam_name is not None, f"sample without TYPE: {ln!r}"
        mtype, samples = families[fam_name]
        suffix = sample_name[len(fam_name):]
        if mtype == "counter":
            assert suffix == "_total", f"counter sample must be _total: {ln!r}"
        elif mtype == "histogram":
            assert suffix in ("_bucket", "_sum", "_count"), ln
        else:
            assert suffix == "", f"gauge sample must be bare: {ln!r}"
        samples.append((suffix, labels_raw or "", value))
    # histogram invariants: cumulative buckets, +Inf bucket == _count — PER
    # LABEL SET (a federated payload repeats one histogram family per
    # instance; each instance's series must hold the invariants on its own)
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

    def series_key(labels_raw: str) -> frozenset:
        return frozenset((k, v) for k, v in label_re.findall(labels_raw)
                         if k != "le")

    for name, (mtype, samples) in families.items():
        if mtype != "histogram":
            continue
        buckets: dict = {}
        counts: dict = {}
        for s, lr, v in samples:
            if s == "_bucket":
                buckets.setdefault(series_key(lr), []).append(
                    (lr, float(v)))
            elif s == "_count":
                counts.setdefault(series_key(lr), []).append(float(v))
        assert buckets and set(buckets) == set(counts), name
        for key, series in buckets.items():
            assert len(counts[key]) == 1, f"{name} duplicate _count"
            values = [v for _, v in series]
            assert values == sorted(values), f"{name} buckets not cumulative"
            assert 'le="+Inf"' in series[-1][0], f"{name} missing +Inf bucket"
            assert series[-1][1] == counts[key][0], f"{name} +Inf != _count"
    return families


def golden_engine_metrics():
    """The canonical deterministic recording sequence behind the golden file
    (tools/regen_golden_metrics.py re-renders it)."""
    em = engine_metrics()
    em.state_fetch_timer.record_ms(5.0)
    em.state_fetch_timer.record_ms(15.0)
    em.command_handling_timer.record_ms(2.0)
    em.publish_failure_counter.record()
    em.fence_counter.record(2)
    em.live_entities.record(7)
    em.standby_lag.record(3)
    em.replay_timer.record_ms(120000.0)  # overflow bucket: +Inf only in export
    # the device observatory's round gauges + cause-split fallback counters
    # (ISSUE 16) — the steady-ragged shape the roofline anchors on
    em.resident_round_events.record(50)
    em.resident_padding_waste_ratio.record(9.0)
    em.resident_dispatch_occupancy.record(1.0 / 9.0)
    em.resident_events_per_dispatch_us.record(0.125)
    em.resident_shard_skew.record(1.25)
    # bucketed ragged dispatch (ISSUE 18): 3 occupied length buckets,
    # lane-level fill across their pow2 lane slots
    em.resident_bucket_dispatches.record(3)
    em.resident_bucket_fill_ratio.record(0.62)
    em.resident_fallbacks.record(3)
    em.resident_fallbacks_lag.record(2)
    em.resident_fallbacks_poison.record(1)
    em.query_scan_rows.record(5)
    em.query_pushdown_selectivity.record(0.4)
    # the materialized-view fold leg + changefeed hub (ISSUE 17)
    em.views_fold_timer.record_ms(3.0)
    em.views_delta_rows.record(12)
    em.views_subscribers.record(2)
    em.views_resume_gap_rounds.record(4)
    return em


def golden_broker_metrics():
    """The broker registry's canonical deterministic recording sequence
    (tools/regen_golden_metrics.py re-renders it into metrics_broker.om)."""
    bm = broker_metrics()
    bm.repl_insync_replicas.record(2)
    bm.repl_isr_churn.record()
    bm.repl_queue_depth.record(3)
    bm.repl_epoch.record(2)
    bm.repl_catchup_records.record(1000)
    bm.repl_ship_timer.record_ms(4.0)
    bm.journal_fsync_round_timer.record_ms(1.5)
    bm.journal_fsync_round_timer.record_ms(30.0)
    bm.journal_round_occupancy.record(6)
    bm.journal_rotations.record()
    bm.journal_wal_bytes.record(1 << 20)
    bm.txn_inorder_wait_timer.record_ms(12.0)
    bm.txn_dedup_window.record(5)
    bm.txn_alias_window.record(1)
    bm.txn_pipelined_depth.record(4)
    bm.failover_promotions.record()
    bm.failover_fencings.record()
    bm.failover_truncated_records.record(2)
    bm.faults_injected.record(3)
    bm.faults_armed.record(2)
    return bm


def test_render_matches_golden():
    text = render_openmetrics(golden_engine_metrics().registry)
    validate_openmetrics(text)
    with open(GOLDEN_PATH) as f:
        golden = f.read()
    assert text == golden, (
        "OpenMetrics payload drifted from tests/golden/metrics.om — if the "
        "change is intentional run tools/regen_golden_metrics.py and update "
        "the docs/observability.md metric catalog")


def test_broker_render_matches_golden():
    text = render_openmetrics(golden_broker_metrics().registry)
    families = validate_openmetrics(text)
    # the acceptance families: replication instruments + the journal
    # fsync-round histogram, full _bucket/_sum/_count series
    assert "surge_log_replication_insync_replicas" in families
    assert families["surge_log_journal_fsync_round_timer_ms"][0] \
        == "histogram"
    with open(BROKER_GOLDEN_PATH) as f:
        golden = f.read()
    assert text == golden, (
        "broker OpenMetrics payload drifted from tests/golden/"
        "metrics_broker.om — if the change is intentional run "
        "tools/regen_golden_metrics.py and update the docs/observability.md "
        "broker catalog (golden and catalog are coupled; regen both "
        "together)")


@pytest.mark.parametrize("quiver_factory,golden_path", [
    (engine_metrics, GOLDEN_PATH),
    (broker_metrics, BROKER_GOLDEN_PATH),
    (fleet_metrics, FLEET_GOLDEN_PATH),
], ids=["engine", "broker", "fleet"])
def test_every_instrument_in_export_docs_catalog_and_golden(quiver_factory,
                                                            golden_path):
    """Catalog completeness across EVERY registry (engine AND broker): each
    registered Sensor appears in the rendered export, in the docs metric
    catalog, and in the regenerated golden file."""
    quiver = quiver_factory()
    text = render_openmetrics(quiver.registry)
    families = validate_openmetrics(text)
    docs = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "observability.md")).read()
    with open(golden_path) as f:
        golden_families = validate_openmetrics(f.read())
    for dotted in quiver.registry.get_metrics():
        fam = sanitize_name(dotted[:-len(".p99")] + "_ms"
                            if dotted.endswith(".p99") else dotted)
        assert fam in families, f"{dotted} missing from the export"
        assert fam in golden_families, (
            f"{dotted} missing from {os.path.basename(golden_path)} — run "
            "tools/regen_golden_metrics.py (golden and catalog are coupled; "
            "regen both together)")
        base = dotted[:-len(".p99")] if dotted.endswith(".p99") else dotted
        base = re.sub(r"\.(min|max)$", "", base)
        assert base in docs, f"{base} missing from the docs metric catalog"
    # histogram series carry buckets, not a lone p99 point
    sample = {engine_metrics: "surge.replay.rebuild-timer",
              broker_metrics: "surge.log.journal.fsync-round-timer",
              fleet_metrics: "surge.fleet.scrape-timer"}[quiver_factory]
    assert families[sanitize_name(sample) + "_ms"][0] == "histogram"


def test_exemplar_renders_and_passes_grammar():
    """A histogram recording inside an active sampled span captures the trace
    id; the exposition renders it in OpenMetrics exemplar syntax on exactly
    that bucket, and the grammar validator accepts it."""
    from surge_tpu.tracing import InMemoryTracer

    m = Metrics(exemplars=True)
    timer = m.timer(MetricInfo("surge.test.exemplar-timer", "exemplar test"))
    tracer = InMemoryTracer()
    with tracer.start_span("publish") as span:
        timer.record_ms(7.0)
    timer.record_ms(3.0)  # outside any span: no exemplar captured
    text = render_openmetrics(m)
    validate_openmetrics(text)
    want = f'# {{trace_id="{span.context.trace_id}"}} 7 '
    bucket_lines = [ln for ln in text.splitlines() if want in ln]
    assert len(bucket_lines) == 1, text
    assert 'le="10"' in bucket_lines[0]  # 7ms lands in the 10ms bucket
    # unsampled spans yield no exemplar (nothing exported to link to)
    m2 = Metrics(exemplars=True)
    t2 = m2.timer(MetricInfo("surge.test.unsampled-timer", "x"))
    with InMemoryTracer(sample_rate=0.0).start_span("p"):
        t2.record_ms(7.0)
    assert "trace_id" not in render_openmetrics(m2)


def test_label_escaping_and_name_sanitization():
    m = Metrics()
    m.gauge(MetricInfo("weird.metric-name/x", "helps\nwith\\newlines",
                       tags=(("topic", 'a"b\\c\nd'),))).record(1)
    text = render_openmetrics(m)
    validate_openmetrics(text)
    assert "weird_metric_name_x" in text
    assert '\\"b\\\\c\\nd' in text  # escaped quote, backslash, newline


def test_health_collector_joins_export():
    bus = HealthSignalBus()
    sup = HealthSupervisor(bus)
    bus.emit("publisher-0.fenced", "error", source="publisher-0")
    bus.emit("state-store.lag", "warning", source="state-store")
    bus.emit("state-store.lag", "warning", source="state-store")

    class _Dummy:
        async def restart(self):
            pass

        async def shutdown(self):
            pass

    sup.register("state-store", _Dummy(), restart_patterns=[])
    sup._registrations["state-store"].restarts = 2
    text = render_openmetrics(Metrics(),
                              collectors=[health_collector(bus, sup)])
    validate_openmetrics(text)
    assert 'surge_health_signals_total{level="error"} 1' in text
    assert 'surge_health_signals_total{level="warning"} 2' in text
    assert ('surge_health_component_restarts_total{component="state-store"} 2'
            in text)


def test_http_scrape_endpoint():
    em = engine_metrics()
    em.live_entities.record(4)
    bus = HealthSignalBus()
    bus.emit("x.y", "trace")
    server = MetricsHTTPServer(em.registry, collectors=[health_collector(bus)])
    port = server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            body = resp.read().decode()
        families = validate_openmetrics(body)
        assert "surge_engine_live_entities" in families
        assert 'surge_health_signals_total{level="trace"} 1' in body
        # unknown paths 404, the scrape loop stays up
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as resp:
            assert resp.status == 200
    finally:
        server.stop()
