"""OpenMetrics exposition: grammar validation, golden payload, scrape endpoint,
and the instrument-catalog contract (every predeclared EngineMetrics instrument
appears in the export AND in the docs metric catalog)."""

import os
import re
import urllib.request

from surge_tpu.health import HealthSignalBus, HealthSupervisor
from surge_tpu.metrics import MetricInfo, Metrics, engine_metrics
from surge_tpu.metrics.exposition import (
    MetricsHTTPServer,
    health_collector,
    render_openmetrics,
    sanitize_name,
)

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "metrics.om")

_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(gauge|counter|histogram)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"            # sample name
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"  # labels
    r" (-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|[+-]Inf|NaN)$")     # value


def validate_openmetrics(text: str) -> dict:
    """Minimal OpenMetrics grammar check; returns {family: (type, samples)}.

    Enforces the parts a scraper depends on: EOF terminator, every sample under
    a declared TYPE, counter samples suffixed ``_total``, histogram series
    limited to ``_bucket``/``_sum``/``_count`` with cumulative buckets ending
    in a ``+Inf`` bucket that equals ``_count``.
    """
    assert text.endswith("# EOF\n"), "payload must end with # EOF"
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    families: dict = {}
    for ln in lines[:-1]:
        if ln.startswith("# HELP "):
            m = _HELP_RE.match(ln)
            assert m, f"bad HELP line: {ln!r}"
            continue
        if ln.startswith("# TYPE "):
            m = _TYPE_RE.match(ln)
            assert m, f"bad TYPE line: {ln!r}"
            name, mtype = m.group(1), m.group(2)
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = (mtype, [])
            continue
        assert not ln.startswith("#"), f"unknown comment line: {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"bad sample line: {ln!r}"
        sample_name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        fam_name = None
        for suffix in ("", "_total", "_bucket", "_sum", "_count"):
            cand = sample_name[: len(sample_name) - len(suffix)] \
                if suffix and sample_name.endswith(suffix) else (
                    sample_name if not suffix else None)
            if cand in families:
                fam_name = cand
                break
        assert fam_name is not None, f"sample without TYPE: {ln!r}"
        mtype, samples = families[fam_name]
        suffix = sample_name[len(fam_name):]
        if mtype == "counter":
            assert suffix == "_total", f"counter sample must be _total: {ln!r}"
        elif mtype == "histogram":
            assert suffix in ("_bucket", "_sum", "_count"), ln
        else:
            assert suffix == "", f"gauge sample must be bare: {ln!r}"
        samples.append((suffix, labels_raw or "", value))
    # histogram invariants: cumulative buckets, +Inf bucket == _count
    for name, (mtype, samples) in families.items():
        if mtype != "histogram":
            continue
        buckets = [(lr, float(v)) for s, lr, v in samples if s == "_bucket"]
        counts = [float(v) for s, _, v in samples if s == "_count"]
        assert buckets and len(counts) == 1, name
        values = [v for _, v in buckets]
        assert values == sorted(values), f"{name} buckets not cumulative"
        assert 'le="+Inf"' in buckets[-1][0], f"{name} missing +Inf bucket"
        assert buckets[-1][1] == counts[0], f"{name} +Inf != _count"
    return families


def golden_engine_metrics():
    """The canonical deterministic recording sequence behind the golden file
    (tools/regen_golden_metrics.py re-renders it)."""
    em = engine_metrics()
    em.state_fetch_timer.record_ms(5.0)
    em.state_fetch_timer.record_ms(15.0)
    em.command_handling_timer.record_ms(2.0)
    em.publish_failure_counter.record()
    em.fence_counter.record(2)
    em.live_entities.record(7)
    em.standby_lag.record(3)
    em.replay_timer.record_ms(120000.0)  # overflow bucket: +Inf only in export
    return em


def test_render_matches_golden():
    text = render_openmetrics(golden_engine_metrics().registry)
    validate_openmetrics(text)
    with open(GOLDEN_PATH) as f:
        golden = f.read()
    assert text == golden, (
        "OpenMetrics payload drifted from tests/golden/metrics.om — if the "
        "change is intentional run tools/regen_golden_metrics.py and update "
        "the docs/observability.md metric catalog")


def test_every_engine_instrument_in_export_and_docs_catalog():
    em = engine_metrics()
    text = render_openmetrics(em.registry)
    families = validate_openmetrics(text)
    docs = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "observability.md")).read()
    for dotted in em.registry.get_metrics():
        fam = sanitize_name(dotted[:-len(".p99")] + "_ms"
                            if dotted.endswith(".p99") else dotted)
        assert fam in families, f"{dotted} missing from the export"
        base = dotted[:-len(".p99")] if dotted.endswith(".p99") else dotted
        base = re.sub(r"\.(min|max)$", "", base)
        assert base in docs, f"{base} missing from the docs metric catalog"
    # histogram series carry buckets, not a lone p99 point
    assert families[sanitize_name("surge.replay.rebuild-timer") + "_ms"][0] \
        == "histogram"


def test_label_escaping_and_name_sanitization():
    m = Metrics()
    m.gauge(MetricInfo("weird.metric-name/x", "helps\nwith\\newlines",
                       tags=(("topic", 'a"b\\c\nd'),))).record(1)
    text = render_openmetrics(m)
    validate_openmetrics(text)
    assert "weird_metric_name_x" in text
    assert '\\"b\\\\c\\nd' in text  # escaped quote, backslash, newline


def test_health_collector_joins_export():
    bus = HealthSignalBus()
    sup = HealthSupervisor(bus)
    bus.emit("publisher-0.fenced", "error", source="publisher-0")
    bus.emit("state-store.lag", "warning", source="state-store")
    bus.emit("state-store.lag", "warning", source="state-store")

    class _Dummy:
        async def restart(self):
            pass

        async def shutdown(self):
            pass

    sup.register("state-store", _Dummy(), restart_patterns=[])
    sup._registrations["state-store"].restarts = 2
    text = render_openmetrics(Metrics(),
                              collectors=[health_collector(bus, sup)])
    validate_openmetrics(text)
    assert 'surge_health_signals_total{level="error"} 1' in text
    assert 'surge_health_signals_total{level="warning"} 2' in text
    assert ('surge_health_component_restarts_total{component="state-store"} 2'
            in text)


def test_http_scrape_endpoint():
    em = engine_metrics()
    em.live_entities.record(4)
    bus = HealthSignalBus()
    bus.emit("x.y", "trace")
    server = MetricsHTTPServer(em.registry, collectors=[health_collector(bus)])
    port = server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            body = resp.read().decode()
        families = validate_openmetrics(body)
        assert "surge_engine_live_entities" in families
        assert 'surge_health_signals_total{level="trace"} 1' in body
        # unknown paths 404, the scrape loop stays up
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as resp:
            assert resp.status == 200
    finally:
        server.stop()
