"""Epoch-fenced leader failover: promotion, NOT_LEADER redirects, KIP-101
tail truncation on the fenced ex-leader, exactly-once across the failover
(client-side ledger), the barrier-replicated compaction path, and the seeded
chaos schedules (3-seed fast variant in tier-1; the long soak is ``slow``)."""

import json
import os
import time

import pytest

from conftest import free_ports
from surge_tpu.config import Config
from surge_tpu.log import (
    FileLog,
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.log.transport import NotLeaderError, ProducerFencedError
from surge_tpu.testing.faults import FaultPlane, FaultRule


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


FAST_CFG = Config(overrides={
    "surge.log.replication-ack-timeout-ms": 1_500,
    "surge.log.replication-isr-timeout-ms": 600,
    "surge.log.failover.probe-interval-ms": 150,
    "surge.log.failover.probe-failures": 2,
})


def _pair(leader_log=None, follower_log=None, auto_promote=False,
          config=FAST_CFG):
    """leader ⇄ follower pair with explicit roles (follower_of=)."""
    lport, fport = free_ports(2)
    follower = LogServer(follower_log or InMemoryLog(), port=fport,
                         follower_of=f"127.0.0.1:{lport}",
                         auto_promote=auto_promote, config=config)
    follower.start()
    leader = LogServer(leader_log or InMemoryLog(), port=lport,
                       replicate_to=[f"127.0.0.1:{fport}"], config=config)
    leader.start()
    return leader, follower, lport, fport


class Ledger:
    """Client-side exactly-once ladder, mirroring the publisher's semantics:
    an unknown-outcome commit retries VERBATIM; a fencing (broker failover /
    NOT_LEADER) re-opens the producer — resuming the replicated idempotency
    numbering — and retries the same payload, which the broker's dedup
    window / reopen absorption answers instead of appending twice."""

    def __init__(self, transport: GrpcLogTransport, txn_id: str) -> None:
        self.transport = transport
        self.txn_id = txn_id
        self.acked: list = []  # payload bytes acked to the "user"
        self._producer = None  # opened lazily inside the retry ladder (a
        # broker freshly rebound on a known address sits out gRPC's cached
        # subchannel backoff first)

    def _reopen(self, deadline: float) -> None:
        while True:
            try:
                self._producer = self.transport.transactional_producer(
                    self.txn_id)
                return
            except Exception:  # noqa: BLE001 — broker mid-failover
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)

    def commit(self, topic: str, key: str, payload: bytes,
               timeout: float = 30.0, partition: int = 0) -> None:
        deadline = time.monotonic() + timeout
        if self._producer is None:
            self._reopen(deadline)
        while True:
            try:
                self._producer.begin()
                self._producer.send(rec(topic, key, payload, partition))
                self._producer.commit()
                self.acked.append(payload)
                return
            except (ProducerFencedError, NotLeaderError):
                if time.monotonic() > deadline:
                    raise
                self._reopen(deadline)
            except Exception:  # noqa: BLE001 — transport hiccup: retry
                if time.monotonic() > deadline:
                    raise
                if self._producer.in_transaction:
                    self._producer.abort()
                time.sleep(0.1)


def _values(log, topic, partitions=1):
    out = []
    for p in range(partitions):
        out.extend(r.value for r in log.read(topic, p))
    return out


def _assert_exactly_once(log, topic, acked, partitions=1):
    present = _values(log, topic, partitions)
    for payload in acked:
        n = present.count(payload)
        assert n == 1, f"acked payload {payload!r} appears {n} times"


# -- roles & redirects ----------------------------------------------------------------


def test_client_failover_histograms_record_and_carry_exemplars():
    """The client-side failover histograms (redirect reconnect + jittered
    backoff) record on the retry path, and — with exemplar capture on and an
    active sampled span — their buckets link to the commanding trace:
    the last ROADMAP item-6 leg."""
    from surge_tpu.metrics import Metrics, engine_metrics
    from surge_tpu.metrics.exposition import render_openmetrics
    from surge_tpu.tracing import InMemoryTracer

    leader, follower, lport, fport = _pair()
    try:
        em = engine_metrics(Metrics(exemplars=True))
        tracer = InMemoryTracer()
        # connect to the FOLLOWER: OpenProducer answers NOT_LEADER with the
        # leader hint, the transport reconnects (redirect timer) — all under
        # an active sampled span, as a command's publish path would be
        client = GrpcLogTransport(f"127.0.0.1:{fport}", config=FAST_CFG,
                                  metrics=em, tracer=tracer)
        client.create_topic(TopicSpec("ev", 1))
        with tracer.start_span("cmd") as span:
            producer = client.transactional_producer("t")
        values = em.registry.get_metrics()
        assert values["surge.log.failover.redirects"] == 1.0
        assert values["surge.log.failover.redirect-timer.p99"] > 0.0
        text = render_openmetrics(em.registry)
        assert (f'trace_id="{span.context.trace_id}"') in text
        bucket_lines = [ln for ln in text.splitlines()
                        if "surge_log_failover_redirect_timer_ms_bucket"
                        in ln and "trace_id" in ln]
        assert bucket_lines, text  # the redirect bucket carries the exemplar
        # the backoff histogram records the jittered sleep actually paid
        with tracer.start_span("retry"):
            client._backoff_sleep(0.004)
        assert em.registry.get_metrics()[
            "surge.log.failover.backoff-timer.p99"] > 0.0
        assert "surge_log_failover_backoff_timer_ms_bucket" in \
            render_openmetrics(em.registry)

        # context threading: a pipelined commit dispatched from inside a
        # span ships on a POOL thread, yet its broker-call span is a child
        # of the dispatching span (copied contextvars + active-span parent)
        with tracer.start_span("flush") as flush_span:
            producer.begin()
            producer.send(rec("ev", "k", b"v"))
            handle = producer.commit_pipelined()
        handle.future.result(timeout=10)
        transact_spans = [s for s in tracer.spans_named("log.Transact")
                          if s.attributes.get("txn_seq") == handle.seq]
        assert transact_spans, [s.name for s in tracer.finished]
        assert transact_spans[0].context.trace_id == \
            flush_span.context.trace_id
        assert transact_spans[0].parent_id == flush_span.context.span_id
        client.close()
    finally:
        leader.stop()
        follower.stop()


def test_follower_refuses_writes_and_client_follows_redirect():
    leader, follower, lport, fport = _pair()
    try:
        # a client aimed at the FOLLOWER must end up writing on the leader
        # purely through the NOT_LEADER redirect hint
        client = GrpcLogTransport(f"127.0.0.1:{fport}")
        client.create_topic(TopicSpec("ev", 1))
        led = Ledger(client, "t-redirect")
        led.commit("ev", "a", b"via-redirect")
        assert client.target == f"127.0.0.1:{lport}"  # learned the leader
        assert [r.value for r in leader.log.read("ev", 0)] == [b"via-redirect"]
        status = client.broker_status()
        assert status["role"] == "leader" and status["epoch"] == 1
        client.close()
    finally:
        leader.stop()
        follower.stop()


def test_promotion_bumps_epoch_and_records_epoch_start():
    leader, follower, lport, fport = _pair()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}")
        client.create_topic(TopicSpec("ev", 2))
        led = Ledger(client, "t-promo")
        for i in range(4):
            led.commit("ev", f"k{i}", f"v{i}".encode())
        leader.kill()
        fclient = GrpcLogTransport(f"127.0.0.1:{fport}")
        status = fclient.promote_follower()
        assert status["role"] == "leader"
        assert status["epoch"] == 2
        # epoch-start records the promotion-time frontier per partition
        assert status["epoch_start"]["ev"] == {
            "0": follower.log.end_offset("ev", 0),
            "1": follower.log.end_offset("ev", 1)}
        # idempotent re-promotion does not bump again
        assert fclient.promote_follower()["epoch"] == 2
        client.close()
        fclient.close()
    finally:
        leader.stop()
        follower.stop()


# -- the acceptance path --------------------------------------------------------------


def test_leader_crash_at_crash_point_failover_exactly_once_and_fenced_truncation(tmp_path):
    """The acceptance chaos test: kill the leader mid-load at a named
    crash-point (post-apply: the commit is on the leader's disk but neither
    replicated nor acked), the follower auto-promotes when the liveness
    prober declares the leader dead, the client ledger rides through on the
    txn-seq dedup window, and the fenced ex-leader truncates its divergent
    tail and converges with the new leader — every acked payload exactly
    once, everywhere."""
    leader_log = InMemoryLog()
    leader, follower, lport, fport = _pair(leader_log=leader_log,
                                           auto_promote=True)
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport},127.0.0.1:{fport}")
        client.create_topic(TopicSpec("ev", 1))
        # arm at runtime through the admin RPC: crash on a mid-load commit
        client.arm_faults(json.dumps({"rules": [{
            "site": "crash.transact.post-apply", "action": "crash",
            "after": 6}]}), seed=5)

        led = Ledger(client, "t-chaos")
        for i in range(14):
            led.commit("ev", f"k{i}", f"chaos-{i}".encode())
        assert len(led.acked) == 14

        # the follower promoted itself (prober) and holds every acked record
        # exactly once
        status = follower.broker_status()
        assert status["role"] == "leader" and status["epoch"] >= 2
        _assert_exactly_once(follower.log, "ev", led.acked)

        # the dead leader applied the crash-point commit locally (its
        # divergent unreplicated tail is nonempty) before anyone acked it
        assert leader_log.end_offset("ev", 0) \
            >= status["epoch_start"]["ev"]["0"]

        # restart the ex-leader: the split-brain guard finds the higher
        # epoch BEFORE serving, demotes, truncates to the epoch-start and
        # catches up — both logs now agree record-for-record
        if leader.kill_done is not None:
            assert leader.kill_done.wait(5), "killed socket never closed"
        relit = LogServer(leader_log, port=lport,
                          replicate_to=[f"127.0.0.1:{fport}"],
                          config=FAST_CFG)
        relit.start()
        try:
            assert relit.role == "follower"
            assert relit.epoch == status["epoch"]
            mine = [(r.offset, r.key, r.value)
                    for r in leader_log.read("ev", 0)]
            theirs = [(r.offset, r.key, r.value)
                      for r in follower.log.read("ev", 0)]
            assert mine == theirs
            _assert_exactly_once(leader_log, "ev", led.acked)
            # and a write against the fenced ex-leader redirects to the new
            # leader instead of forking the log
            rclient = GrpcLogTransport(f"127.0.0.1:{lport}")
            rled = Ledger(rclient, "t-after")
            rled.commit("ev", "post", b"after-fence")
            assert _values(follower.log, "ev").count(b"after-fence") == 1
            rclient.close()
        finally:
            relit.stop()
        client.close()
    finally:
        leader.stop()
        follower.stop()


def test_divergent_tail_truncated_on_fence_via_ship(tmp_path):
    """Fencing through the OUTBOUND ship (no restart): the old leader keeps
    running, accumulates a leader-only tail while its follower is ISR-evicted
    (blackholed ships), the follower promotes, and the old leader's next ship
    is answered with the higher epoch — it demotes in place, truncates the
    unreplicated tail, and serves redirects."""
    leader, follower, lport, fport = _pair()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}")
        client.create_topic(TopicSpec("ev", 1))
        led = Ledger(client, "t-fence")
        led.commit("ev", "base", b"replicated")

        # blackhole every ship, then commit: the follower drops from the
        # in-sync set (isr-timeout) and the records land leader-only
        leader.faults = FaultPlane([FaultRule(site="ship.*", action="drop",
                                              times=None)])
        led.commit("ev", "lost1", b"leader-only-1")
        led.commit("ev", "lost2", b"leader-only-2")
        assert follower.log.end_offset("ev", 0) == 1
        assert leader.log.end_offset("ev", 0) == 3

        fclient = GrpcLogTransport(f"127.0.0.1:{fport}")
        fclient.promote_follower(replicate_to=[f"127.0.0.1:{lport}"])
        leader.faults.disarm()  # heal the network: the next ship gets fenced

        new_led = Ledger(fclient, "t-after-promo")
        new_led.commit("ev", "fresh", b"new-epoch")

        deadline = time.time() + 10
        while leader.role != "follower" and time.time() < deadline:
            time.sleep(0.05)
        assert leader.role == "follower", "old leader never demoted"
        # KIP-101: the unreplicated tail is GONE, the new epoch's record is
        # pulled in, and both logs agree
        deadline = time.time() + 10
        while time.time() < deadline:
            mine = [(r.offset, r.value) for r in leader.log.read("ev", 0)]
            theirs = [(r.offset, r.value) for r in follower.log.read("ev", 0)]
            if mine == theirs:
                break
            time.sleep(0.1)
        assert mine == theirs
        vals = [v for _, v in mine]
        assert b"leader-only-1" not in vals and b"leader-only-2" not in vals
        assert vals.count(b"new-epoch") == 1
        client.close()
        fclient.close()
    finally:
        leader.stop()
        follower.stop()


# -- barrier-replicated compaction ----------------------------------------------------


def _seg_bytes(flog, topic, p):
    part = flog._parts[(topic, p)]
    with open(part.path, "rb") as f:
        return f.read()


def test_compaction_barrier_leaves_leader_and_follower_byte_identical(tmp_path):
    """Compaction on a replicated leader no longer refuses: the pass rides
    the replication stream as a barrier, the follower replays the identical
    generational swap, and the segment files are BYTE-identical afterwards
    (verbatim replication preserves offsets AND timestamps)."""
    lroot, froot = str(tmp_path / "l"), str(tmp_path / "f")
    leader_log = FileLog(lroot, fsync="none")
    follower_log = FileLog(froot, fsync="none")
    leader, follower, lport, fport = _pair(leader_log, follower_log)
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}")
        client.create_topic(TopicSpec("state", 2, compacted=True))
        led = Ledger(client, "t-compact")
        for round_ in range(6):
            for k in range(4):
                for p in range(2):
                    led.commit("state", f"k{k}", f"r{round_}-{k}-{p}".encode(),
                               partition=p)
        before = leader_log.end_offset("state", 0)

        stats = client.compact_topic("state", 0)
        assert stats["records_after"] < stats["records_before"]
        # offsets preserved, latest-per-key retained, tail record kept
        latest = leader_log.latest_by_key("state", 0)
        assert set(latest) == {f"k{k}" for k in range(4)}
        assert leader_log.end_offset("state", 0) == before

        for p in range(2):  # p=1 never compacted: byte-identical either way
            assert _seg_bytes(leader_log, "state", p) \
                == _seg_bytes(follower_log, "state", p), f"partition {p}"

        # post-barrier commits keep replicating on the compacted log
        led.commit("state", "k0", b"after-barrier")
        deadline = time.time() + 5
        while time.time() < deadline and (
                follower_log.end_offset("state", 0)
                != leader_log.end_offset("state", 0)):
            time.sleep(0.05)
        assert _seg_bytes(leader_log, "state", 0) \
            == _seg_bytes(follower_log, "state", 0)
        client.close()
    finally:
        leader.stop()
        follower.stop()
        leader_log.close()
        follower_log.close()


def test_dirty_ratio_scheduler_runs_supervised_on_replicated_leader(tmp_path):
    """The LogCompactor schedules the LEADER SERVER as its log: every pass it
    triggers goes through the replication barrier (never behind the stream's
    back), under health-bus supervision."""
    import asyncio

    from surge_tpu.health import HealthSignalBus, HealthSupervisor, RegexMatcher
    from surge_tpu.log.compactor import LogCompactor

    leader, follower, lport, fport = _pair()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}")
        client.create_topic(TopicSpec("state", 1, compacted=True))
        led = Ledger(client, "t-sched")
        for round_ in range(4):
            for k in range(8):
                led.commit("state", f"k{k}", f"r{round_}".encode())

        cfg = Config(overrides={
            "surge.log.compaction.interval-ms": 50,
            "surge.log.compaction.min-dirty-ratio": 0.01,
            "surge.log.compaction.min-dirty-records": 1,
            "surge.log.compaction.tombstone-retention-ms": 0})

        async def run():
            bus = HealthSignalBus(25)
            supervisor = HealthSupervisor(bus, cfg)
            compactor = LogCompactor(leader, config=cfg, topics=["state"],
                                     on_signal=bus.signal_fn("log-compactor"))
            supervisor.register("log-compactor", compactor,
                                restart_patterns=[
                                    RegexMatcher(r"log-compactor.*fatal")])
            supervisor.start()
            await compactor.start()
            deadline = time.time() + 10
            while not compactor.total_stats and time.time() < deadline:
                await asyncio.sleep(0.05)
            assert compactor.running
            await compactor.stop()
            supervisor.stop()
            return list(compactor.total_stats)

        stats = asyncio.run(run())
        assert stats, "scheduler never compacted"
        # the barrier converged the follower onto the same retained set
        assert dict(follower.log.latest_by_key("state", 0)).keys() \
            == dict(leader.log.latest_by_key("state", 0)).keys()
        assert follower.log.read("state", 0)[0].offset \
            == leader.log.read("state", 0)[0].offset
        client.close()
    finally:
        leader.stop()
        follower.stop()


# -- seeded chaos schedules -----------------------------------------------------------


def _chaos_round(seed: int, commits: int = 18) -> None:
    """One seeded schedule: flaky transport + ship drops + a mid-load leader
    crash with auto-promotion; every acked payload must appear exactly once
    on whichever broker ends up the leader."""
    leader, follower, lport, fport = _pair(auto_promote=True)
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport},127.0.0.1:{fport}")
        client.create_topic(TopicSpec("ev", 1))
        client.arm_faults(json.dumps({"rules": [
            {"site": "rpc.Transact", "action": "reorder", "p": 0.2,
             "times": None, "delay_ms": 30.0},
            {"site": "ship.*", "action": "drop", "p": 0.15, "times": None},
            {"site": "crash.transact.post-enqueue", "action": "crash",
             "after": 5 + seed % 7},
        ]}), seed=seed)
        led = Ledger(client, f"t-soak-{seed}")
        for i in range(commits):
            led.commit("ev", f"k{i}", f"s{seed}-{i}".encode(), timeout=60.0)
        assert len(led.acked) == commits
        status = follower.broker_status()
        assert status["role"] == "leader", "follower never promoted"
        _assert_exactly_once(follower.log, "ev", led.acked)
        client.close()
    finally:
        leader.stop()
        follower.stop()


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_chaos_failover_deterministic_seeds(seed):
    """Tier-1 fast variant of the soak: three fixed seeds, one leader kill
    each, exactly-once proven per seed."""
    _chaos_round(seed)


@pytest.mark.slow
def test_chaos_soak_randomized_schedules():
    """Minutes-long randomized (but seeded) soak across many schedules."""
    for seed in range(20, 32):
        _chaos_round(seed, commits=40)


# -- chaos CLI ------------------------------------------------------------------------


def test_chaos_cli_smoke():
    """tools/chaos.py end to end against a live broker: list plans, arm a
    named plan, read status/broker views, disarm."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cli = os.path.join(repo, "tools", "chaos.py")

    def run(*argv):
        out = subprocess.run([sys.executable, cli, *argv],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, (argv, out.stderr[-500:])
        return out.stdout

    assert "flaky-network" in run("plans")

    leader, follower, lport, fport = _pair()
    try:
        target = f"127.0.0.1:{lport}"
        stats = json.loads(run("arm", target, "fsync-hiccup", "--seed", "3"))
        assert stats["rules"][0]["site"] == "fsync.journal"
        assert json.loads(run("status", target))["seed"] == 3
        broker = json.loads(run("broker", target))
        assert broker["role"] == "leader" and broker["epoch"] == 1
        assert json.loads(run("disarm", target))["rules"] == []
        # promote drill against the follower
        promoted = json.loads(run("promote", f"127.0.0.1:{fport}"))
        assert promoted["role"] == "leader" and promoted["epoch"] == 2
    finally:
        leader.stop()
        follower.stop()


# -- reopen alias window --------------------------------------------------------------


def test_reopen_alias_window_absorbs_in_limbo_batch():
    """A producer reopened while its last commit is APPLIED but not yet
    follower-acked numbers PAST that seq; re-sending the same payload under
    the new seq must join/absorb the original — never append twice — and a
    retriable-timeout retry of the ALIAS seq must re-join the same original
    (the failover-bench duplicate class, closed at the broker)."""
    from surge_tpu.log.transport import ProducerFencedError as PFE

    cfg = Config(overrides={
        "surge.log.replication-ack-timeout-ms": 400,
        "surge.log.replication-isr-timeout-ms": 60_000,  # keep it in-sync
        "surge.log.txn-inorder-timeout-ms": 300,
    })
    lport, fport = free_ports(2)
    follower = LogServer(InMemoryLog(), port=fport,
                         follower_of=f"127.0.0.1:{lport}", config=cfg)
    follower.start()
    leader = LogServer(InMemoryLog(), port=lport,
                       replicate_to=[f"127.0.0.1:{fport}"], config=cfg)
    leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("ev", 1))
        p = client.transactional_producer("t")
        for i in range(2):
            p.begin()
            p.send(rec("ev", "k", f"v{i}".encode()))
            p.commit()  # seqs 1, 2 acked + replicated

        # blackhole ships: seq 3 applies locally, stays in-limbo
        leader.faults = FaultPlane([FaultRule(site="ship.*", action="drop",
                                              times=None)])
        p.begin()
        p.send(rec("ev", "k", b"limbo"))
        with pytest.raises(PFE):
            p.commit()  # retriable exhausted -> fenced (publisher ladder)
        assert leader.log.end_offset("ev", 0) == 3  # applied once

        # reopen: numbering starts PAST the in-limbo seq
        p2 = client.transactional_producer("t")
        assert p2._next_seq == 4
        # the alias retry while the batch is STILL in limbo answers
        # retriable (joins the pending item, which cannot ack yet)
        try:
            client._transact(p2._token, "commit", [rec("ev", "k", b"limbo")],
                             seq=4, attempts=2)
        except PFE:
            pass  # still unresolved: correct — the point is no re-append
        assert leader.log.end_offset("ev", 0) == 3  # STILL exactly one copy

        # heal the network: the worker finalizes the original; the alias
        # retry now answers from its cache with the ORIGINAL offsets
        leader.faults.disarm()
        out = client._transact(p2._token, "commit",
                               [rec("ev", "k", b"limbo")], seq=4)
        assert out.ok and [m.offset for m in out.records] == [2]
        assert leader.log.end_offset("ev", 0) == 3
        assert [r.value for r in leader.log.read("ev", 0)] == \
            [b"v0", b"v1", b"limbo"]
        # and the follower converges with exactly one copy too
        deadline = time.time() + 10
        while time.time() < deadline and \
                follower.log.end_offset("ev", 0) < 3:
            time.sleep(0.05)
        assert [r.value for r in follower.log.read("ev", 0)] == \
            [b"v0", b"v1", b"limbo"]
        # a fresh payload on the reopened producer appends normally (the raw
        # seq-4 transacts above bypassed the producer's counter: advance it)
        p2._next_seq = 5
        p2.begin()
        p2.send(rec("ev", "k", b"fresh"))
        assert p2.commit()[0].offset == 3
        client.close()
    finally:
        leader.stop()
        follower.stop()
