"""Fault-injection plane: deterministic decision engine, FileLog WAL fault
sites (torn journal writes, failed/stalled fsync rounds), and commit-journal
rotation (bounded growth + crash recovery across a rotation boundary)."""

import os
import shutil
import time

import pytest

from surge_tpu.log import FileLog, LogRecord, TopicSpec
from surge_tpu.testing.faults import (
    NAMED_PLANS,
    FaultPlane,
    FaultRule,
    SimulatedCrash,
)


def _commit(log, prod, topic, key, value, partition=0):
    prod.begin()
    prod.send(LogRecord(topic=topic, key=key, value=value,
                        partition=partition))
    return prod.commit()


# -- decision engine ------------------------------------------------------------------


def test_same_seed_same_schedule():
    """The plane is deterministic: identical seeds against identical call
    sequences fire identical faults (the chaos soak's reproducibility rests
    on this)."""
    def run(seed):
        plane = FaultPlane([FaultRule(site="ship.*", action="drop", p=0.5,
                                      times=None)], seed=seed)
        return [plane.on_ship("t") is not None for _ in range(64)]

    assert run(7) == run(7)
    assert run(7) != run(8)  # and the seed actually matters


def test_times_after_and_probability_bounds():
    plane = FaultPlane([FaultRule(site="rpc.Transact", action="drop",
                                  times=2, after=1)])
    fires = [plane.on_rpc("Transact") is not None for _ in range(5)]
    # skips the first crossing (after=1), fires twice (times=2), then stops
    assert fires == [False, True, True, False, False]
    assert plane.stats()["injected"] == 2
    # sites that do not match never fire
    assert plane.on_rpc("Read") is None


def test_arm_disarm_and_named_plans():
    plane = FaultPlane()
    assert plane.on_rpc("Transact") is None  # empty plane: no-op
    for name, factory in NAMED_PLANS.items():
        rules = factory()
        assert rules, name
        plane.arm(rules, seed=3)
        assert plane.stats()["rules"], name
    plane.disarm()
    assert plane.stats()["rules"] == []
    # from_spec accepts names and JSON
    assert FaultPlane.from_spec("torn-journal").rules[0].action == "torn"
    spec = '{"seed": 9, "rules": [{"site": "fsync.journal", "action": "error"}]}'
    p2 = FaultPlane.from_spec(spec)
    assert p2.seed == 9 and p2.rules[0].site == "fsync.journal"


def test_reorder_draws_bounded_holds():
    held = []
    plane = FaultPlane([FaultRule(site="rpc.Transact", action="reorder",
                                  times=None, delay_ms=40.0)],
                       seed=1, clock=held.append)
    for _ in range(16):
        plane.on_rpc("Transact")
    assert len(held) == 16
    assert all(0.0 <= h <= 0.040 for h in held)
    assert len(set(held)) > 1  # actually randomized, not a fixed delay


# -- FileLog WAL sites ----------------------------------------------------------------


def test_torn_journal_write_crash_recovers_committed_prefix(tmp_path):
    """Arm the torn-journal rule: the next commit's journal line is cut
    mid-write and the 'process' dies. Recovery must expose every earlier
    commit intact and the torn transaction not at all — then keep serving."""
    root = str(tmp_path / "log")
    flog = FileLog(root, fsync="commit")
    flog.create_topic(TopicSpec("ev", 1))
    prod = flog.transactional_producer("t")
    for i in range(3):
        _commit(flog, prod, "ev", f"k{i}", f"v{i}".encode())
    flog.faults = FaultPlane(NAMED_PLANS["torn-journal"]())  # arm live
    with pytest.raises(SimulatedCrash):
        _commit(flog, prod, "ev", "torn", b"never-durable")

    relog = FileLog(root, fsync="commit")
    got = [(r.key, r.value) for r in relog.read("ev", 0)]
    assert got == [(f"k{i}", f"v{i}".encode()) for i in range(3)]
    prod2 = relog.transactional_producer("t")
    _commit(relog, prod2, "ev", "k3", b"v3")
    assert [r.key for r in relog.read("ev", 0)] == ["k0", "k1", "k2", "k3"]
    relog.close()


def test_fsync_round_failure_fails_commit_then_heals(tmp_path):
    """fsync.journal error (times=1): the covered commit sees the failure —
    durability unknown, the caller's retry ladder owns it — and the next
    round succeeds."""
    root = str(tmp_path / "log")
    plane = FaultPlane([FaultRule(site="fsync.journal", action="error",
                                  times=1)])
    flog = FileLog(root, fsync="commit", faults=plane)
    flog.create_topic(TopicSpec("ev", 1))
    prod = flog.transactional_producer("t")
    with pytest.raises(OSError):
        _commit(flog, prod, "ev", "a", b"1")
    # the transient hiccup heals: the SAME producer commits on a later round
    _commit(flog, prod, "ev", "b", b"2")
    # the first transaction WAS applied (only its durability was unknown):
    # both records surface once the next round covers the journal
    assert [r.key for r in flog.read("ev", 0)] == ["a", "b"]
    flog.close()


def test_fsync_stall_holds_the_round(tmp_path):
    root = str(tmp_path / "log")
    plane = FaultPlane([FaultRule(site="fsync.journal", action="stall",
                                  delay_ms=150.0)])
    flog = FileLog(root, fsync="commit", faults=plane)
    flog.create_topic(TopicSpec("ev", 1))
    prod = flog.transactional_producer("t")
    t0 = time.perf_counter()
    _commit(flog, prod, "ev", "a", b"1")
    assert time.perf_counter() - t0 >= 0.14
    assert [r.key for r in flog.read("ev", 0)] == ["a"]
    flog.close()


# -- journal rotation -----------------------------------------------------------------


def _journal_size(root):
    return os.path.getsize(os.path.join(root, "commits.log"))


def test_rotation_bounds_journal_and_survives_restart(tmp_path):
    """With a tiny rotation threshold the journal must stay bounded (each
    generation is GC'd by the rename) while every committed record stays
    readable across a clean restart."""
    root = str(tmp_path / "log")
    flog = FileLog(root, fsync="commit", journal_rotate_bytes=4096)
    flog.create_topic(TopicSpec("ev", 2))
    prod = flog.transactional_producer("t")
    payload = os.urandom(256)
    for i in range(40):
        _commit(flog, prod, "ev", f"k{i}", payload, partition=i % 2)
    # wait out the gc worker's opportunistic rotation
    deadline = time.time() + 5.0
    while _journal_size(root) > 8192 and time.time() < deadline:
        time.sleep(0.05)
    assert _journal_size(root) <= 8192, "journal never rotated"
    flog.close()

    relog = FileLog(root, fsync="commit")
    for p in (0, 1):
        keys = [r.key for r in relog.read("ev", p)]
        assert keys == [f"k{i}" for i in range(40) if i % 2 == p]
    relog.close()


def test_forced_rotation_bounds_never_idle_leader_and_survives_crash(tmp_path):
    """A never-idle leader defeats the opportunistic quiesce check — a tight
    pipelined-commit loop keeps a fresh journal line in flight across every
    sync round, so the quiesced rotation never fires and the WAL would grow
    without bound. Past twice the threshold the size-forced barrier must
    rotate anyway (taking the log lock to MAKE the quiesced invariant true),
    and crashing right after a forced rotation must recover every record on
    both sides of the forced boundary."""
    from surge_tpu.observability import FlightRecorder

    root = str(tmp_path / "log")
    rotate = 4096
    flog = FileLog(root, fsync="commit", journal_rotate_bytes=rotate)
    flog.flight = FlightRecorder(name="b1", capacity=512)
    flog.create_topic(TopicSpec("ev", 1))
    prod = flog.transactional_producer("t")
    payload = os.urandom(700)

    def rotations():
        return [e for e in flog.flight.events()
                if e["type"] == "journal.rotate"]

    handles = []
    drained = 0
    n = 0
    max_seen = 0
    deadline = time.time() + 30.0
    while not any(e.get("forced") for e in rotations()):
        assert time.time() < deadline, "forced rotation never fired"
        prod.begin()
        prod.send(LogRecord(topic="ev", key=f"k{n}", value=payload))
        handles.append(prod.commit_pipelined())
        n += 1
        # a real publisher lane: bounded in-flight window, refilled the
        # moment the round resolves the oldest — so every sync round ends
        # with fresh lines already pending and the quiesce check keeps
        # failing, without the unthrottled loop starving the gc worker
        if n - drained >= 32:
            handles[drained].future.result(timeout=10.0)
            drained += 1
        max_seen = max(max_seen, _journal_size(root))
    # bounded: sustained load overshoots the 2x force ceiling by the
    # in-flight window plus whatever lands while the barrier waits for the
    # log lock — but stays within the same order of magnitude, not log-sized
    assert max_seen <= 16 * rotate, f"WAL grew unbounded ({max_seen} bytes)"

    # a couple of post-forced-boundary commits, then crash (copytree, no
    # close): recovery must serve both sides of the FORCED boundary
    for i in range(3):
        _commit(flog, prod, "ev", f"post{i}", b"tail")
    crash_root = str(tmp_path / "crash")
    shutil.copytree(root, crash_root)
    for h in handles:
        h.future.result(timeout=10.0)  # all durable before the clean close
    flog.close()

    relog = FileLog(crash_root, fsync="commit")
    keys = [r.key for r in relog.read("ev", 0)]
    assert keys == [f"k{i}" for i in range(n)] + [f"post{i}" for i in range(3)]
    prod2 = relog.transactional_producer("t")
    _commit(relog, prod2, "ev", "alive", b"1")
    assert [r.key for r in relog.read("ev", 0)][-1] == "alive"
    relog.close()


def test_crash_recovery_across_rotation_boundary(tmp_path):
    """Commit → rotate → commit more → crash (copytree, no close): recovery
    must serve BOTH sides of the rotation boundary — pre-rotation records now
    stand on their fsynced segments + the frontier line, post-rotation ones
    on the new journal's WAL lines."""
    root = str(tmp_path / "log")
    flog = FileLog(root, fsync="commit", journal_rotate_bytes=2048)
    flog.create_topic(TopicSpec("ev", 1))
    prod = flog.transactional_producer("t")
    payload = os.urandom(200)
    pre = 12
    for i in range(pre):
        _commit(flog, prod, "ev", f"pre{i}", payload)
    deadline = time.time() + 5.0
    while _journal_size(root) > 4096 and time.time() < deadline:
        time.sleep(0.05)
    assert _journal_size(root) <= 4096, "journal never rotated"
    # post-rotation commits (small: no second rotation)
    for i in range(3):
        _commit(flog, prod, "ev", f"post{i}", b"tail")

    crash_root = str(tmp_path / "crash")
    shutil.copytree(root, crash_root)  # crash: no close(), no final fsyncs
    flog.close()

    relog = FileLog(crash_root, fsync="commit")
    keys = [r.key for r in relog.read("ev", 0)]
    assert keys == [f"pre{i}" for i in range(pre)] + [f"post{i}"
                                                      for i in range(3)]
    # and the recovered log keeps accepting + rotating
    prod2 = relog.transactional_producer("t")
    _commit(relog, prod2, "ev", "alive", b"1")
    assert [r.key for r in relog.read("ev", 0)][-1] == "alive"
    relog.close()
