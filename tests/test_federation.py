"""Federated scrape: parser round trip, instance/role labelling, the
down-target / duplicate-family / type-conflict / skewed-staleness edge cases,
the fleet golden payload (canned engine+broker targets, coupled into
tools/regen_golden_metrics.py), and the live 3-broker + 1-engine federation
over real GetMetricsText RPCs + an HTTP scrape endpoint."""

import os

from conftest import free_ports
from surge_tpu.log import GrpcLogTransport, InMemoryLog, LogRecord, LogServer, TopicSpec
from surge_tpu.metrics import engine_metrics
from surge_tpu.metrics.exposition import (
    Family,
    MetricsHTTPServer,
    Sample,
    render_openmetrics,
)
from surge_tpu.metrics.fleet import fleet_metrics
from surge_tpu.observability import (
    FederatedScraper,
    ScrapeTarget,
    parse_openmetrics,
    target_from_spec,
)
from tests.test_exposition import (
    golden_broker_metrics,
    golden_engine_metrics,
    validate_openmetrics,
)

FLEET_GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden",
                                 "metrics_fleet.om")


# -- parser ---------------------------------------------------------------------------


def test_parser_round_trips_engine_registry():
    em = golden_engine_metrics()
    text = render_openmetrics(em.registry)
    fams = {f.name: f for f in parse_openmetrics(text)}
    # typed families survive with their samples
    assert fams["surge_engine_live_entities"].mtype == "gauge"
    assert fams["surge_engine_live_entities"].samples[0].value == 7.0
    assert fams["surge_producer_publish_failures"].mtype == "counter"
    hist = fams["surge_aggregate_state_fetch_timer_ms"]
    assert hist.mtype == "histogram"
    suffixes = {s.suffix for s in hist.samples}
    assert suffixes == {"_bucket", "_sum", "_count"}


def test_parser_reads_exemplars_and_label_escapes():
    text = ('# TYPE t_ms histogram\n'
            't_ms_bucket{le="10"} 1 # {trace_id="' + "ab" * 16 + '"} 7 1.5\n'
            't_ms_sum 7\nt_ms_count 1\n'
            '# TYPE g gauge\n'
            'g{topic="a\\"b\\\\c\\nd"} 2\n'
            'untyped_sample 3\n'
            '# EOF\n')
    fams = {f.name: f for f in parse_openmetrics(text)}
    bucket = fams["t_ms"].samples[0]
    assert bucket.exemplar == ("ab" * 16, 7.0, 1.5)
    assert fams["g"].samples[0].labels == (("topic", 'a"b\\c\nd'),)
    assert fams["untyped_sample"].mtype == "gauge"  # lenient fallback


# -- merge ----------------------------------------------------------------------------


def _scraper(targets, clock=lambda: 1000.0, **kw):
    return FederatedScraper(targets, clock=clock, **kw)


def test_merge_labels_every_sample_with_instance_and_role():
    em, bm = golden_engine_metrics(), golden_broker_metrics()
    s = _scraper([
        ScrapeTarget("e1", "engine",
                     fetch=lambda: render_openmetrics(em.registry)),
        ScrapeTarget("b1", "broker",
                     fetch=lambda: render_openmetrics(bm.registry)),
    ])
    assert s.scrape_once() == {"targets": 2, "up": 2, "errors": {}}
    text = s.render()
    families = validate_openmetrics(text)
    # per-instance labels on merged samples + the up gauges
    assert 'surge_engine_live_entities{instance="e1",role="engine"} 7' in text
    assert ('surge_log_replication_insync_replicas'
            '{instance="b1",role="broker"} 2') in text
    assert 'up{instance="e1",role="engine"} 1' in text
    assert 'up{instance="b1",role="broker"} 1' in text
    # duplicate family names across the two registries merge under ONE
    # TYPE declaration with both instances' samples
    assert text.count("# TYPE surge_log_failover_promotions counter") == 1
    fam = families["surge_log_failover_promotions"]
    labels = {lr for suffix, lr, _v in fam[1] if suffix == "_total"}
    assert labels == {'instance="e1",role="engine"',
                      'instance="b1",role="broker"'}
    # fleet self-instruments join the same payload
    assert "surge_fleet_up_targets 2" in text


def test_down_target_serves_stale_payload_with_up_zero():
    em = golden_engine_metrics()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] > 1:
            raise ConnectionError("target died")
        return render_openmetrics(em.registry)

    now = {"t": 1000.0}
    s = _scraper([ScrapeTarget("e1", "engine", fetch=flaky)],
                 clock=lambda: now["t"])
    assert s.scrape_once()["up"] == 1
    now["t"] = 1030.0
    summary = s.scrape_once()
    assert summary["up"] == 0 and "e1" in summary["errors"]
    text = s.render()
    validate_openmetrics(text)
    # the payload still renders: up flips to 0, the cached families keep
    # serving, and the staleness stamp carries their age
    assert 'up{instance="e1",role="engine"} 0' in text
    assert 'surge_engine_live_entities{instance="e1",role="engine"} 7' in text
    assert ('surge_fleet_scrape_staleness_seconds'
            '{instance="e1",role="engine"} 30') in text
    assert "surge_fleet_max_staleness_seconds 30" in text


def test_never_scraped_target_renders_up_zero_only():
    s = _scraper([ScrapeTarget("gone", "broker",
                               fetch=lambda: (_ for _ in ()).throw(
                                   ConnectionError("refused")))])
    s.scrape_once()
    text = s.render()
    validate_openmetrics(text)
    assert 'up{instance="gone",role="broker"} 0' in text
    assert 'staleness_seconds{instance="gone"' not in text  # nothing cached


def test_type_conflict_rehomes_under_type_suffixed_name():
    a = "# TYPE foo gauge\nfoo 1\n# EOF\n"
    b = "# TYPE foo counter\nfoo_total 2\n# EOF\n"
    s = _scraper([ScrapeTarget("x", "engine", fetch=lambda: a),
                  ScrapeTarget("y", "broker", fetch=lambda: b)])
    s.scrape_once()
    text = s.render()
    families = validate_openmetrics(text)
    assert families["foo"][0] == "gauge"
    assert families["foo_counter"][0] == "counter"  # re-homed, not dropped


def test_reserved_labels_from_targets_are_renamed():
    payload = ('# TYPE g gauge\n'
               'g{instance="liar",role="fake"} 5\n# EOF\n')
    s = _scraper([ScrapeTarget("real", "broker", fetch=lambda: payload)])
    s.scrape_once()
    text = s.render()
    validate_openmetrics(text)
    assert ('g{instance="real",role="broker",'
            'exported_instance="liar",exported_role="fake"} 5') in text


def test_skewed_staleness_stamps_per_instance():
    """Two targets whose payloads aged differently carry DIFFERENT stamps —
    the fleet view never averages staleness away."""
    em, bm = golden_engine_metrics(), golden_broker_metrics()
    healthy = lambda: render_openmetrics(em.registry)  # noqa: E731
    calls = {"n": 0}

    def dies_after_first(_bm=bm):
        calls["n"] += 1
        if calls["n"] > 1:
            raise TimeoutError("skewed")
        return render_openmetrics(_bm.registry)

    now = {"t": 0.0}
    s = _scraper([ScrapeTarget("fresh", "engine", fetch=healthy),
                  ScrapeTarget("stale", "broker", fetch=dies_after_first)],
                 clock=lambda: now["t"])
    s.scrape_once()
    now["t"] = 60.0
    s.scrape_once()
    text = s.render()
    assert ('surge_fleet_scrape_staleness_seconds'
            '{instance="fresh",role="engine"} 0') in text
    assert ('surge_fleet_scrape_staleness_seconds'
            '{instance="stale",role="broker"} 60') in text


# -- golden ---------------------------------------------------------------------------


def golden_fleet_scrape() -> FederatedScraper:
    """The canonical deterministic federation: the engine and broker golden
    recording sequences as two canned targets under a pinned clock
    (tools/regen_golden_metrics.py re-renders this into metrics_fleet.om).
    Exercises the real merge: instance/role labelling, duplicate-family
    collapse (the shared failover/faults counters), up + staleness gauges,
    and the fleet self-instruments."""
    em, bm = golden_engine_metrics(), golden_broker_metrics()
    scraper = FederatedScraper(
        [ScrapeTarget("engine-0", "engine",
                      fetch=lambda: render_openmetrics(em.registry)),
         ScrapeTarget("broker-0", "broker",
                      fetch=lambda: render_openmetrics(bm.registry))],
        metrics=fleet_metrics(), clock=lambda: 1_700_000_000.0)
    scraper.scrape_once()
    return scraper


def test_fleet_render_matches_golden():
    text = golden_fleet_scrape().render()
    validate_openmetrics(text)
    with open(FLEET_GOLDEN_PATH) as f:
        golden = f.read()
    assert text == golden, (
        "federated OpenMetrics payload drifted from tests/golden/"
        "metrics_fleet.om — if the change is intentional run "
        "tools/regen_golden_metrics.py and update the docs/observability.md "
        "fleet catalog (golden and catalog are coupled; regen both together)")


# -- live federation (3 brokers + 1 engine) -------------------------------------------


def test_live_federation_three_brokers_one_engine():
    """The acceptance shape: three real LogServers scraped over their
    GetMetricsText RPC plus one engine registry over a real HTTP scrape
    endpoint, merged into one grammar-valid payload with per-instance labels
    and up gauges — then one broker dies and the payload degrades honestly."""
    ports = free_ports(3)
    brokers = []
    try:
        for port in ports:
            srv = LogServer(InMemoryLog(), port=port)
            srv.start()
            brokers.append(srv)
        client = GrpcLogTransport(f"127.0.0.1:{ports[0]}")
        client.create_topic(TopicSpec("ev", 1))
        p = client.transactional_producer("t")
        p.begin()
        p.send(LogRecord(topic="ev", key="k", value=b"v"))
        p.commit()
        client.close()

        em = engine_metrics()
        em.live_entities.record(3)
        http = MetricsHTTPServer(em.registry)
        http_port = http.start()
        try:
            specs = [f"broker@127.0.0.1:{p}" for p in ports]
            specs.append(f"engine@http://127.0.0.1:{http_port}/metrics")
            scraper = FederatedScraper(specs)
            try:
                summary = scraper.scrape_once()
                assert summary == {"targets": 4, "up": 4, "errors": {}}
                text = scraper.render()
                families = validate_openmetrics(text)
                for port in ports:
                    assert (f'up{{instance="127.0.0.1:{port}",'
                            f'role="broker"}} 1') in text
                assert (f'up{{instance="127.0.0.1:{http_port}",'
                        f'role="engine"}} 1') in text
                # per-broker registries merged under one TYPE block each
                fam = families["surge_log_journal_fsync_round_timer_ms"]
                assert fam[0] == "histogram"
                assert ('surge_engine_live_entities'
                        f'{{instance="127.0.0.1:{http_port}",'
                        'role="engine"} 3') in text
                # the scraper's own scrape port serves the same merge
                fleet_port = scraper.serve()
                import urllib.request

                with urllib.request.urlopen(
                        f"http://127.0.0.1:{fleet_port}/metrics") as resp:
                    body = resp.read().decode()
                validate_openmetrics(body)
                assert "surge_fleet_up_targets 4" in body
                # one broker dies: the next pass still renders, up drops
                brokers[1].stop()
                summary = scraper.scrape_once()
                assert summary["up"] == 3
                text = scraper.render()
                validate_openmetrics(text)
                assert (f'up{{instance="127.0.0.1:{ports[1]}",'
                        'role="broker"} 0') in text
            finally:
                scraper.stop()
        finally:
            http.stop()
    finally:
        for b in brokers:
            try:
                b.stop()
            except Exception:  # noqa: BLE001 — one already stopped
                pass


def test_target_from_spec_parsing():
    t = target_from_spec("broker@127.0.0.1:16001")
    assert (t.role, t.address, t.instance) == (
        "broker", "127.0.0.1:16001", "127.0.0.1:16001")
    t = target_from_spec("engine@http://host:9464/metrics")
    assert t.role == "engine" and t.instance == "host:9464"
    t = target_from_spec("127.0.0.1:16002")  # bare addr defaults to broker
    assert t.role == "broker"


def test_merged_families_returns_sorted_families():
    em = golden_engine_metrics()
    s = _scraper([ScrapeTarget("e", "engine",
                               fetch=lambda: render_openmetrics(em.registry))])
    s.scrape_once()
    names = [f.name for f in s.merged_families()]
    assert names == sorted(names)


def test_family_dataclass_reuse():
    """The parser emits the exposition module's own Family/Sample types, so
    merged families re-render through the same _render_family path."""
    fams = parse_openmetrics("# TYPE x gauge\nx 1\n# EOF\n")
    assert isinstance(fams[0], Family)
    assert isinstance(fams[0].samples[0], Sample)


def test_scrape_and_render_one_call():
    em = golden_engine_metrics()
    s = _scraper([ScrapeTarget("e", "engine",
                               fetch=lambda: render_openmetrics(em.registry))])
    text = s.scrape_and_render()
    validate_openmetrics(text)
    assert 'up{instance="e",role="engine"} 1' in text
