"""FileLog: durable transport parity with InMemoryLog + crash recovery.

The EmbeddedKafka-analog contract (SURVEY.md §4) must hold identically for the durable
backend: atomic multi-topic transactions, epoch fencing (now surviving restarts),
read_committed views, compaction, torn-write recovery via the commit journal.
"""

import json
import os
import random

import pytest

from surge_tpu.log import (
    FileLog,
    InMemoryLog,
    LogRecord,
    ProducerFencedError,
    TopicSpec,
)
from surge_tpu.log import segment as seg


@pytest.fixture
def root(tmp_path):
    return str(tmp_path / "log")


def _fresh(root, **kw):
    return FileLog(root, fsync="none", **kw)


def test_fuzz_random_crash_points_preserve_committed_frontier(tmp_path):
    """Randomized crash-recovery fuzz: run a random transactional workload with
    fsync=commit, snapshot every file's size at each commit (the fsync points),
    then truncate data files and the journal to RANDOM independent lengths at
    or beyond a random committed frontier k — modelling lost unsynced tails
    AND post-fsync tail corruption in any combination across files. Reopening
    must expose the first k transactions' records intact as a prefix (they
    were fsynced at k), only later-transaction data beyond it (in the
    corruption model later txns may surface partially clamped, value-wise a
    subset of what was committed — never invented or aborted data), no
    records at all on untouched partitions, and the log must accept new
    transactions afterwards."""
    import shutil

    for seed in range(6):
        rng = random.Random(100 + seed)
        root = str(tmp_path / f"fuzz-{seed}")
        flog = FileLog(root, fsync="commit")
        flog.create_topic(TopicSpec("ev", 2))
        flog.create_topic(TopicSpec("st", 1, compacted=True))
        prod = flog.transactional_producer("fz")
        committed: list = []  # per txn: list of (topic, partition, value)
        sizes: list = []  # per txn: {relpath: size}

        def walk_sizes():
            out = {}
            for dirpath, _, files in os.walk(root):
                for fn in files:
                    p = os.path.join(dirpath, fn)
                    out[os.path.relpath(p, root)] = os.path.getsize(p)
            return out

        for t in range(rng.randrange(4, 10)):
            prod.begin()
            recs = []
            for _ in range(rng.randrange(1, 5)):
                topic = rng.choice(["ev", "ev", "st"])
                part = rng.randrange(2) if topic == "ev" else 0
                val = f"txn{t}-{rng.randrange(1000)}".encode()
                prod.send(LogRecord(topic=topic, key=f"k{rng.randrange(6)}",
                                    value=val, partition=part))
                recs.append((topic, part, val))
            if rng.random() < 0.15:
                prod.abort()
            else:
                prod.commit()
                committed.append(recs)
                sizes.append(walk_sizes())
        flog.close()
        if not committed:
            continue

        # crash: keep everything up to commit k, then cut each file somewhere
        # between its size-at-k and its final size (unsynced tail may be lost
        # in ANY combination across files)
        k = rng.randrange(len(committed))
        crash_root = str(tmp_path / f"fuzz-{seed}-crash")
        shutil.copytree(root, crash_root)
        frontier = sizes[k]
        final = walk_sizes()
        for rel, size_k in frontier.items():
            p = os.path.join(crash_root, rel)
            if not os.path.exists(p):
                continue
            hi = final.get(rel, size_k)
            cut = rng.randrange(size_k, hi + 1) if hi > size_k else size_k
            with open(p, "r+b") as f:
                f.truncate(cut)

        relog = FileLog(crash_root, fsync="commit")
        want: dict = {}
        for recs in committed[: k + 1]:
            for topic, part, val in recs:
                want.setdefault((topic, part), []).append(val)
        for topic, part in (("ev", 0), ("ev", 1), ("st", 0)):
            got = [r.value for r in relog.read(topic, part)]
            vals = want.get((topic, part), [])
            # committed frontier k must be fully present as a prefix
            assert got[: len(vals)] == vals, (seed, topic, part)
            # anything beyond it must come from LATER committed transactions —
            # never aborted or invented data (partitions with no committed
            # records must read back empty apart from such later survivors)
            extra = got[len(vals):]
            later = [v for recs in committed[k + 1:] for tp, pp, v in recs
                     if (tp, pp) == (topic, part)]
            for v in extra:
                assert v in later, (seed, topic, part, v)
        # the reopened log must still accept traffic
        p2 = relog.transactional_producer("fz2")
        p2.begin()
        p2.send(LogRecord(topic="ev", key="post", value=b"alive", partition=0))
        p2.commit()
        assert [r.value for r in relog.read("ev", 0)][-1] == b"alive"
        relog.close()


def test_randomized_parity_with_memory_log(root):
    rng = random.Random(3)
    flog, mlog = _fresh(root), InMemoryLog()
    for log in (flog, mlog):
        log.create_topic(TopicSpec("events", 2))
        log.create_topic(TopicSpec("state", 2, compacted=True))
    fp, mp = (flog.transactional_producer("tx"), mlog.transactional_producer("tx"))
    keys = [f"agg-{i}" for i in range(20)]
    for _ in range(60):
        n = rng.randrange(1, 6)
        fp.begin(), mp.begin()
        for _ in range(n):
            key = rng.choice(keys)
            part = rng.randrange(2)
            value = None if rng.random() < 0.1 else rng.randbytes(rng.randrange(0, 50))
            topic = rng.choice(["events", "state"])
            headers = {"h": "v"} if rng.random() < 0.3 else {}
            for prod in (fp, mp):
                prod.send(LogRecord(topic=topic, key=key, value=value, partition=part,
                                    headers=headers))
        if rng.random() < 0.15:
            fp.abort(), mp.abort()
        else:
            fr, mr = fp.commit(), mp.commit()
            assert [(r.topic, r.partition, r.offset, r.key, r.value) for r in fr] == \
                   [(r.topic, r.partition, r.offset, r.key, r.value) for r in mr]
    for topic in ("events", "state"):
        for p in range(2):
            f = [(r.offset, r.key, r.value, r.headers) for r in flog.read(topic, p)]
            m = [(r.offset, r.key, r.value, r.headers) for r in mlog.read(topic, p)]
            assert f == m
            assert flog.end_offset(topic, p) == mlog.end_offset(topic, p)
            fl = {k: (v.offset, v.value) for k, v in flog.latest_by_key(topic, p).items()}
            ml = {k: (v.offset, v.value) for k, v in mlog.latest_by_key(topic, p).items()}
            assert fl == ml
    flog.close()


def test_reopen_resumes_offsets_and_data(root):
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("tx")
    prod.begin()
    for i in range(5):
        prod.send(LogRecord(topic="t", key=f"k{i}", value=f"v{i}".encode()))
    prod.commit()
    log.close()

    log2 = _fresh(root)
    assert log2.end_offset("t", 0) == 5
    assert [r.value for r in log2.read("t", 0)] == [f"v{i}".encode() for i in range(5)]
    prod2 = log2.transactional_producer("tx")
    prod2.begin()
    prod2.send(LogRecord(topic="t", key="k9", value=b"after"))
    (r,) = prod2.commit()
    assert r.offset == 5
    log2.close()


def test_fencing_survives_restart(root):
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    old = log.transactional_producer("pub-0")
    log.close()

    log2 = _fresh(root)
    new = log2.transactional_producer("pub-0")  # epoch bumps past the durable one
    # the pre-restart producer handle is fenced against the reopened log
    with pytest.raises(ProducerFencedError):
        log2._check_epoch("pub-0", old.epoch)
    new.begin()
    new.send(LogRecord(topic="t", key="k", value=b"v"))
    new.commit()
    log2.close()


def test_torn_data_tail_is_truncated(root):
    """Data blocks written without a journal line (crash between data fsync and
    journal fsync) must disappear on recovery."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("tx")
    prod.begin()
    prod.send(LogRecord(topic="t", key="a", value=b"committed"))
    prod.commit()
    log.close()

    seg_path = os.path.join(root, "data", "t-0.seg")
    block = seg.encode_block(
        [LogRecord(topic="t", key="b", value=b"uncommitted", offset=1)], 1)
    with open(seg_path, "ab") as f:
        f.write(block[: len(block) - 3])  # torn mid-block, no journal entry

    log2 = _fresh(root)
    assert log2.end_offset("t", 0) == 1
    assert [r.value for r in log2.read("t", 0)] == [b"committed"]
    # and the log keeps working past the recovered frontier
    p2 = log2.transactional_producer("tx")
    p2.begin()
    p2.send(LogRecord(topic="t", key="c", value=b"next"))
    (r,) = p2.commit()
    assert r.offset == 1
    log2.close()


def test_torn_journal_line_is_ignored(root):
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("tx")
    prod.begin()
    prod.send(LogRecord(topic="t", key="a", value=b"one"))
    prod.commit()
    log.close()
    with open(os.path.join(root, "commits.log"), "ab") as f:
        f.write(b'{"parts": [["t", 0, 77')  # crash mid journal write

    log2 = _fresh(root)
    assert log2.end_offset("t", 0) == 1
    log2.close()


def test_abort_discards_and_immediate_appends(root):
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("tx")
    prod.begin()
    prod.send(LogRecord(topic="t", key="x", value=b"gone"))
    prod.abort()
    assert log.end_offset("t", 0) == 0
    r = prod.send_immediate(LogRecord(topic="t", key="y", value=b"kept"))
    assert r.offset == 0
    log.close()


def test_blocks_are_compressed_when_codec_built(root):
    if not seg.native_codec_available():
        pytest.skip("native segment codec not built")
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("tx")
    prod.begin()
    for i in range(200):
        prod.send(LogRecord(topic="t", key=f"agg-{i}",
                            value=json.dumps({"count": i, "version": i}).encode()))
    prod.commit()
    log.close()
    raw = open(os.path.join(root, "data", "t-0.seg"), "rb").read()
    codec = raw[4]
    assert codec == seg.CODEC_SLZ
    # compressed block is much smaller than the ~200 records * ~30B payload
    assert len(raw) < 3000


def test_tombstone_round_trip(root):
    log = _fresh(root)
    log.create_topic(TopicSpec("s", 1, compacted=True))
    prod = log.transactional_producer("tx")
    prod.begin()
    prod.send(LogRecord(topic="s", key="a", value=b"v1"))
    prod.send(LogRecord(topic="s", key="b", value=b"v2"))
    prod.commit()
    prod.begin()
    prod.send(LogRecord(topic="s", key="a", value=None))  # tombstone
    prod.commit()
    log.close()
    log2 = _fresh(root)
    latest = log2.latest_by_key("s", 0)
    assert set(latest) == {"b"}
    rec = log2.read("s", 0)[2]
    assert rec.key == "a" and rec.value is None
    log2.close()


def test_engine_end_to_end_on_file_log(root):
    """Full engine over the durable transport: commands → transactional publish →
    indexer, then a cold restart on a fresh FileLog instance resumes every
    aggregate's state from disk (the reference's restart-from-Kafka story, §5.4)."""
    import asyncio

    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.models import counter

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 2,
    })

    def logic():
        return SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting())

    async def scenario():
        log = _fresh(root)
        engine = create_engine(logic(), log=log, config=cfg)
        await engine.start()
        for i in range(12):
            ref = engine.aggregate_for(f"agg-{i}")
            for _ in range(i % 4 + 1):
                await ref.send_command(counter.Increment(f"agg-{i}"))
        await engine.stop()
        log.close()

        # cold restart: fresh FileLog over the same directory
        log2 = _fresh(root)
        engine2 = create_engine(logic(), log=log2, config=cfg)
        await engine2.start()
        for i in range(12):
            st = await engine2.aggregate_for(f"agg-{i}").get_state()
            assert st is not None and st.count == i % 4 + 1, (i, st)
        # and new writes continue cleanly after recovery
        r = await engine2.aggregate_for("agg-0").send_command(
            counter.Increment("agg-0"))
        assert r.state.count == 2
        await engine2.stop()
        log2.close()

    asyncio.run(scenario())


def test_commit_after_torn_journal_survives_second_restart(root):
    """Regression: a torn journal tail must be truncated at recovery, or the next
    commit's line concatenates onto it and a SECOND restart loses that commit."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()
    log.close()
    with open(os.path.join(root, "commits.log"), "ab") as f:
        f.write(b'{"parts": [["t", 0, 9')  # torn, no newline

    log2 = _fresh(root)
    p2 = log2.transactional_producer("tx")
    p2.begin(); p2.send(LogRecord(topic="t", key="b", value=b"B")); p2.commit()
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"B"]
    log2.close()

    log3 = _fresh(root)  # the commit made after recovery must still be durable
    assert [r.value for r in log3.read("t", 0)] == [b"A", b"B"]
    log3.close()


def test_failed_journal_write_rolls_back_data_blocks(root):
    """Regression: if the journal write fails, the staged data blocks must be
    physically truncated — otherwise a later commit journals a frontier that
    resurrects the aborted block on recovery."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()

    class Boom(RuntimeError):
        pass

    real_journal = log._journal

    class FailingJournal:
        def write(self, data):
            raise Boom()

        def flush(self):
            pass

        def tell(self):
            return real_journal.tell()

        def truncate(self, n):
            return real_journal.truncate(n)

        def seek(self, *a):
            return real_journal.seek(*a)

        def fileno(self):
            return real_journal.fileno()

        def close(self):
            real_journal.close()

    log._journal = FailingJournal()
    p.begin(); p.send(LogRecord(topic="t", key="b", value=b"LOST"))
    with pytest.raises(Boom):
        p.commit()
    log._journal = real_journal

    p.begin(); p.send(LogRecord(topic="t", key="c", value=b"C"))
    (r,) = p.commit()
    assert r.offset == 1
    log.close()

    log2 = _fresh(root)
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"C"]
    log2.close()


def test_failed_partition_write_rolls_back_own_torn_bytes(root):
    """Regression (r2 advisor): when a partition's OWN write/flush raises mid-commit,
    its torn bytes must be truncated too — not just the partitions already staged —
    or later commits append after garbage and corrupt the partition until restart."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 2))
    p = log.transactional_producer("tx")
    p.begin()
    p.send(LogRecord(topic="t", key="a", value=b"A0", partition=0))
    p.send(LogRecord(topic="t", key="a", value=b"A1", partition=1))
    p.commit()

    class Boom(RuntimeError):
        pass

    part1 = log._parts[("t", 1)]
    real_file = part1.file

    class TornWriteFile:
        """Writes land (torn bytes on disk) but flush explodes once."""

        def __init__(self):
            self.armed = True

        def write(self, data):
            return real_file.write(data)

        def flush(self):
            if self.armed:
                self.armed = False
                real_file.flush()  # make sure the torn bytes really hit the file
                raise Boom()
            return real_file.flush()

        def truncate(self, n):
            return real_file.truncate(n)

        def seek(self, *a):
            return real_file.seek(*a)

        def fileno(self):
            return real_file.fileno()

        def close(self):
            return real_file.close()

    part1.file = TornWriteFile()
    p.begin()
    p.send(LogRecord(topic="t", key="b", value=b"B0", partition=0))
    p.send(LogRecord(topic="t", key="b", value=b"LOST", partition=1))
    with pytest.raises(Boom):
        p.commit()
    part1.file = real_file

    # same-process follow-up commit must land cleanly on both partitions
    p.begin()
    p.send(LogRecord(topic="t", key="c", value=b"C0", partition=0))
    p.send(LogRecord(topic="t", key="c", value=b"C1", partition=1))
    p.commit()
    assert [r.value for r in log.read("t", 0)] == [b"A0", b"C0"]
    assert [r.value for r in log.read("t", 1)] == [b"A1", b"C1"]
    log.close()

    log2 = _fresh(root)  # and survive recovery
    assert [r.value for r in log2.read("t", 0)] == [b"A0", b"C0"]
    assert [r.value for r in log2.read("t", 1)] == [b"A1", b"C1"]
    log2.close()


def _strip_journal_payloads(root):
    """Rewrite commits.log without the embedded "blk" payloads — simulates a
    pre-WAL journal (or oversized, non-embedded blocks) so the clamp paths
    stay testable now that recovery normally backfills from the payloads."""
    import json as _json
    import os as _os

    path = _os.path.join(root, "commits.log")
    lines = []
    with open(path, "rb") as f:
        for line in f:
            entry = _json.loads(line)
            entry.pop("blk", None)
            lines.append((_json.dumps(entry) + "\n").encode())
    with open(path, "wb") as f:
        f.writelines(lines)


def test_journal_ahead_of_data_backfills_from_wal_payloads(root):
    """A crash can persist the journal line but lose data-file bytes; the
    journal line embeds the block (WAL mode), so the reopened log
    re-materializes the lost tail instead of dropping the committed record."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()
    first_end_pos = log._parts[("t", 0)].end_pos
    p.begin(); p.send(LogRecord(topic="t", key="b", value=b"B")); p.commit()
    log.close()

    # crash simulation: journal retained both lines, data lost the second block's tail
    seg_path = log._parts[("t", 0)].path
    with open(seg_path, "r+b") as f:
        f.truncate(first_end_pos + 7)  # mid-header of block 2

    log2 = _fresh(root)  # block 2 rebuilt from its journal payload
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"B"]
    assert log2.end_offset("t", 0) == 2
    p2 = log2.transactional_producer("tx")
    p2.begin(); p2.send(LogRecord(topic="t", key="c", value=b"C")); p2.commit()
    log2.close()

    log3 = _fresh(root)  # the backfilled frontier + new commit survive another restart
    assert [r.value for r in log3.read("t", 0)] == [b"A", b"B", b"C"]
    assert log3.end_offset("t", 0) == 3
    log3.close()


def test_journal_ahead_of_data_without_payloads_clamps_to_intact_prefix(root):
    """Regression (r2 advisor): when no journal payload exists (pre-WAL journal
    or oversized block under fsync='none'), the reopened log must clamp to the
    last intact block instead of raising BlockCorruptError from the
    constructor."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()
    first_end_pos = log._parts[("t", 0)].end_pos
    p.begin(); p.send(LogRecord(topic="t", key="b", value=b"B")); p.commit()
    log.close()
    seg_path = log._parts[("t", 0)].path
    with open(seg_path, "r+b") as f:
        f.truncate(first_end_pos + 7)  # mid-header of block 2
    _strip_journal_payloads(root)

    log2 = _fresh(root)  # must open, clamped to block 1
    assert [r.value for r in log2.read("t", 0)] == [b"A"]
    assert log2.end_offset("t", 0) == 1
    p2 = log2.transactional_producer("tx")
    p2.begin(); p2.send(LogRecord(topic="t", key="c", value=b"C")); p2.commit()
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"C"]
    log2.close()

    log3 = _fresh(root)  # the clamped frontier + new commit survive another restart
    assert [r.value for r in log3.read("t", 0)] == [b"A", b"C"]
    assert log3.end_offset("t", 0) == 2
    log3.close()


def test_whole_data_file_lost_backfills_from_wal_payloads(root):
    """Extreme crash: the data file never reached disk at all — every journaled
    block is re-materialized from its embedded payload."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"KEPT")); p.commit()
    seg_path = log._parts[("t", 0)].path
    log.close()
    import os as _os
    _os.remove(seg_path)

    log2 = _fresh(root)
    assert [r.value for r in log2.read("t", 0)] == [b"KEPT"]
    assert log2.end_offset("t", 0) == 1
    p2 = log2.transactional_producer("tx")
    p2.begin(); p2.send(LogRecord(topic="t", key="b", value=b"B")); p2.commit()
    log2.close()
    log3 = _fresh(root)
    assert [r.value for r in log3.read("t", 0)] == [b"KEPT", b"B"]
    log3.close()


def test_whole_data_file_lost_without_payloads_clamps_to_empty(root):
    """The payload-less variant of the total-data-loss crash clamps to empty."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"GONE")); p.commit()
    seg_path = log._parts[("t", 0)].path
    log.close()
    import os as _os
    _os.remove(seg_path)
    _strip_journal_payloads(root)

    log2 = _fresh(root)
    assert log2.read("t", 0) == []
    assert log2.end_offset("t", 0) == 0
    p2 = log2.transactional_producer("tx")
    p2.begin(); p2.send(LogRecord(topic="t", key="b", value=b"B")); p2.commit()
    log2.close()
    log3 = _fresh(root)
    assert [r.value for r in log3.read("t", 0)] == [b"B"]
    log3.close()


def test_partial_journal_line_is_rolled_back(root):
    """A journal flush that fails after a partial OS write must not leave a torn
    half-line poisoning the journal — later committed transactions would be
    discarded by recovery's torn-tail scan."""
    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()

    class Boom(RuntimeError):
        pass

    real_journal = log._journal

    class HalfWriteJournal:
        """Half the line reaches the file, then flush explodes."""

        def write(self, data):
            real_journal.write(data[: len(data) // 2])

        def flush(self):
            real_journal.flush()
            raise Boom()

        def tell(self):
            return real_journal.tell()

        def truncate(self, n):
            return real_journal.truncate(n)

        def seek(self, *a):
            return real_journal.seek(*a)

        def fileno(self):
            return real_journal.fileno()

        def close(self):
            return real_journal.close()

    log._journal = HalfWriteJournal()
    p.begin(); p.send(LogRecord(topic="t", key="b", value=b"LOST"))
    with pytest.raises(Boom):
        p.commit()
    log._journal = real_journal

    # an acknowledged commit AFTER the failed one must survive restart
    p.begin(); p.send(LogRecord(topic="t", key="c", value=b"C")); p.commit()
    log.close()
    log2 = _fresh(root)
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"C"]
    log2.close()


def test_garbled_payload_with_intact_header_repairs_or_clamps_at_open(root):
    """Unordered writeback can persist a block header but garble its payload;
    recovery must CRC-check it — repairing from the journal payload when one
    exists, clamping otherwise — rather than index a block whose first read
    would crash the indexer."""
    from surge_tpu.log import segment as seg

    log = _fresh(root)
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("tx")
    p.begin(); p.send(LogRecord(topic="t", key="a", value=b"A")); p.commit()
    first_end = log._parts[("t", 0)].end_pos
    p.begin(); p.send(LogRecord(topic="t", key="b", value=b"B" * 64)); p.commit()
    seg_path = log._parts[("t", 0)].path
    log.close()

    # garble block 2's payload, leaving its header intact
    def garble():
        with open(seg_path, "r+b") as f:
            f.seek(first_end + seg.HEADER_SIZE)
            f.write(b"\x00" * 8)

    garble()
    log2 = _fresh(root)  # WAL payload repairs the garbled block in place
    assert [r.value for r in log2.read("t", 0)] == [b"A", b"B" * 64]
    assert log2.end_offset("t", 0) == 2
    log2.close()

    garble()
    _strip_journal_payloads(root)
    log3 = _fresh(root)  # no payload: clamp to the intact prefix
    assert [r.value for r in log3.read("t", 0)] == [b"A"]
    assert log3.end_offset("t", 0) == 1
    p3 = log3.transactional_producer("tx")
    p3.begin(); p3.send(LogRecord(topic="t", key="c", value=b"C")); p3.commit()
    assert [r.value for r in log3.read("t", 0)] == [b"A", b"C"]
    log3.close()
