"""Flight recorder + failover-timeline reconstruction: ring-buffer semantics,
the merge/reconstruction library on CANNED dumps (no live brokers — the
tier-1-safe smoke for tools/flight_timeline.py), the broker's DumpFlight /
GetMetricsText RPCs, the crash auto-dump, and the chaos CLI's status tail."""

import json
import os
import subprocess
import sys
import time

import pytest

from conftest import free_ports
from surge_tpu.config import Config
from surge_tpu.log import GrpcLogTransport, InMemoryLog, LogRecord, LogServer, TopicSpec
from surge_tpu.observability import FlightRecorder, merge_dumps, reconstruct_failover

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- ring buffer ----------------------------------------------------------------------


def test_recorder_is_bounded_and_ordered():
    rec = FlightRecorder(capacity=16, name="b1")
    for i in range(40):
        rec.record("tick", i=i)
    events = rec.events()
    assert len(events) == 16  # ring: oldest 24 evicted
    assert [e["i"] for e in events] == list(range(24, 40))
    assert [e["seq"] for e in events] == list(range(25, 41))  # seq never resets
    monos = [e["mono"] for e in events]
    assert monos == sorted(monos)
    assert rec.events(last=3) == events[-3:]
    assert rec.events(last=0) == []  # 0 means none, not "the whole ring"
    dump = rec.dump()
    assert dump["recorder"] == "b1" and dump["node"] and dump["pid"]
    assert len(dump["events"]) == 16


def test_recorder_counts_dropped_events_and_reports_stats():
    """The bounded ring must be able to tell an operator it wrapped: stats
    carry occupancy + dropped count, and the dump envelope ships them."""
    rec = FlightRecorder(capacity=16, name="b1")
    assert rec.stats() == {"events": 0, "capacity": 16, "dropped": 0}
    for i in range(40):
        rec.record("tick", i=i)
    assert rec.stats() == {"events": 16, "capacity": 16, "dropped": 24}
    dump = rec.dump()
    assert dump["stats"]["dropped"] == 24
    assert dump["role"] == "broker"  # default lane
    eng = FlightRecorder(name="engine:x", role="engine")
    eng.record("lane.dispatch", partition=0)
    assert eng.dump()["role"] == "engine"


def test_merge_tags_each_event_with_its_dump_lane():
    broker = {"recorder": "b1", "node": "h", "events": [
        {"seq": 1, "mono": 1.0, "wall": 1.0, "type": "broker.kill"}]}
    engine = {"recorder": "engine:c", "node": "h", "role": "engine",
              "events": [
                  {"seq": 1, "mono": 2.0, "wall": 2.0, "type": "lane.fence"},
                  {"seq": 2, "mono": 3.0, "wall": 3.0, "type": "lane.rejoin"},
              ]}
    merged = merge_dumps([broker, engine])
    assert [(e["type"], e["lane"]) for e in merged] == [
        ("broker.kill", "broker"), ("lane.fence", "engine"),
        ("lane.rejoin", "engine")]


def test_reconstruct_tolerates_engine_lane_only_dumps():
    """A merged set with NO broker-shaped events (engine lane only) must
    reconstruct to all-missing phases, not raise — and events without mono
    stamps yield span None instead of a KeyError."""
    engine = {"recorder": "engine:c", "node": "h", "role": "engine",
              "events": [
                  {"seq": 1, "mono": 1.0, "wall": 1.0, "type": "lane.fence",
                   "partition": 0},
                  {"seq": 2, "mono": 2.0, "wall": 2.0,
                   "type": "rebalance.retarget", "granted": [1]},
                  {"seq": 3, "mono": 3.0, "wall": 3.0, "type": "slo.breach",
                   "objective": "fleet-up"},
              ]}
    recon = reconstruct_failover(merge_dumps([engine]))
    assert recon["complete"] is False
    assert all(v is None for v in recon["phases"].values())
    assert recon["span_ms"] is None
    # a promotion whose decision/ack events lack mono stamps: no span
    stampless = {"recorder": "b", "node": "h", "events": [
        {"seq": 1, "type": "role.promote-decision"},
        {"seq": 2, "type": "role.promote", "epoch": 2},
        {"seq": 3, "type": "txn.first-ack"}]}
    recon = reconstruct_failover(merge_dumps([stampless]))
    assert recon["span_ms"] is None
    assert recon["phases"]["promotion"]["epoch"] == 2


def test_recorder_dump_to_is_best_effort(tmp_path):
    rec = FlightRecorder(name="b")
    rec.record("x")
    path = str(tmp_path / "flight.json")
    rec.dump_to(path)
    assert json.load(open(path))["events"][0]["type"] == "x"
    rec.dump_to(str(tmp_path / "no-such-dir" / "f.json"))  # must not raise


# -- canned-dump merge + reconstruction (the timeline-tool smoke) ---------------------


def _canned_dumps():
    """Two brokers' dumps of one failover, same host (mono comparable): the
    wall clocks are deliberately SKEWED so a wall-ordered merge would get the
    fence/truncate order wrong — monotonic ordering must win."""
    base = 1000.0

    def ev(seq, mono_off, etype, wall_skew=0.0, **attrs):
        return {"seq": seq, "mono": base + mono_off,
                "wall": 1.7e9 + mono_off + wall_skew, "type": etype, **attrs}

    follower = {"recorder": "127.0.0.1:16002", "node": "host-a", "pid": 42,
                "events": [
                    ev(1, 0.010, "role.promote-decision",
                       dead_leader="127.0.0.1:16001", failure_streak=2),
                    ev(2, 0.012, "role.promote", epoch=2),
                    ev(3, 0.090, "txn.first-ack", epoch=2, txn_seq=7),
                ]}
    exleader = {"recorder": "127.0.0.1:16001", "node": "host-a", "pid": 43,
                "events": [
                    ev(1, 0.000, "broker.kill", role="leader", epoch=1),
                    # wall skewed 5s EARLY: a wall merge would front-run it
                    ev(2, 0.450, "role.fence", old_epoch=1, new_epoch=2,
                       wall_skew=-5.0),
                    ev(3, 0.460, "log.truncate", records=3, wall_skew=-5.0),
                ]}
    return follower, exleader


def test_merge_orders_by_monotonic_on_one_host():
    follower, exleader = _canned_dumps()
    merged = merge_dumps([follower, exleader])
    assert [e["type"] for e in merged] == [
        "broker.kill", "role.promote-decision", "role.promote",
        "txn.first-ack", "role.fence", "log.truncate"]
    assert {e["recorder"] for e in merged} == {"127.0.0.1:16001",
                                              "127.0.0.1:16002"}


def test_merge_falls_back_to_wall_across_hosts():
    follower, exleader = _canned_dumps()
    exleader["node"] = "host-b"  # different clock domain: mono incomparable
    merged = merge_dumps([follower, exleader])
    # the skewed wall stamps now order the fence/truncate first — exactly why
    # same-host merges must use monotonic time
    assert [e["type"] for e in merged][:2] == ["role.fence", "log.truncate"]


def _three_host_skewed_dumps(with_headers=True):
    """Three brokers on three HOSTS (mono bases incomparable) telling one
    failover, with wall clocks that were WRONG during the incident and
    NTP-stepped back to true before the dumps: the promoted follower ran 5s
    slow, the third voter 3s fast. A raw-wall merge front-runs the promotion
    before the leader even died; the ``dumped_mono``/``dumped_wall`` header
    pair lets :func:`merge_dumps` estimate each host's mono↔wall offset and
    recover the true order. ``with_headers=False`` strips the header pair
    (legacy dumps) to show the raw-wall fallback scrambling."""
    dump_t = 30.0  # dump time (seconds after incident start), clocks healed

    def host(recorder, node, mono_base, incident_skew, events):
        evs = [{"seq": i + 1, "mono": mono_base + t,
                "wall": 1.7e9 + t + incident_skew, "type": etype, **attrs}
               for i, (t, etype, attrs) in enumerate(events)]
        d = {"recorder": recorder, "node": node, "pid": 1, "events": evs}
        if with_headers:
            d["dumped_mono"] = mono_base + dump_t
            d["dumped_wall"] = 1.7e9 + dump_t  # stepped back to true by now
        return d

    exleader = host("127.0.0.1:16001", "host-a", 100.0, 0.0, [
        (0.00, "broker.kill", {"role": "leader", "epoch": 1}),
        (0.45, "role.fence", {"old_epoch": 1, "new_epoch": 2}),
        (0.46, "log.truncate", {"records": 3}),
    ])
    promoted = host("127.0.0.1:16002", "host-b", 2000.0, -5.0, [
        (0.10, "role.promote-decision",
         {"dead_leader": "127.0.0.1:16001", "failure_streak": 2}),
        (0.12, "role.promote", {"epoch": 2}),
        (0.50, "txn.first-ack", {"epoch": 2, "txn_seq": 7}),
    ])
    voter = host("127.0.0.1:16003", "host-c", 777.0, 3.0, [
        (0.11, "vote.grant", {"candidate": "127.0.0.1:16002", "epoch": 2}),
    ])
    return [exleader, promoted, voter]


TRUE_ORDER = ["broker.kill", "role.promote-decision", "vote.grant",
              "role.promote", "role.fence", "log.truncate", "txn.first-ack"]


def test_three_host_merge_estimates_offsets_from_dump_headers():
    from surge_tpu.observability import host_wall_offset
    dumps = _three_host_skewed_dumps()
    assert host_wall_offset(dumps[0]) == 1.7e9 + 30.0 - 130.0
    merged = merge_dumps(dumps)
    assert [e["type"] for e in merged] == TRUE_ORDER
    # and the merged 3-host story reconstructs the full failover
    recon = reconstruct_failover(merged)
    assert recon["complete"]
    assert recon["phases"]["promotion"]["epoch"] == 2
    assert recon["span_ms"] == pytest.approx(400.0)  # decision 0.10 -> ack 0.50


def test_three_host_merge_without_headers_falls_back_to_raw_wall():
    """Legacy dumps (no header pair): raw wall is all we have, and the
    incident-time skew scrambles the story — the promoted follower's whole
    timeline front-runs the kill. This is the failure mode the header
    estimate exists to fix."""
    dumps = _three_host_skewed_dumps(with_headers=False)
    assert all("dumped_mono" not in d for d in dumps)
    from surge_tpu.observability import host_wall_offset
    assert host_wall_offset(dumps[0]) is None
    merged = merge_dumps(dumps)
    types = [e["type"] for e in merged]
    assert types != TRUE_ORDER
    assert types.index("role.promote") < types.index("broker.kill")


def test_reconstruct_failover_phases_from_canned_dumps():
    merged = merge_dumps(list(_canned_dumps()))
    recon = reconstruct_failover(merged)
    assert recon["complete"]
    phases = recon["phases"]
    assert phases["promotion_decision"]["failure_streak"] == 2
    assert phases["promotion"]["epoch"] == 2
    assert phases["fence"]["new_epoch"] == 2
    assert phases["truncation"]["records"] == 3
    assert phases["first_acked_commit"]["txn_seq"] == 7
    assert recon["span_ms"] == 80.0  # decision 0.010 -> first ack 0.090


def test_reconstruct_reports_missing_phases():
    follower, _ = _canned_dumps()
    recon = reconstruct_failover(merge_dumps([follower]))
    assert not recon["complete"]
    assert recon["phases"]["fence"] is None
    assert recon["phases"]["truncation"] is None
    # manual promotion (no prober decision) still anchors the timeline
    manual = {"recorder": "b", "node": "h", "events": [
        {"seq": 1, "mono": 1.0, "wall": 1.0, "type": "role.promote",
         "epoch": 2}]}
    recon = reconstruct_failover(merge_dumps([manual]))
    assert recon["phases"]["promotion_decision"]["type"] == "role.promote"


def test_reconstruct_anchors_to_the_newest_promotion():
    """A ring holding TWO incidents must not stitch incident 1's promotion to
    incident 2's fence and call the mix 'complete': phases anchor to the
    newest promotion, so an unhealed incident 1 stays visibly unhealed."""
    def ev(seq, mono, etype, **attrs):
        return {"seq": seq, "mono": mono, "wall": mono, "type": etype,
                **attrs}

    ring = {"recorder": "b", "node": "h", "events": [
        # incident 1: promotion only — ex-leader never rejoined (no fence)
        ev(1, 1.0, "role.promote-decision", incident=1),
        ev(2, 1.1, "role.promote", epoch=2, incident=1),
        ev(3, 1.2, "txn.first-ack", epoch=2, incident=1),
        # incident 2: a later, complete failover
        ev(4, 9.0, "role.promote-decision", incident=2),
        ev(5, 9.1, "role.promote", epoch=3, incident=2),
        ev(6, 9.2, "txn.first-ack", epoch=3, incident=2),
        ev(7, 9.5, "role.fence", new_epoch=3, incident=2),
        ev(8, 9.6, "log.truncate", records=1, incident=2),
    ]}
    recon = reconstruct_failover(merge_dumps([ring]))
    assert recon["complete"]
    assert all(e["incident"] == 2 for e in recon["phases"].values())
    # drop incident 2's promotion events: incident 1 alone must NOT borrow
    # incident 2's fence/truncate
    ring["events"] = [e for e in ring["events"] if e["incident"] == 1
                      or e["type"] in ("role.fence", "log.truncate")]
    recon = reconstruct_failover(merge_dumps([ring]))
    assert recon["phases"]["promotion"]["incident"] == 1
    assert recon["phases"]["fence"]["incident"] == 2  # later events DO count
    # ...but a ring truncated before any promotion reconstructs nothing
    assert reconstruct_failover(merge_dumps([{
        "recorder": "b", "node": "h",
        "events": [ev(1, 1.0, "role.fence", new_epoch=2)]}]))["phases"][
            "fence"] is None


def test_flight_timeline_cli_on_canned_dumps(tmp_path):
    """tools/flight_timeline.py end to end on canned dump FILES (no brokers):
    human view, --json view, and the incomplete-reconstruction exit code."""
    follower, exleader = _canned_dumps()
    fpath, lpath = str(tmp_path / "f.json"), str(tmp_path / "l.json")
    json.dump(follower, open(fpath, "w"))
    json.dump(exleader, open(lpath, "w"))
    cli = os.path.join(REPO, "tools", "flight_timeline.py")

    out = subprocess.run([sys.executable, cli, fpath, lpath],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-500:]
    assert "reconstruction complete" in out.stdout
    assert "decision -> first ack: 80.0ms" in out.stdout

    out = subprocess.run([sys.executable, cli, fpath, lpath, "--json"],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    payload = json.loads(out.stdout)
    assert payload["complete"] and len(payload["events"]) == 6

    out = subprocess.run([sys.executable, cli, fpath],  # follower alone
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 1
    assert "MISSING" in out.stdout

    # cross-host: offsets must come from the wall key the merge ordered by
    # (monotonic stamps are incomparable across hosts — offsets from them
    # would contradict the printed order)
    exleader["node"] = "host-b"
    json.dump(exleader, open(lpath, "w"))
    out = subprocess.run([sys.executable, cli, fpath, lpath],
                         capture_output=True, text=True, timeout=60)
    assert "cross-host: wall-clock ordering" in out.stdout
    offsets = [float(ln.strip().split("ms")[0].lstrip("+"))
               for ln in out.stdout.splitlines()
               if ln.strip().startswith("+")][:6]  # the merged event lines
    assert offsets == sorted(offsets), out.stdout
    assert offsets[0] == 0.0


def test_flight_timeline_cli_engine_lane(tmp_path):
    """--engine interleaves an engine-lane dump: events print with the
    [engine] lane tag in causal position, and an engine-only input reports
    MISSING phases (exit 1) instead of crashing."""
    follower, exleader = _canned_dumps()
    engine = {"recorder": "engine:counter", "node": "host-a", "pid": 9,
              "role": "engine",  # dumps from the admin RPC carry this
              "events": [
                  {"seq": 1, "mono": 1000.005, "wall": 1.7e9 + 0.005,
                   "type": "lane.fence", "partition": 0},
                  {"seq": 2, "mono": 1000.100, "wall": 1.7e9 + 0.100,
                   "type": "lane.rejoin", "partition": 0},
              ]}
    fpath = str(tmp_path / "f.json")
    lpath = str(tmp_path / "l.json")
    epath = str(tmp_path / "e.json")
    json.dump(follower, open(fpath, "w"))
    json.dump(exleader, open(lpath, "w"))
    json.dump(engine, open(epath, "w"))
    cli = os.path.join(REPO, "tools", "flight_timeline.py")

    out = subprocess.run(
        [sys.executable, cli, fpath, lpath, "--engine", epath],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-500:]
    assert "lanes: broker, engine" in out.stdout
    lines = out.stdout.splitlines()
    fence_idx = next(i for i, ln in enumerate(lines)
                     if "[engine]" in ln and "lane.fence" in ln)
    # the engine lane fence (t=5ms) sits between the broker kill (t=0) and
    # the promotion decision (t=10ms) — one interleaved story
    assert "broker.kill" in lines[fence_idx - 1]
    assert "role.promote-decision" in lines[fence_idx + 1]
    assert "reconstruction complete" in out.stdout

    out = subprocess.run([sys.executable, cli, epath],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 1  # engine-only: phases missing, not a crash
    assert "MISSING" in out.stdout
    assert "[engine]" in out.stdout  # auto-detected from the envelope

    out = subprocess.run(
        [sys.executable, cli, fpath, lpath, "--engine", epath, "--json"],
        capture_output=True, text=True, timeout=60)
    payload = json.loads(out.stdout)
    assert {e["lane"] for e in payload["events"]} == {"broker", "engine"}


# -- live broker plane ----------------------------------------------------------------


FAST_CFG = Config(overrides={
    "surge.log.replication-ack-timeout-ms": 1_500,
    "surge.log.replication-isr-timeout-ms": 600,
})


def _pair(config=FAST_CFG, **leader_kw):
    lport, fport = free_ports(2)
    follower = LogServer(InMemoryLog(), port=fport,
                         follower_of=f"127.0.0.1:{lport}", config=config)
    follower.start()
    leader = LogServer(InMemoryLog(), port=lport,
                       replicate_to=[f"127.0.0.1:{fport}"], config=config,
                       **leader_kw)
    leader.start()
    return leader, follower, lport, fport


def test_broker_flight_rpc_and_failover_timeline_reconstruction():
    """A real promote→fence→truncate cycle is reconstructable purely from the
    two brokers' DumpFlight RPCs — the acceptance path, in-process scale."""
    leader, follower, lport, fport = _pair()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}", config=FAST_CFG)
        client.create_topic(TopicSpec("ev", 1))
        p = client.transactional_producer("t")
        p.begin()
        p.send(LogRecord(topic="ev", key="k", value=b"v0"))
        p.commit()

        fclient = GrpcLogTransport(f"127.0.0.1:{fport}", config=FAST_CFG)
        fclient.promote_follower(replicate_to=[f"127.0.0.1:{lport}"])
        # first post-promotion ack on the new leader
        p2 = fclient.transactional_producer("t2")
        p2.begin()
        p2.send(LogRecord(topic="ev", key="k", value=b"v1"))
        p2.commit()
        # the old leader learns of the fence from the new leader's probe/ship;
        # wait for the WHOLE demotion (truncate + catch_up run after the role
        # flips — dumping at the flip would race the log.truncate event)
        deadline = time.time() + 10
        while leader.catch_up_state.get("state") != "done" \
                and time.time() < deadline:
            time.sleep(0.05)
        assert leader.role == "follower"
        assert leader.catch_up_state.get("state") == "done"

        merged = merge_dumps([client.flight_dump(), fclient.flight_dump()])
        recon = reconstruct_failover(merged)
        assert recon["phases"]["promotion"]["epoch"] == 2
        assert recon["phases"]["fence"] is not None
        assert recon["phases"]["truncation"] is not None
        assert recon["phases"]["first_acked_commit"] is not None
        # both brokers' events interleave in one monotonic order
        monos = [e["mono"] for e in merged]
        assert monos == sorted(monos)
        assert {e["recorder"] for e in merged} == {f"127.0.0.1:{lport}",
                                                   f"127.0.0.1:{fport}"}
        # BrokerStatus satellite: the fenced ex-leader is VISIBLY a rejoiner
        status = client.broker_status()
        assert status["catch_up"]["state"] == "done"
        assert status["last_truncation"]["epoch"] == 2
        assert status["last_applied_epoch_start"]["ev"]["0"] == 1
        # ...while the never-fenced new leader shows a clean slate
        fresh = fclient.broker_status()
        assert fresh["catch_up"]["state"] == "idle"
        assert fresh["last_truncation"] is None
        client.close()
        fclient.close()
    finally:
        leader.stop()
        follower.stop()


def test_broker_metrics_scrape_rpc_and_port():
    """GetMetricsText + the optional scrape port serve a grammar-valid
    payload carrying the surge.log.replication.* lag and surge.log.journal.*
    families (the acceptance scrape), byte-identical across both surfaces."""
    import urllib.request

    from tests.test_exposition import validate_openmetrics

    leader, follower, lport, fport = _pair(metrics_port=0)
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}", config=FAST_CFG)
        client.create_topic(TopicSpec("ev", 1))
        p = client.transactional_producer("t")
        for i in range(3):
            p.begin()
            p.send(LogRecord(topic="ev", key="k", value=f"v{i}".encode()))
            p.commit()
        text = client.log_metrics_text()
        families = validate_openmetrics(text)
        assert "surge_log_replication_insync_replicas" in families
        assert "surge_log_replication_lag_records" in families
        assert "surge_log_replication_lag_batches" in families
        assert "surge_log_journal_fsync_round_timer_ms" in families
        assert "surge_log_txn_dedup_window" in families
        assert f'follower="127.0.0.1:{fport}"' in text
        # acked commits: the follower's lag gauges read 0
        assert f'surge_log_replication_lag_records{{follower="127.0.0.1:'\
               f'{fport}"}} 0' in text
        with urllib.request.urlopen(
                "http://127.0.0.1:"
                f"{leader.metrics_bound_port}/metrics") as resp:
            body = resp.read().decode()
        validate_openmetrics(body)
        assert "surge_log_broker_is_leader 1" in body
        client.close()
    finally:
        leader.stop()
        follower.stop()


def test_restarted_broker_rewires_inner_log_hooks(tmp_path):
    """A broker RESTARTED over the same FileLog (the rejoin path) must
    re-point the log's journal metrics/flight hooks at ITS quiver/ring —
    not leave them frozen on the dead server's."""
    from surge_tpu.log import FileLog

    flog = FileLog(str(tmp_path), fsync="none")
    s1 = LogServer(flog)
    assert flog.broker_metrics is s1.broker_metrics
    assert flog.flight is s1.flight
    s2 = LogServer(flog)
    assert flog.broker_metrics is s2.broker_metrics
    assert flog.flight is s2.flight
    flog.close()


def test_fault_firings_join_flight_ring_and_crash_auto_dumps(tmp_path):
    """Armed-fault firings are flight-recorded, and a fault-plane crash trip
    auto-dumps the ring to surge.log.flight.dump-dir."""
    cfg = Config(overrides={
        "surge.log.replication-ack-timeout-ms": 1_500,
        "surge.log.flight.dump-dir": str(tmp_path),
    })
    lport, = free_ports(1)
    leader = LogServer(InMemoryLog(), port=lport, config=cfg)
    leader.start()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
        client.create_topic(TopicSpec("ev", 1))
        client.arm_faults(json.dumps({"rules": [
            {"site": "crash.transact.post-apply", "action": "crash",
             "after": 1}]}), seed=1)
        p = client.transactional_producer("t")
        p.begin()
        p.send(LogRecord(topic="ev", key="k", value=b"v0"))
        p.commit()  # seen=1 <= after: no fire
        p.begin()
        p.send(LogRecord(topic="ev", key="k", value=b"v1"))
        try:
            p.commit()  # the crash point fires: broker hard-stops
        except Exception:  # noqa: BLE001 — UNAVAILABLE, as a real crash
            pass
        dump_path = str(tmp_path / f"flight-{lport}.json")
        deadline = time.time() + 5
        while not os.path.exists(dump_path) and time.time() < deadline:
            time.sleep(0.05)
        dump = json.load(open(dump_path))
        types = [e["type"] for e in dump["events"]]
        assert "fault.fire" in types and "broker.kill" in types
        fired = next(e for e in dump["events"] if e["type"] == "fault.fire")
        assert fired["site"] == "crash.transact.post-apply"
        client.close()
    finally:
        leader.stop()


def test_chaos_cli_status_includes_flight_tail_and_lag():
    """tools/chaos.py status (satellite): the one-command chaos debug view —
    fault-plane stats + flight tail + replication-lag gauges."""
    cli = os.path.join(REPO, "tools", "chaos.py")
    leader, follower, lport, fport = _pair()
    try:
        client = GrpcLogTransport(f"127.0.0.1:{lport}", config=FAST_CFG)
        client.create_topic(TopicSpec("ev", 1))
        client.arm_faults("fsync-hiccup", seed=3)
        out = subprocess.run(
            [sys.executable, cli, "status", f"127.0.0.1:{lport}"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr[-500:]
        status = json.loads(out.stdout)
        assert status["seed"] == 3  # fault stats still lead the payload
        assert isinstance(status["flight_tail"], list)
        assert any(ln.startswith("surge_log_replication_lag_records")
                   for ln in status["replication_lag"])
        # the flight subcommand dumps the full merge-ready envelope
        out = subprocess.run(
            [sys.executable, cli, "flight", f"127.0.0.1:{lport}"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0
        dump = json.loads(out.stdout)
        assert dump["recorder"] == f"127.0.0.1:{lport}"
        client.close()
    finally:
        leader.stop()
        follower.stop()
