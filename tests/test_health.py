"""Health bus, matchers, windows, supervisor — and the pipeline restart-on-signal test
(the SurgeMessagePipelineSpec:150-253 analog: inject a fatal signal, observe the
registered component restart through its Controllable)."""

import asyncio
import time

from surge_tpu.common import Ack, Controllable
from surge_tpu.config import default_config
from surge_tpu.health import (
    HealthSignal,
    HealthSignalBus,
    HealthSupervisor,
    NameEqualsMatcher,
    RegexMatcher,
    RepeatingSignalMatcher,
    SlidingSignalWindow,
)


def test_bus_ring_buffer_and_subscribers():
    bus = HealthSignalBus(buffer_size=3)
    seen = []
    bus.subscribe(seen.append)
    for i in range(5):
        bus.emit(f"s{i}", "warning", source="t")
    assert [s.name for s in bus.recent()] == ["s2", "s3", "s4"]  # bounded
    assert len(seen) == 5
    fn = bus.signal_fn("component")
    fn("component.err", "error")
    assert seen[-1].name == "component.err" and seen[-1].source == "component"


def test_matchers():
    w = SlidingSignalWindow(10.0)
    sig = HealthSignal("kafka.fatal.error", "error")
    assert NameEqualsMatcher("kafka.fatal.error").matches(sig, w)
    assert not NameEqualsMatcher("other").matches(sig, w)
    assert RegexMatcher(r"fatal").matches(sig, w)
    assert not RegexMatcher(r"^other").matches(sig, w)


def test_repeating_matcher_requires_window_count():
    w = SlidingSignalWindow(10.0)
    m = RepeatingSignalMatcher(3, NameEqualsMatcher("x"))
    for i in range(3):
        sig = HealthSignal("x")
        w.add(sig)
        matched = m.matches(sig, w)
    assert matched  # third occurrence within the window fires
    # old signals expire out of the window
    w2 = SlidingSignalWindow(0.001)
    w2.add(HealthSignal("x", timestamp=time.time() - 1))
    sig = HealthSignal("x")
    w2.add(sig)
    assert not m.matches(sig, w2)


def test_window_slider_threshold():
    w = SlidingSignalWindow(1000.0, advance_threshold=2)
    for i in range(5):
        w.add(HealthSignal(f"s{i}"))
    assert len(w) == 2  # buffer advance on threshold


class Restartable(Controllable):
    def __init__(self):
        self.starts = 0
        self.stops = 0
        self.shutdowns = 0

    async def start(self) -> Ack:
        self.starts += 1
        return Ack()

    async def stop(self) -> Ack:
        self.stops += 1
        return Ack()

    async def shutdown(self) -> Ack:
        self.shutdowns += 1
        return Ack()


def test_supervisor_restarts_on_pattern_then_escalates():
    async def scenario():
        bus = HealthSignalBus()
        sup = HealthSupervisor(bus, default_config().with_overrides(
            {"surge.health.supervisor-restart-max": 2}))
        comp = Restartable()
        sup.register("comp", comp, restart_patterns=[RegexMatcher("fatal")])
        sup.start()

        bus.emit("kafka.fatal.error", "error")
        await asyncio.sleep(0.01)
        assert comp.starts == 1 and comp.stops == 1  # restarted via Controllable
        assert any(s.name == "health.component-restarted" for s in bus.recent())

        bus.emit("kafka.fatal.error", "error")
        await asyncio.sleep(0.01)
        assert comp.starts == 2

        # budget exhausted -> escalate to shutdown
        bus.emit("kafka.fatal.error", "error")
        await asyncio.sleep(0.01)
        assert comp.starts == 2 and comp.shutdowns == 1
        sup.stop()

    asyncio.run(scenario())


def test_supervisor_shutdown_pattern():
    async def scenario():
        bus = HealthSignalBus()
        sup = HealthSupervisor(bus)
        comp = Restartable()
        sup.register("comp", comp, restart_patterns=[],
                     shutdown_patterns=[NameEqualsMatcher("die")])
        sup.start()
        bus.emit("die", "error")
        await asyncio.sleep(0.01)
        assert comp.shutdowns == 1 and comp.starts == 0
        sup.stop()

    asyncio.run(scenario())


def test_pipeline_restarts_state_store_on_fatal_signal():
    """Engine-level: a fatal state-store signal triggers a supervised restart and the
    engine keeps serving commands afterwards."""
    from surge_tpu import SurgeCommandBusinessLogic, CommandSuccess, create_engine, default_config
    from surge_tpu.models import counter

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.engine.num-partitions": 2,
    })

    async def scenario():
        engine = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), config=cfg)
        await engine.start()
        r = await engine.aggregate_for("a").send_command(counter.Increment("a"))
        assert isinstance(r, CommandSuccess)

        engine.health_bus.emit("state-store.fatal.error", "error", source="test")
        await asyncio.sleep(0.05)
        assert any(s.name == "health.component-restarted" and s.source == "state-store"
                   for s in engine.health_bus.recent())
        assert engine.indexer.running  # restarted, not dead
        assert engine.health_check().is_healthy()

        r = await engine.aggregate_for("b").send_command(counter.Increment("b"))
        assert isinstance(r, CommandSuccess)

        # metrics were recorded along the command path
        snap = engine.metrics_registry.get_metrics()
        assert snap["surge.engine.command-rate.one-minute-rate"] > 0
        assert snap["surge.aggregate.event-publish-timer"] > 0
        await engine.stop()

    asyncio.run(scenario())


def test_event_loop_prober_detects_starvation():
    """ExecutionContextProber analog (SURVEY.md §5.2): blocking the loop makes
    probes late; sustained lateness emits a health signal."""
    import asyncio
    import time

    from surge_tpu.config import default_config
    from surge_tpu.health import HealthSignalBus
    from surge_tpu.health.prober import EventLoopProber

    async def scenario():
        bus = HealthSignalBus()
        cfg = default_config().with_overrides({
            "surge.event-loop-prober.interval-ms": 10,
            "surge.event-loop-prober.threshold-ms": 20,
            "surge.event-loop-prober.late-probes": 2,
        })
        prober = EventLoopProber(cfg, on_signal=bus.signal_fn("event-loop"))
        prober.start()
        # block the loop synchronously (the starvation hazard); a loaded CI host can
        # also be "naturally" late, so the test only asserts the positive direction
        for _ in range(8):
            time.sleep(0.04)  # deliberate sync block
            await asyncio.sleep(0)  # minimal yield: every probe fires late
        await asyncio.sleep(0.05)
        await prober.stop()
        assert prober.starvation_events >= 1
        assert any(s.name == "event-loop.starvation" for s in bus.recent())
        assert prober.max_delay_s > 0.02

    asyncio.run(scenario())
