"""surgelint — the repo-native static analysis suite (surge_tpu/analysis).

Three layers:

- per-rule fixture corpus (tests/lint_fixtures/): every shipped rule catches
  its known-bad snippet at EXACT rule ids + line numbers and stays quiet on
  the known-good one;
- framework mechanics: pragma suppression (justification required, tallied),
  baseline round-trip, JSON reporter, CLI smoke;
- the tier-1 gate: the full suite over surge_tpu/, tools/ and bench.py must
  come back with ZERO unbaselined findings inside the time budget — a new
  finding fails tier-1 until it is fixed, justified inline, or explicitly
  baselined (docs/static-analysis.md).
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from surge_tpu.analysis import (
    DEFAULT_TARGETS,
    ModuleContext,
    RepoContext,
    all_rules,
    render_json,
    run_paths,
    write_baseline,
)
from surge_tpu.analysis.rules.proto import (
    check_proto_drift,
    parse_methods_table,
    parse_proto,
    repo_drift,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")
BASELINE = os.path.join(REPO, ".surgelint-baseline.json")


def _module_findings(rule_id: str, path: str):
    rule = all_rules()[rule_id]
    ctx = ModuleContext.parse(path, REPO)
    return sorted((f.rule, f.line) for f in rule.check_module(ctx))


def _repo_rule_findings(rule_id: str, path: str):
    """Run a repo-scope rule with ONLY the fixture as its module set (real
    DEFAULTS / docs / goldens as the registries), filtered to the fixture."""
    rule = all_rules()[rule_id]
    ctx = ModuleContext.parse(path, REPO)
    repo_ctx = RepoContext(REPO, [ctx])
    return sorted((f.rule, f.line) for f in rule.check_repo(repo_ctx)
                  if f.path == ctx.rel_path)


# -- per-rule fixture corpus ---------------------------------------------------------

MODULE_RULE_CASES = [
    ("await-under-lock", "await_under_lock", [12, 14]),
    ("blocking-in-async", "blocking_in_async", [10, 11, 12, 14, 17]),
    ("waitfor-cancellation-swallow", "waitfor_cancellation_swallow", [8, 12]),
    ("orphan-task", "orphan_task", [7, 10]),
    ("span-leak", "span_leak", [9, 13, 18]),
    ("jit-purity", "jit_purity", [12, 13, 14, 15]),
    ("hot-path-asyncio", "hot_path_asyncio", [9, 14, 18]),
]


@pytest.mark.parametrize("rule_id,fixture,bad_lines", MODULE_RULE_CASES,
                         ids=[c[0] for c in MODULE_RULE_CASES])
def test_module_rule_fixture_corpus(rule_id, fixture, bad_lines):
    bad = _module_findings(rule_id, os.path.join(FIXTURES, fixture, "bad.py"))
    assert bad == [(rule_id, ln) for ln in bad_lines], bad
    good = _module_findings(rule_id, os.path.join(FIXTURES, fixture, "good.py"))
    assert good == [], good


@pytest.mark.parametrize("rule_id,fixture,bad_lines", [
    ("config-key-registry", "config_key_registry", [7]),
    ("metric-catalog", "metric_catalog", [6]),
], ids=["config-key-registry", "metric-catalog"])
def test_repo_rule_fixture_corpus(rule_id, fixture, bad_lines):
    bad = _repo_rule_findings(rule_id,
                              os.path.join(FIXTURES, fixture, "bad.py"))
    assert bad == [(rule_id, ln) for ln in bad_lines], bad
    good = _repo_rule_findings(rule_id,
                               os.path.join(FIXTURES, fixture, "good.py"))
    assert good == [], good


def test_metric_catalog_golden_coupling(tmp_path):
    """An instrument created in a golden-coupled module (the engine/broker
    quivers) must ALSO be in a golden .om file — docs row alone is not
    enough, because golden and catalog regen together."""
    mod_dir = tmp_path / "surge_tpu" / "metrics"
    mod_dir.mkdir(parents=True)
    mod = mod_dir / "broker.py"
    mod.write_text(
        "from surge_tpu.metrics import MetricInfo, Metrics\n"
        "def build(m):\n"
        "    return m.timer(MetricInfo('surge.lint-fixture.golden-gap', 'x'))\n")
    (tmp_path / "docs").mkdir()
    # documented, so only the golden half fires
    (tmp_path / "docs" / "observability.md").write_text(
        "| `surge.lint-fixture.golden-gap` | timer | documented |\n")
    (tmp_path / "tests" / "golden").mkdir(parents=True)
    (tmp_path / "tests" / "golden" / "metrics.om").write_text(
        "# TYPE surge_other_metric gauge\n")
    (tmp_path / "tests" / "golden" / "metrics_broker.om").write_text("")
    rule = all_rules()["metric-catalog"]
    ctx = ModuleContext.parse(str(mod), str(tmp_path))
    found = list(rule.check_repo(RepoContext(str(tmp_path), [ctx])))
    assert len(found) == 1 and "golden" in found[0].message, found


def test_slo_definitions_must_reference_cataloged_instruments(tmp_path):
    """An SLO citing a family no golden exposition renders is a DEAD
    objective (it watches a metric nothing emits, so it can never page) —
    the metric-catalog rule rejects it; a golden-backed family passes."""
    mod_dir = tmp_path / "surge_tpu" / "observability"
    mod_dir.mkdir(parents=True)
    mod = mod_dir / "slo.py"
    mod.write_text(
        "from dataclasses import dataclass\n"
        "@dataclass(frozen=True)\n"
        "class SLO:\n"
        "    name: str; family: str; kind: str; objective: float\n"
        "    good_family: str = ''\n"
        "LIVE = SLO('ok', family='surge_real_family', kind='bound',\n"
        "           objective=0.99)\n"
        "DEAD = SLO('dead', family='surge_ghost_family', kind='bound',\n"
        "           objective=0.99)\n"
        "DEAD_TOTAL = SLO('dead2', family='surge_real_family',\n"
        "                 kind='availability', objective=0.99,\n"
        "                 good_family='surge_ghost_total')\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("")
    (tmp_path / "tests" / "golden").mkdir(parents=True)
    (tmp_path / "tests" / "golden" / "metrics.om").write_text(
        "# TYPE surge_real_family gauge\n")
    (tmp_path / "tests" / "golden" / "metrics_broker.om").write_text("")
    (tmp_path / "tests" / "golden" / "metrics_fleet.om").write_text("")
    rule = all_rules()["metric-catalog"]
    ctx = ModuleContext.parse(str(mod), str(tmp_path))
    found = [f for f in rule.check_repo(RepoContext(str(tmp_path), [ctx]))
             if "SLO references" in f.message]
    assert sorted(f.message.split("`")[1] for f in found) == [
        "surge_ghost_family", "surge_ghost_total"], found


def test_shipped_default_slos_are_all_golden_backed():
    """The runtime half of the no-dead-objectives gate: every family the
    shipped DEFAULT_SLOS cite is rendered by a checked-in golden."""
    import re as _re

    from surge_tpu.observability import DEFAULT_SLOS

    golden_families = set()
    for name in ("metrics.om", "metrics_broker.om", "metrics_fleet.om"):
        with open(os.path.join(REPO, "tests", "golden", name)) as f:
            golden_families |= set(
                _re.findall(r"^# TYPE (\S+) ", f.read(), _re.M))
    for slo in DEFAULT_SLOS:
        for fam in filter(None, (slo.family, slo.good_family)):
            assert any(g == fam or g.startswith(fam + "_")
                       for g in golden_families), (
                f"SLO {slo.name!r} references {fam!r}, which no golden "
                "exposition renders — a dead objective")


# -- proto-drift ---------------------------------------------------------------------

_FIXTURE_METHODS = {"Ping": ("PingRequest", "PingReply"),
                    "Status": ("PingRequest", "PingReply")}
_FIXTURE_PB2_SERVICES = {"Ping": ("PingRequest", "PingReply")}
_FIXTURE_PB2_MESSAGES = {"PingRequest": {"name": 1},
                         "PingReply": {"ok": 1, "error": 2}}


def test_proto_drift_good_fixture_is_clean():
    text = open(os.path.join(FIXTURES, "proto_drift", "good.proto")).read()
    assert check_proto_drift(text, _FIXTURE_METHODS, _FIXTURE_PB2_SERVICES,
                             _FIXTURE_PB2_MESSAGES) == []


def test_proto_drift_bad_fixture_catches_every_class():
    text = open(os.path.join(FIXTURES, "proto_drift", "bad.proto")).read()
    drift = "\n".join(check_proto_drift(
        text, _FIXTURE_METHODS, _FIXTURE_PB2_SERVICES, _FIXTURE_PB2_MESSAGES))
    # rpc signature drift between proto and METHODS
    assert "rpc `Ping` signature drift" in drift
    # proto rpc with no route / METHODS route not in proto
    assert "`Orphan`" in drift
    assert "METHODS route `Status` is not in" in drift
    # pb2-descriptor field the hand-synced .proto lost
    assert "field `PingReply.error` is in the pb2 descriptor" in drift


def test_proto_drift_real_repo_in_sync():
    """The shipped artifacts are in sync (what `regen_log_proto.py --check`
    runs; the proto-drift rule rides the same function in the full suite)."""
    assert repo_drift(REPO) == []


def test_parse_helpers_read_the_real_artifacts():
    declared, reuse, messages = parse_proto(
        open(os.path.join(REPO, "proto", "log_service.proto")).read())
    assert "Transact" in declared and "HandoffPartition" in reuse
    assert messages["ReplicateRequest"]["high_watermarks"] == 8
    methods = parse_methods_table(
        open(os.path.join(REPO, "surge_tpu", "log", "server.py")).read())
    assert methods["Transact"] == ("TxnRequest", "TxnReply")
    assert set(declared) | set(reuse) == set(methods)


# -- pragmas, baseline, reporters ----------------------------------------------------

def test_pragma_requires_justification():
    report = run_paths([os.path.join(FIXTURES, "pragma", "bad.py")], REPO,
                       select=["orphan-task"])
    assert [(f.rule, f.line) for f in report.findings] == \
        [("pragma-justification", 7)]
    assert report.suppressed == []


def test_justified_pragma_suppresses_and_tallies():
    report = run_paths([os.path.join(FIXTURES, "pragma", "good.py")], REPO,
                       select=["orphan-task"])
    assert report.findings == [] and report.exit_code == 0
    assert report.suppression_tally() == {"orphan-task": 1}
    assert "fire-and-forget" in report.suppressed[0].justification


def test_baseline_roundtrip(tmp_path):
    bad = os.path.join(FIXTURES, "orphan_task", "bad.py")
    first = run_paths([bad], REPO, select=["orphan-task"])
    assert len(first.findings) == 2
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), first.findings)
    second = run_paths([bad], REPO, select=["orphan-task"],
                       baseline_path=str(baseline))
    assert second.findings == [] and second.exit_code == 0
    assert len(second.baselined) == 2
    # a NEW finding (beyond the baselined multiset) still fails
    data = json.loads(baseline.read_text())
    data["findings"] = data["findings"][:1]
    baseline.write_text(json.dumps(data))
    third = run_paths([bad], REPO, select=["orphan-task"],
                      baseline_path=str(baseline))
    assert len(third.findings) == 1 and third.exit_code == 1


def test_json_reporter_schema():
    report = run_paths([os.path.join(FIXTURES, "orphan_task", "bad.py")],
                       REPO, select=["orphan-task"])
    payload = json.loads(render_json(report))
    assert payload["exit_code"] == 1
    assert payload["tally"] == {"orphan-task": 2}
    f = payload["findings"][0]
    assert set(f) >= {"rule", "path", "line", "message"}
    assert f["path"].startswith("tests/lint_fixtures/")


def test_unknown_rule_id_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        run_paths(["bench.py"], REPO, select=["no-such-rule"])


def test_nonexistent_target_is_an_error_not_a_clean_run():
    """A typo'd path in a CI hook must not lint nothing and stay green."""
    with pytest.raises(FileNotFoundError, match="no/such/path"):
        run_paths(["no/such/path"], REPO, select=["orphan-task"])


def test_cli_json_smoke():
    """One subprocess smoke: --format=json over a fixture, selected rule."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "surgelint.py"),
         os.path.join(FIXTURES, "orphan_task", "bad.py"),
         "--select", "orphan-task", "--format=json", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1, out.stderr
    payload = json.loads(out.stdout)
    assert payload["tally"] == {"orphan-task": 2}


# -- the recommended replacement actually works --------------------------------------

def test_cancel_safe_wait_for_does_not_swallow_cancellation():
    """The helper the waitfor-cancellation-swallow rule prescribes: a loop
    built on it dies on the FIRST cancel even when the inner awaitable
    completes in the same tick (the py3.10 wait_for swallow interleaving)."""
    from surge_tpu.common import cancel_safe_wait_for

    async def scenario():
        ev = asyncio.Event()
        spins = 0

        async def loop():
            nonlocal spins
            while True:
                try:
                    await cancel_safe_wait_for(ev.wait(), timeout=5.0)
                except asyncio.TimeoutError:
                    continue
                spins += 1

        task = asyncio.ensure_future(loop())
        await asyncio.sleep(0.02)
        task.cancel()          # cancel and completion race on one tick
        ev.set()
        for _ in range(50):
            if task.done():
                break
            await asyncio.sleep(0.01)
        assert task.cancelled(), "loop survived task.cancel()"
        assert spins <= 1

    asyncio.run(scenario())


def test_cancel_safe_wait_for_timeout_and_result():
    from surge_tpu.common import cancel_safe_wait_for

    async def scenario():
        async def quick():
            return 42
        assert await cancel_safe_wait_for(quick(), timeout=1.0) == 42
        with pytest.raises(asyncio.TimeoutError):
            await cancel_safe_wait_for(asyncio.Event().wait(), timeout=0.01)

    asyncio.run(scenario())


def test_cancel_safe_wait_for_completion_beats_the_timeout_cancel():
    """An awaitable that completes (or fails for real) inside the timeout's
    cancel window surfaces its actual result/exception — not a masking
    TimeoutError plus an unretrieved-task warning."""
    from surge_tpu.common import cancel_safe_wait_for

    async def scenario():
        async def refuses_cancel_then_fails():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                raise RuntimeError("producer fenced") from None

        with pytest.raises(RuntimeError, match="producer fenced"):
            await cancel_safe_wait_for(refuses_cancel_then_fails(),
                                       timeout=0.01)

        async def refuses_cancel_then_succeeds():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                return "committed"

        assert await cancel_safe_wait_for(refuses_cancel_then_succeeds(),
                                          timeout=0.01) == "committed"

    asyncio.run(scenario())


def test_cancel_safe_wait_for_inner_does_not_outlive_cancelled_caller():
    """bpo-32751 parity with wait_for: when the CALLER is cancelled, the
    inner awaitable's cleanup finishes before the CancelledError propagates
    out of the helper."""
    from surge_tpu.common import cancel_safe_wait_for

    async def scenario():
        cleaned_up = asyncio.Event()

        async def inner():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                await asyncio.sleep(0.02)  # slow cleanup must still finish
                cleaned_up.set()
                raise

        async def caller():
            await cancel_safe_wait_for(inner(), timeout=30)

        t = asyncio.ensure_future(caller())
        await asyncio.sleep(0.02)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        assert cleaned_up.is_set(), "inner cleanup outlived the caller"

    asyncio.run(scenario())


# -- the tier-1 gate -----------------------------------------------------------------

def test_full_suite_zero_unbaselined_findings_in_budget():
    """`python tools/surgelint.py` over the canonical surface: zero
    unbaselined, unsuppressed findings, no parse errors, inside the time
    budget (nominally <10s; the assert allows this container's documented
    2-3x load swing)."""
    t0 = time.perf_counter()
    report = run_paths(list(DEFAULT_TARGETS), REPO, baseline_path=BASELINE)
    elapsed = time.perf_counter() - t0
    assert report.errors == [], report.errors
    assert report.findings == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in report.findings)
    assert report.exit_code == 0
    assert report.files_scanned > 80  # the whole canonical surface, not a subset
    assert len(report.rules_run) >= 8
    assert elapsed < 25.0, f"surgelint took {elapsed:.1f}s (budget 10s nominal)"
