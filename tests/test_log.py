"""Log transport semantics: atomic transactions, fencing, compaction, waits.

Covers the broker behaviors the engine depends on (reference seam:
KafkaProducer.scala:106-117 transactions, KafkaProducerActorImpl.scala:502-528 fencing,
SurgeStateStoreConsumer.scala:38 read_committed)."""

import asyncio

import pytest

from surge_tpu.log import (
    InMemoryLog,
    LogRecord,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


def test_transaction_atomic_multi_topic_commit():
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 2))
    log.create_topic(TopicSpec("state", 2, compacted=True))
    p = log.transactional_producer("txn-state-0")

    p.begin()
    p.send(rec("events", "a", b"e1"))
    p.send(rec("events", "a", b"e2"))
    p.send(rec("state", "a", b"s2"))
    # nothing visible before commit
    assert log.end_offset("events", 0) == 0
    assert log.end_offset("state", 0) == 0

    out = p.commit()
    assert [r.offset for r in out] == [0, 1, 0]
    assert [r.value for r in log.read("events", 0)] == [b"e1", b"e2"]
    assert log.latest_by_key("state", 0)["a"].value == b"s2"


def test_abort_discards_and_allows_new_transaction():
    log = InMemoryLog()
    p = log.transactional_producer("t")
    p.begin()
    p.send(rec("events", "a", b"dead"))
    p.abort()
    assert log.end_offset("events", 0) == 0
    p.begin()
    p.send(rec("events", "a", b"live"))
    p.commit()
    assert [r.value for r in log.read("events", 0)] == [b"live"]


def test_zombie_producer_fenced_no_duplicate_or_lost_writes():
    log = InMemoryLog()
    old = log.transactional_producer("txn-0")
    old.begin()
    old.send(rec("events", "a", b"zombie-write"))

    new = log.transactional_producer("txn-0")  # bumps epoch: fences `old`
    assert old.fenced and not new.fenced
    with pytest.raises(ProducerFencedError):
        old.commit()
    assert log.end_offset("events", 0) == 0  # zombie write lost, not half-applied

    new.begin()
    new.send(rec("events", "a", b"good"))
    new.commit()
    assert [r.value for r in log.read("events", 0)] == [b"good"]
    with pytest.raises(ProducerFencedError):
        old.send_immediate(rec("events", "a", b"late"))


def test_transaction_state_errors():
    log = InMemoryLog()
    p = log.transactional_producer("t")
    with pytest.raises(TransactionStateError):
        p.send(rec("e", "k", b"v"))
    with pytest.raises(TransactionStateError):
        p.commit()
    p.begin()
    with pytest.raises(TransactionStateError):
        p.begin()
    with pytest.raises(TransactionStateError):
        p.send_immediate(rec("e", "k", b"v"))


def test_compacted_view_tombstones_and_latest_wins():
    log = InMemoryLog()
    p = log.transactional_producer("t")
    for value in (b"v1", b"v2"):
        p.begin()
        p.send(rec("state", "a", value))
        p.commit()
    p.begin()
    p.send(rec("state", "b", b"bv"))
    p.send(rec("state", "a", None))  # tombstone
    p.commit()
    view = log.latest_by_key("state", 0)
    assert set(view) == {"b"}
    assert view["b"].value == b"bv"


def test_wait_for_append_wakes_consumer():
    async def scenario():
        log = InMemoryLog()
        p = log.transactional_producer("t")

        async def produce_later():
            await asyncio.sleep(0.01)
            p.begin()
            p.send(rec("events", "k", b"v"))
            p.commit()

        task = asyncio.ensure_future(produce_later())
        await asyncio.wait_for(log.wait_for_append("events", 0, after_offset=0), 2.0)
        assert log.end_offset("events", 0) == 1
        await task

    asyncio.run(scenario())


def test_partitioned_offsets_independent():
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 3))
    p = log.transactional_producer("t")
    p.begin()
    p.send(rec("events", "a", b"p0", partition=0))
    p.send(rec("events", "b", b"p2", partition=2))
    p.send(rec("events", "c", b"p2b", partition=2))
    p.commit()
    assert log.end_offset("events", 0) == 1
    assert log.end_offset("events", 1) == 0
    assert log.end_offset("events", 2) == 2
