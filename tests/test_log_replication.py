"""Broker replication: ship-on-commit follower + client failover on leader death.

The acks=all role of the reference's replicated Kafka cluster (VERDICT r3 next
#5; common reference.conf:112-124): a commit is acknowledged only once the
follower has it, the follower's log is always a gap-free prefix of the leader's,
and killing the leader mid-traffic loses no committed record — the engine keeps
serving against the follower, with replicated txn-dedup preventing duplicate
appends from acked-but-reply-lost commits."""

import asyncio

import pytest

from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)


@pytest.fixture
def pair():
    """A leader LogServer replicating to a follower LogServer."""
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    clients = []

    def connect(failover=True) -> GrpcLogTransport:
        targets = (f"127.0.0.1:{lport},127.0.0.1:{fport}" if failover
                   else f"127.0.0.1:{lport}")
        c = GrpcLogTransport(targets)
        clients.append(c)
        return c

    yield leader, follower, fport, connect
    for c in clients:
        c.close()
    leader.stop()
    follower.stop()


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


def test_commits_ship_to_follower_with_identical_offsets(pair):
    leader, follower, fport, connect = pair
    log = connect()
    log.create_topic(TopicSpec("events", 2))
    log.create_topic(TopicSpec("state", 2, compacted=True))
    p = log.transactional_producer("txn-0")
    p.begin()
    p.send(rec("events", "a", b"e1"))
    p.send(rec("events", "a", b"e2", partition=1))
    p.send(rec("state", "a", b"s1"))
    out = p.commit()
    assert [r.offset for r in out] == [0, 0, 0]
    # read directly from the follower: same records, same offsets, same specs
    flog = GrpcLogTransport(f"127.0.0.1:{fport}")
    try:
        assert flog.topic("events").partitions == 2
        assert flog.topic("state").compacted
        assert [r.value for r in flog.read("events", 0)] == [b"e1"]
        assert [r.value for r in flog.read("events", 1)] == [b"e2"]
        assert flog.latest_by_key("state", 0)["a"].value == b"s1"
    finally:
        flog.close()


def test_acked_commits_survive_leader_kill(pair):
    """Every acknowledged commit must be readable after the leader dies —
    acks=all means replication happens BEFORE the ack."""
    leader, follower, fport, connect = pair
    log = connect()
    log.create_topic(TopicSpec("events", 1))
    p = log.transactional_producer("txn-0")
    acked = []
    for i in range(20):
        p.begin()
        p.send(rec("events", f"k{i}", f"v{i}".encode()))
        out = p.commit()
        acked.append((out[0].offset, f"v{i}".encode()))
    leader.stop(grace=0.1)  # the kill: socket closes, client sees UNAVAILABLE
    # reads fail over to the follower and see every acked record
    values = {r.offset: r.value for r in log.read("events", 0)}
    for off, val in acked:
        assert values[off] == val


def test_producer_fails_over_and_resumes_idempotency_numbering(pair):
    """After leader death the producer re-opens on the follower (fenced →
    reopen ladder) and its txn_seq continues from the replicated dedup state,
    so a retry of the last acked commit cannot append twice."""
    from surge_tpu.log.transport import ProducerFencedError

    leader, follower, fport, connect = pair
    log = connect()
    log.create_topic(TopicSpec("events", 1))
    p = log.transactional_producer("txn-0")
    for i in range(3):
        p.begin()
        p.send(rec("events", "a", f"v{i}".encode()))
        p.commit()
    assert p._next_seq == 4
    leader.stop(grace=0.1)

    # next commit observes the failover as fencing
    p.begin()
    p.send(rec("events", "a", b"v3"))
    with pytest.raises(ProducerFencedError):
        p.commit()
    assert p.fenced

    # re-open (what the publisher's reinit does): numbering resumes at 4
    p2 = log.transactional_producer("txn-0")
    assert p2._next_seq == 4
    # the acked-but-reply-lost case: the LAST commit acked by the dead leader
    # (seq 3) is retried against the follower — the replicated dedup answers
    # from cache instead of appending v2 a second time
    replay = log._transact(p2._token, "commit", [rec("events", "a", b"v2")],
                           seq=3)
    assert replay.ok and [m.offset for m in replay.records] == [2]
    assert log.end_offset("events", 0) == 3  # nothing appended twice
    p2.begin()
    p2.send(rec("events", "a", b"v3"))
    out = p2.commit()
    assert out[0].offset == 3
    assert [r.value for r in log.read("events", 0)] == [b"v0", b"v1", b"v2", b"v3"]


def test_engine_survives_broker_failover_mid_traffic(pair):
    """The full engine keeps serving commands across a leader kill: publisher
    re-initializes on the follower via the fenced ladder, committed state is
    recovered, and no command's effect is lost or doubled."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.models import counter

    leader, follower, fport, connect = pair
    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.aggregate.publish-timeout-ms": 4000,
        "surge.engine.num-partitions": 2,
    })

    def logic():
        return SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting())

    async def scenario():
        log = connect()
        engine = create_engine(logic(), log=log, config=cfg)
        await engine.start()
        for i in range(10):
            agg = f"agg-{i % 3}"
            r = await engine.aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess)

        leader.stop(grace=0.1)  # kill mid-traffic

        async def send_retrying(agg):
            for _ in range(30):  # publisher reinit window: commands retry
                r = await engine.aggregate_for(agg).send_command(
                    counter.Increment(agg))
                if isinstance(r, CommandSuccess):
                    return r
                await asyncio.sleep(0.2)
            raise AssertionError(f"command never succeeded after failover: {r}")

        r = await send_retrying("agg-0")
        assert r.state.count == 5  # 4 pre-kill + 1 post-failover: nothing lost
        r = await send_retrying("agg-1")
        assert r.state.count == 4
        await engine.stop()

        # a FRESH engine against only the follower sees all committed state
        engine2 = create_engine(logic(), log=connect(), config=cfg)
        await engine2.start()
        st = await engine2.aggregate_for("agg-0").get_state()
        assert st.count == 5
        await engine2.stop()

    asyncio.run(scenario())


# -- availability under follower failure (VERDICT r4 missing #5) ---------------------

def _degrade_cfg(**extra):
    from surge_tpu.config import default_config

    return default_config().with_overrides({
        "surge.log.replication-ack-timeout-ms": 400,
        "surge.log.replication-isr-timeout-ms": 800,
        **extra})


def _commit_retrying(p, r, attempts=40):
    """The publisher's behavior: retry the same txn_seq on retriable errors."""
    import time as _t

    last = None
    for _ in range(attempts):
        try:
            p.begin()
            p.send(r)
            return p.commit()
        except Exception as exc:  # noqa: BLE001 — retriable commit error
            last = exc
            _t.sleep(0.1)
    raise AssertionError(f"commit never succeeded: {last!r}")


def test_follower_death_degrades_to_min_insync_and_drains():
    """With min-insync=1 (default), a dead follower blocks commits only for
    the isr-timeout window: the leader then drops it from the in-sync set,
    the replication queue drains, and commits ack leader-only — no livelock,
    no unbounded queue (VERDICT r4 weak #7)."""
    import time as _t

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=_degrade_cfg(),
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=_degrade_cfg())
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        p.begin()
        p.send(rec("events", "k", b"v0"))
        p.commit()
        assert leader.replication_status()["replicas"] == {f"127.0.0.1:{fport}": True}

        follower.stop(grace=0.1)  # follower dies
        # commits keep the same txn_seq through retriable errors and succeed
        # once the isr window (0.8s) expires
        t0 = _t.perf_counter()
        out = _commit_retrying(p, rec("events", "k", b"v1"))
        assert out[0].offset == 1
        assert _t.perf_counter() - t0 < 15
        assert leader.replication_status()["replicas"] == {f"127.0.0.1:{fport}": False}

        # degraded steady state: commits are instant (no follower wait) and
        # the queue never grows — each item finalizes on dispatch
        for i in range(10):
            p.begin()
            p.send(rec("events", "k", f"w{i}".encode()))
            p.commit()
        assert len(leader._repl_queue) == 0
        assert client.end_offset("events", 0) == 12
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_follower_rejoins_via_catch_up_mid_traffic():
    """A replacement follower (empty log, same address) must NOT re-join on
    its first reachable ship — only after catch_up makes it a complete prefix;
    once re-joined, a leader kill proves the follower holds EVERY acked
    record, including those committed while it was dead."""
    import time as _t

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    # auto-resync capped to 4 records: this test exercises the OPERATOR bulk
    # path — the outage lag (7+) exceeds the cap so only catch_up can bridge
    # it, while the live tail that accumulates between catch_up and the next
    # probe (≤ ~3 ticks at the cadence below) still fits under it
    cfg = _degrade_cfg(**{"surge.log.replication-auto-resync-max-records": 4})
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport},127.0.0.1:{fport}",
                              config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        for i in range(3):
            p.begin()
            p.send(rec("events", f"k{i}", f"v{i}".encode()))
            p.commit()

        follower.stop(grace=0.1)
        _commit_retrying(p, rec("events", "kd", b"dead-window"))  # degrade
        assert leader.replication_status()["replicas"][f"127.0.0.1:{fport}"] is False

        # replacement broker on the SAME port with an EMPTY log: reachable,
        # but behind — the leader's probes must keep it out of the set
        follower = LogServer(InMemoryLog(), port=fport)
        follower.start()
        for i in range(3):
            p.begin()
            p.send(rec("events", f"r{i}", f"live{i}".encode()))
            p.commit()
        _t.sleep(1.2)  # beyond the probe interval: reachable != caught up
        assert leader.replication_status()["replicas"][f"127.0.0.1:{fport}"] is False

        copied = follower.catch_up(f"127.0.0.1:{lport}")
        # 7 data records (3 + dead-window + 3 committed while out); broker-
        # internal topics (__txn_state, __broker_meta) are self-maintained
        # per side and never copied — the dedup table travels via the
        # DedupSnapshot merge below instead
        assert copied == 7
        assert sum(1 for _ in follower.log.read("events", 0)) == 7
        # catch_up must also carry the txn-dedup table: a failover client
        # retrying an in-flight seq would otherwise re-append records this
        # copy already holds (exactly-once across the outage window)
        assert (follower._txn_dedup["txn-0"].last_seq
                == leader._txn_dedup["txn-0"].last_seq > 0)
        # traffic continues; the next probe verifies end offsets and re-joins
        deadline = _t.perf_counter() + 10
        while (_t.perf_counter() < deadline
               and not leader.replication_status()["replicas"][f"127.0.0.1:{fport}"]):
            p.begin()
            p.send(rec("events", "probe", b"tick"))
            p.commit()
            _t.sleep(0.3)
        assert leader.replication_status()["replicas"][f"127.0.0.1:{fport}"] is True

        # post-rejoin commits are replicated again: kill the leader and read
        # EVERYTHING back from the follower
        p.begin()
        p.send(rec("events", "final", b"after-rejoin"))
        p.commit()
        expect = client.end_offset("events", 0)
        leader.stop(grace=0.1)
        values = [r.value for r in client.read("events", 0)]
        assert len(values) == expect
        assert values[3] == b"dead-window" and values[-1] == b"after-rejoin"
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_min_insync_two_keeps_strict_acks_all():
    """min-insync=2 with one follower = strict acks=all: a dead follower
    blocks every commit with retriable errors indefinitely (durability over
    availability), exactly the pre-degrade behavior."""
    cfg = _degrade_cfg(**{"surge.log.replication-min-insync": 2})
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        p.begin()
        p.send(rec("events", "k", b"v0"))
        p.commit()
        follower.stop(grace=0.1)
        with pytest.raises(Exception):
            p.begin()
            p.send(rec("events", "k", b"v1"))
            p.commit()  # retriable error surfaces: nothing degrades
        assert leader.replication_status()["replicas"] == {f"127.0.0.1:{fport}": True}
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_replication_status_rpc_exposes_in_sync_set():
    """Operators read the in-sync set off the broker itself (the Kafka
    under-replicated-partitions view): healthy -> in_sync, post-degrade ->
    out, queue drained."""
    cfg = _degrade_cfg()
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        p.begin(); p.send(rec("events", "k", b"v")); p.commit()
        st = client.replication_status()
        assert st["replicas"] == {f"127.0.0.1:{fport}": True}
        assert st["insync_count"] == 2 and st["min_insync"] == 1
        follower.stop(grace=0.1)
        _commit_retrying(p, rec("events", "k", b"v2"))
        st = client.replication_status()
        assert st["replicas"] == {f"127.0.0.1:{fport}": False}
        assert st["insync_count"] == 1 and st["queue_depth"] == 0
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_replication_worker_survives_internal_bugs():
    """An uncaught exception inside the replication worker must not kill the
    thread (every later commit would time out retriable forever): the loop
    logs, backs off, and keeps draining."""
    import unittest.mock as mock

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=_degrade_cfg(),
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=_degrade_cfg())
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        # a BUG (raises), not a transport failure (returns error string)
        with mock.patch.object(LogServer, "_ship", autospec=True,
                               side_effect=RuntimeError("worker bug")):
            p.begin()
            p.send(rec("events", "k", b"v0"))
            with pytest.raises(Exception):
                p.commit()  # retriable timeout while the bug persists
        # bug gone: the SAME worker thread finishes the job. Publisher
        # protocol: the FAILED commit's payload retries under its own seq
        # (the dedup answers once the worker drains it)...
        out = _commit_retrying(p, rec("events", "k", b"v0"))
        assert out[0].offset == 0
        # ...and only then does new traffic flow
        p.begin()
        p.send(rec("events", "k", b"v1"))
        out = p.commit()
        assert out[0].offset == 1
        assert leader._repl_thread.is_alive()
        # once the queue drains, the follower is an identical prefix again
        import time as _t

        deadline = _t.perf_counter() + 10
        while _t.perf_counter() < deadline and leader._repl_queue:
            _t.sleep(0.05)
        assert not leader._repl_queue
        flog = GrpcLogTransport(f"127.0.0.1:{fport}")
        try:
            leader_vals = [r.value for r in client.read("events", 0)]
            follower_vals = [r.value for r in flog.read("events", 0)]
            assert follower_vals == leader_vals
            assert b"v1" in follower_vals
        finally:
            flog.close()
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_isr_fuzz_random_follower_churn_never_loses_acked_records():
    """Randomized availability fuzz: while a producer commits continuously,
    the follower is repeatedly killed, replaced empty, caught up, and
    re-joined. Invariants after every cycle and at the end:

    - every ACKED commit's record is present exactly once on the leader;
    - after the final catch_up + rejoin, the follower is byte-identical;
    - the in-sync flag reflects reality (no rejoin while behind).
    """
    import random
    import time as _t

    rng = random.Random(1234)
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    cfg = _degrade_cfg()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    acked: list = []
    try:
        client.create_topic(TopicSpec("events", 2))
        p = client.transactional_producer("fuzz")
        seq = 0

        def commit_one():
            nonlocal seq
            seq += 1
            val = f"r{seq}".encode()
            out = _commit_retrying(p, rec("events", f"k{seq % 7}", val,
                                          partition=seq % 2))
            acked.append((out[0].partition, out[0].offset, val))

        for cycle in range(4):
            for _ in range(rng.randint(2, 5)):
                commit_one()
            follower.stop(grace=0.05)  # kill
            for _ in range(rng.randint(2, 4)):
                commit_one()  # degrade window: acks go leader-only
            # replacement broker, empty log, same address
            follower = LogServer(InMemoryLog(), port=fport)
            follower.start()
            mode = rng.choice(["catch_up", "auto", "idle"])
            if mode == "catch_up":
                follower.catch_up(f"127.0.0.1:{lport}")
            # "auto": the leader's probe resyncs it under live traffic;
            # "idle": no catch_up AND no traffic — idle probing alone heals
            deadline = _t.perf_counter() + 15
            while (_t.perf_counter() < deadline
                   and not leader.replication_status()["replicas"][
                       f"127.0.0.1:{fport}"]):
                if mode != "idle":
                    commit_one()
                _t.sleep(0.1)
            assert leader.replication_status()["replicas"][
                f"127.0.0.1:{fport}"] is True, f"cycle {cycle} ({mode})"

        # leader holds every acked record exactly once, at its acked offset
        for part in (0, 1):
            vals = {r.offset: r.value for r in client.read("events", part)}
            mine = [(o, v) for (pp, o, v) in acked if pp == part]
            assert len(mine) == len(vals)
            for off, val in mine:
                assert vals[off] == val
        # the follower is an identical prefix == full copy once drained
        deadline = _t.perf_counter() + 10
        while _t.perf_counter() < deadline and leader._repl_queue:
            _t.sleep(0.05)
        flog = GrpcLogTransport(f"127.0.0.1:{fport}")
        try:
            for part in (0, 1):
                lv = [(r.offset, r.value) for r in client.read("events", part)]
                fv = [(r.offset, r.value) for r in flog.read("events", part)]
                assert fv == lv, f"partition {part}"
        finally:
            flog.close()
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_auto_resync_rejoins_small_lag_without_operator_catch_up():
    """Within the auto-resync cap the LEADER heals a lagging follower by
    itself — missing suffix pushed through the ordered Replicate stream plus
    the dedup table — because a one-shot catch_up can never converge while
    commits keep landing. No operator action in this test at all."""
    import time as _t

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    cfg = _degrade_cfg()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        # TWO partitions: an offset probe of the empty replacement must not
        # auto-create the topic single-partitioned (regression: the resync
        # ship would then skip creation and mis-partition the replica)
        client.create_topic(TopicSpec("events", 2))
        p = client.transactional_producer("txn-0")
        for i in range(4):
            p.begin()
            p.send(rec("events", f"k{i}", f"v{i}".encode(), partition=i % 2))
            p.commit()
        follower.stop(grace=0.05)
        _commit_retrying(p, rec("events", "kd", b"degrade"))  # ISR drop
        # empty replacement; traffic keeps flowing; NO catch_up anywhere
        follower = LogServer(InMemoryLog(), port=fport)
        follower.start()
        deadline = _t.perf_counter() + 10
        i = 100
        while (_t.perf_counter() < deadline
               and not leader.replication_status()["replicas"][
                   f"127.0.0.1:{fport}"]):
            p.begin()
            p.send(rec("events", f"k{i}", f"live{i}".encode(),
                       partition=i % 2))
            p.commit()
            i += 1
            _t.sleep(0.15)
        assert leader.replication_status()["replicas"][
            f"127.0.0.1:{fport}"] is True
        # dedup rode along: a failover retry of the last seq would dedup here
        assert (follower._txn_dedup["txn-0"].last_seq
                == leader._txn_dedup["txn-0"].last_seq > 0)
        # and the follower is an identical full copy once the queue drains
        deadline = _t.perf_counter() + 10
        while _t.perf_counter() < deadline and leader._repl_queue:
            _t.sleep(0.05)
        flog = GrpcLogTransport(f"127.0.0.1:{fport}")
        try:
            lv = [(r.offset, r.value) for r in client.read("events", 0)]
            fv = [(r.offset, r.value) for r in flog.read("events", 0)]
            assert fv == lv and len(fv) >= 6
        finally:
            flog.close()
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_idle_broker_rejoins_follower_without_traffic():
    """Rejoin must not depend on produce activity: after the follower is
    healed (here: auto-resyncable small lag), an IDLE leader re-admits it
    from the probe loop alone — the Kafka replica fetch loop runs regardless
    of traffic."""
    import time as _t

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    cfg = _degrade_cfg()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        for i in range(3):
            p.begin()
            p.send(rec("events", f"k{i}", f"v{i}".encode()))
            p.commit()
        follower.stop(grace=0.05)
        _commit_retrying(p, rec("events", "kd", b"degrade"))  # ISR drop
        follower = LogServer(InMemoryLog(), port=fport)
        follower.start()
        # NO further commits: the probe loop alone must resync + re-admit
        deadline = _t.perf_counter() + 10
        while (_t.perf_counter() < deadline
               and not leader.replication_status()["replicas"][
                   f"127.0.0.1:{fport}"]):
            _t.sleep(0.1)
        assert leader.replication_status()["replicas"][
            f"127.0.0.1:{fport}"] is True
        flog = GrpcLogTransport(f"127.0.0.1:{fport}")
        try:
            lv = [(r.offset, r.value) for r in client.read("events", 0)]
            fv = [(r.offset, r.value) for r in flog.read("events", 0)]
            assert fv == lv and len(fv) == 4
        finally:
            flog.close()
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_three_replica_min_insync_two_semantics():
    """RF=3 with min-insync=2 (the classic Kafka posture): one dead follower
    degrades the set and commits keep flowing with 2/3 replicas acking; both
    followers dead blocks commits (the floor holds); the healed follower
    auto-rejoins and the set recovers."""
    import time as _t

    cfg = _degrade_cfg(**{"surge.log.replication-min-insync": 2})
    f1 = LogServer(InMemoryLog())
    f2 = LogServer(InMemoryLog())
    p1, p2 = f1.start(), f2.start()
    targets = [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
    leader = LogServer(InMemoryLog(), config=cfg, replicate_to=targets)
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        p.begin(); p.send(rec("events", "k", b"v0")); p.commit()
        assert leader.replication_status()["insync_count"] == 3

        f1.stop(grace=0.05)  # one follower dies: 2/3 still >= min-insync
        out = _commit_retrying(p, rec("events", "k", b"v1"))
        assert out[0].offset == 1
        st = leader.replication_status()
        assert st["insync_count"] == 2
        assert st["replicas"][targets[0]] is False
        assert st["replicas"][targets[1]] is True
        # the surviving follower has every acked record
        flog = GrpcLogTransport(targets[1])
        try:
            assert [r.value for r in flog.read("events", 0)] == [b"v0", b"v1"]
        finally:
            flog.close()

        f2.stop(grace=0.05)  # second follower dies: 1/3 < min-insync=2
        with pytest.raises(Exception):
            p.begin()
            p.send(rec("events", "k", b"v2"))
            p.commit()  # blocks: the floor holds, nothing degrades further
        assert leader.replication_status()["insync_count"] == 2  # not dropped

        # heal follower 2: an EMPTY replacement that is still IN the set
        # (the floor forbade dropping it) gap-fails ships until the in-place
        # resync bridges it; the client's blocked producer observed the
        # unresolved window as fencing, so it re-opens (the publisher's
        # reinit ladder) and traffic resumes
        f2 = LogServer(InMemoryLog(), port=p2)
        f2.start()
        p = client.transactional_producer("txn-0")
        out = _commit_retrying(p, rec("events", "k", b"v3"))
        assert leader.replication_status()["replicas"][targets[1]] is True
        # v2 was applied locally before its ack blocked; once healed it
        # finalized ahead of v3 in queue order
        vals = [r.value for r in client.read("events", 0)]
        assert vals[:2] == [b"v0", b"v1"] and vals[-1] == b"v3"
        assert b"v2" in vals
        # the healed follower holds the identical log
        flog2 = GrpcLogTransport(targets[1])
        try:
            assert [r.value for r in flog2.read("events", 0)] == vals
        finally:
            flog2.close()
    finally:
        client.close()
        leader.stop()
        f1.stop()
        f2.stop()


def test_engine_unaffected_by_follower_churn():
    """The full command engine keeps serving at normal latency while the
    FOLLOWER dies, is replaced empty, and auto-heals — the ISR machinery is
    invisible to the publisher/entity path, no command effect is lost, and
    the healed follower ends byte-identical (so a later leader failover
    would lose nothing)."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.models import counter

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    bcfg = _degrade_cfg()
    leader = LogServer(InMemoryLog(), config=bcfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    ecfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.engine.num-partitions": 2,
        "surge.log.replication-ack-timeout-ms": 400,
    })

    async def scenario():
        import time as _t

        log = GrpcLogTransport(f"127.0.0.1:{lport}", config=ecfg)
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=log, config=ecfg)
        await engine.start()
        counts = {f"agg-{i}": 0 for i in range(4)}

        async def send_ok(agg):
            for _ in range(50):
                r = await engine.aggregate_for(agg).send_command(
                    counter.Increment(agg))
                if isinstance(r, CommandSuccess):
                    counts[agg] += 1
                    return r
                await asyncio.sleep(0.1)
            raise AssertionError(f"command stuck for {agg}: {r}")

        nonlocal follower
        for agg in counts:
            await send_ok(agg)
        follower.stop(grace=0.05)  # follower dies mid-traffic
        for round_ in range(3):
            for agg in counts:
                await send_ok(agg)  # degrade window: engine unaffected
        follower = LogServer(InMemoryLog(), port=fport)
        follower.start()  # empty replacement auto-heals while traffic flows
        deadline = _t.perf_counter() + 15
        while (_t.perf_counter() < deadline
               and not leader.replication_status()["replicas"][
                   f"127.0.0.1:{fport}"]):
            for agg in counts:
                await send_ok(agg)
            await asyncio.sleep(0.05)
        assert leader.replication_status()["replicas"][
            f"127.0.0.1:{fport}"] is True
        # every command's effect is present exactly once
        for agg, n in counts.items():
            st = await engine.aggregate_for(agg).get_state()
            assert (st.count, st.version) == (n, n), agg
        await engine.stop()
        log.close()

    asyncio.run(scenario())
    leader.stop()
    follower.stop()


def test_engine_recovers_from_single_broker_bounce(tmp_path):
    """An UNREPLICATED broker that dies and restarts on the same address
    (FileLog-backed, so the log survives) must not live-lock the engine: the
    restarted broker answers stale producer tokens as fenced, the publisher's
    reinit ladder re-opens, and no command effect is lost or doubled."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter

    broker = LogServer(FileLog(str(tmp_path / "b")))
    port = broker.start()
    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 2,
    })

    async def scenario():
        nonlocal broker
        log = GrpcLogTransport(f"127.0.0.1:{port}", config=cfg)
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=log, config=cfg)
        await engine.start()
        for _ in range(5):
            r = await engine.aggregate_for("a").send_command(
                counter.Increment("a"))
            assert isinstance(r, CommandSuccess)

        broker.stop(grace=0.05)          # total outage...
        await asyncio.sleep(0.7)         # ...long enough for loops to fail
        broker = LogServer(FileLog(str(tmp_path / "b")))
        broker._port = port
        assert broker.start() == port    # ...and the same address comes back

        ok = None
        for _ in range(100):
            r = await engine.aggregate_for("a").send_command(
                counter.Increment("a"))
            if isinstance(r, CommandSuccess):
                ok = r
                break
            await asyncio.sleep(0.2)
        assert ok is not None, "engine never recovered from the bounce"
        assert (ok.state.count, ok.state.version) == (6, 6), ok.state
        await engine.stop()
        log.close()

    asyncio.run(scenario())
    broker.stop()


def test_txn_dedup_survives_broker_restart(tmp_path):
    """The idempotency window must not die with the broker: __txn_state
    persists (txn_id -> seq, record locations) with each commit, the restarted
    broker recovers it, OpenProducer resumes the client's numbering, and a
    replayed seq is answered with the ORIGINAL reply (rebuilt by re-reading
    the committed records) — never appended twice. A replayed seq with a
    DIFFERENT payload is refused loudly."""
    broker = LogServer(FileLogFactory(tmp_path)())
    port = broker.start()
    client = GrpcLogTransport(f"127.0.0.1:{port}")
    client.create_topic(TopicSpec("events", 1))
    p = client.transactional_producer("txn-0")
    p.begin()
    p.send(rec("events", "k", b"v0"))
    out = p.commit()  # seq 1
    assert [r.offset for r in out] == [0]
    end_before = client.end_offset("events", 0)
    client.close()
    broker.stop(grace=0.1)

    broker2 = LogServer(FileLogFactory(tmp_path)())
    broker2._port = port
    assert broker2.start() == port
    client2 = GrpcLogTransport(f"127.0.0.1:{port}")
    try:
        p2 = client2.transactional_producer("txn-0")
        assert p2._next_seq == 2  # numbering recovered across the restart
        # the acked-but-reply-lost case: replay seq 1 with the SAME payload
        replay = client2._transact(p2._token, "commit",
                                   [rec("events", "k", b"v0")], seq=1)
        assert replay.ok and [m.offset for m in replay.records] == [0]
        assert client2.end_offset("events", 0) == end_before  # no re-append
        # and replaying it with a DIFFERENT payload is refused, not absorbed
        bad = client2._transact(p2._token, "commit",
                                [rec("events", "k", b"OTHER")], seq=1)
        assert not bad.ok and bad.error_kind == "state"
        assert client2.end_offset("events", 0) == end_before
        # normal traffic resumes at the next seq
        p2.begin()
        p2.send(rec("events", "k", b"v1"))
        out2 = p2.commit()
        assert out2[0].offset == end_before
    finally:
        client2.close()
        broker2.stop()


def FileLogFactory(tmp_path):
    from surge_tpu.log.file import FileLog

    def make():
        return FileLog(str(tmp_path / "broker"))

    return make


def test_engine_exact_counts_across_repeated_broker_bounces(tmp_path):
    """The exactly-once ledger under the worst single-broker weather: the
    FileLog-backed broker bounces repeatedly while commands flow. Every
    CommandSuccess acked to the caller is counted, and the final aggregate
    states must equal the acked counts EXACTLY — the durable __txn_state
    dedup plus the publisher's verbatim-batch retry make an
    acked-then-bounced commit impossible to double-apply and an
    unacked-landed one impossible to lose or duplicate on retry."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter

    broker = LogServer(FileLog(str(tmp_path / "b")))
    port = broker.start()
    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.aggregate.publish-timeout-ms": 2000,
        "surge.engine.num-partitions": 2,
    })

    async def scenario():
        nonlocal broker
        log = GrpcLogTransport(f"127.0.0.1:{port}", config=cfg)
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=log, config=cfg)
        await engine.start()
        acked = {f"agg-{i}": 0 for i in range(4)}

        async def send_ok(agg):
            for _ in range(120):
                r = await engine.aggregate_for(agg).send_command(
                    counter.Increment(agg))
                if isinstance(r, CommandSuccess):
                    acked[agg] += 1
                    return
                await asyncio.sleep(0.1)
            raise AssertionError(f"command never succeeded for {agg}")

        for bounce in range(3):
            for agg in acked:
                await send_ok(agg)
            broker.stop(grace=0.05)
            await asyncio.sleep(0.3)
            broker = LogServer(FileLog(str(tmp_path / "b")))
            broker._port = port
            assert broker.start() == port
            for agg in acked:
                await send_ok(agg)

        for agg, n in acked.items():
            st = await engine.aggregate_for(agg).get_state()
            assert (st.count, st.version) == (n, n), (agg, st, n)
        await engine.stop()
        log.close()

    asyncio.run(scenario())
    broker.stop()


# -- fault-plane-driven failure semantics (surge_tpu.testing.faults) ------------------
# The ad-hoc-monkeypatch era of these scenarios is over: the same shared,
# seedable plane the chaos tests use drives ship failures and worker bugs.


def test_isr_eviction_and_auto_resync_via_fault_plane():
    """Blackholed ships (plane: ship.* drop) evict the follower from the
    in-sync set after the isr-timeout — commits proceed at min-insync —
    and DISARMING the plane lets the leader's probe auto-resync the small
    lag and re-admit the follower, no operator catch_up involved."""
    from surge_tpu.testing.faults import FaultPlane, FaultRule

    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=_degrade_cfg(),
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=_degrade_cfg())
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        out = _commit_retrying(p, rec("events", "k0", b"v0"))
        assert out[0].offset == 0

        leader.faults = FaultPlane([FaultRule(site="ship.*", action="drop",
                                              times=None)])
        for i in range(1, 4):
            _commit_retrying(p, rec("events", f"k{i}", f"v{i}".encode()))
        status = leader.replication_status()
        assert status["replicas"][f"127.0.0.1:{fport}"] is False  # evicted
        assert follower.log.end_offset("events", 0) == 1  # lag accrued

        leader.faults.disarm()  # network heals: probe pushes the lag itself
        import time as _t

        deadline = _t.perf_counter() + 15
        while _t.perf_counter() < deadline:
            if leader.replication_status()["replicas"][f"127.0.0.1:{fport}"]:
                break
            _t.sleep(0.1)
        assert leader.replication_status()["replicas"][f"127.0.0.1:{fport}"]
        assert [r.value for r in follower.log.read("events", 0)] == \
            [b"v0", b"v1", b"v2", b"v3"]
    finally:
        client.close()
        leader.stop()
        follower.stop()


def test_replication_poison_path_via_fault_plane():
    """A head item that makes the worker RAISE repeatedly (plane:
    raise.repl.iteration) is failed past the queue after the bounded strike
    count (~17s of backoff); the batch — durably applied on the leader — is
    acked into the dedup cache so the client's verbatim retry converges on
    offset 0 instead of livelocking, the worker survives, and later commits
    replicate normally (the skipped batch reaches the follower through the
    gap-triggered resync). Degraded loudly, never stuck silently."""
    from surge_tpu.testing.faults import FaultPlane, FaultRule

    cfg = _degrade_cfg(**{"surge.log.txn-inorder-timeout-ms": 200})
    follower = LogServer(InMemoryLog())
    fport = follower.start()
    leader = LogServer(InMemoryLog(), config=cfg,
                       replicate_to=[f"127.0.0.1:{fport}"])
    lport = leader.start()
    client = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
    try:
        client.create_topic(TopicSpec("events", 1))
        p = client.transactional_producer("txn-0")
        # every iteration with a queued item raises; the 20-strike poison
        # bound then fails the head item past the queue
        leader.faults = FaultPlane([FaultRule(site="raise.repl.iteration",
                                              action="error", times=None,
                                              error="poisoned head item")])
        # the publisher-protocol retry ladder rides through the poison
        # window; exactly-once: the batch lands at offset 0 ONCE
        out = _commit_retrying(p, rec("events", "k", b"poisoned"),
                               attempts=120)
        assert out[0].offset == 0

        import time as _t

        assert not leader._repl_queue, "poisoned item never failed past"
        assert leader._repl_thread.is_alive()  # the worker survived
        assert leader.log.end_offset("events", 0) == 1  # never appended twice

        leader.faults.disarm()
        # fresh traffic replicates again, and the resync path heals the
        # follower's gap from the skipped ship
        out = _commit_retrying(p, rec("events", "k2", b"after"))
        assert out[0].offset == 1
        deadline = _t.perf_counter() + 15
        while _t.perf_counter() < deadline and (
                follower.log.end_offset("events", 0) < 2):
            _t.sleep(0.1)
        follower_vals = [r.value for r in follower.log.read("events", 0)]
        assert follower_vals == [b"poisoned", b"after"]
    finally:
        client.close()
        leader.stop()
        follower.stop()
