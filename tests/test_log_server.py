"""gRPC log broker: the LogTransport contract over the wire.

The seam proof VERDICT r2 missing #2 asks for — transactions, fencing (including
across two client connections, i.e. two would-be processes), read_committed
no-partial-visibility, compaction reads, and an engine running end-to-end against
the networked transport (KafkaProducer.scala:106-117, KafkaConsumer.scala:17-132
roles)."""

import asyncio

import pytest

from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)


@pytest.fixture
def broker():
    server = LogServer(InMemoryLog())
    port = server.start()
    clients = []

    def connect() -> GrpcLogTransport:
        c = GrpcLogTransport(f"127.0.0.1:{port}")
        clients.append(c)
        return c

    yield connect
    for c in clients:
        c.close()
    server.stop()


def rec(topic, key, value, partition=0):
    return LogRecord(topic=topic, key=key, value=value, partition=partition)


def test_broker_hop_spans_share_one_trace():
    """Client-side log.Transact span and the broker-side log.server.transact
    span join on one trace id — the traceparent crosses as gRPC call metadata.
    Reads get log.<Method> spans; WaitForAppend long-polls are excluded."""
    from surge_tpu.tracing import InMemoryTracer

    client_tracer, server_tracer = InMemoryTracer(), InMemoryTracer()
    server = LogServer(InMemoryLog(), tracer=server_tracer)
    port = server.start()
    log = GrpcLogTransport(f"127.0.0.1:{port}", tracer=client_tracer)
    try:
        log.create_topic(TopicSpec("t", 1))
        p = log.transactional_producer("txn-span")
        p.begin()
        p.send(rec("t", "k", b"v"))
        p.commit()
        log.read("t", 0)

        tx = client_tracer.spans_named("log.Transact")[0]
        assert tx.attributes["op"] == "commit"
        srv = server_tracer.spans_named("log.server.transact")
        # the open-producer flow performs broker-side transacts too; find the
        # one continuing the CLIENT's commit trace
        joined = [s for s in srv if s.context.trace_id == tx.context.trace_id]
        assert joined and joined[0].parent_id == tx.context.span_id
        assert joined[0].attributes["op"] == "commit"
        assert client_tracer.spans_named("log.Read")
        assert not client_tracer.spans_named("log.WaitForAppend")
    finally:
        log.close()
        server.stop()


def test_transaction_atomic_multi_topic_commit_over_wire(broker):
    log = broker()
    log.create_topic(TopicSpec("events", 2))
    log.create_topic(TopicSpec("state", 2, compacted=True))
    p = log.transactional_producer("txn-0")
    p.begin()
    p.send(rec("events", "a", b"e1"))
    p.send(rec("events", "a", b"e2"))
    p.send(rec("state", "a", b"s2"))
    assert log.end_offset("events", 0) == 0  # nothing visible pre-commit
    out = p.commit()
    assert [r.offset for r in out] == [0, 1, 0]
    assert [r.value for r in log.read("events", 0)] == [b"e1", b"e2"]
    assert log.latest_by_key("state", 0)["a"].value == b"s2"


def test_fencing_across_two_client_connections(broker):
    """Two connections = two processes: opening the same transactional id from a
    second client must fence the first (the zombie-writer exclusion)."""
    log1, log2 = broker(), broker()
    old = log1.transactional_producer("txn-0")
    old.begin()
    old.send(rec("events", "a", b"zombie"))
    new = log2.transactional_producer("txn-0")  # fences `old` server-side
    with pytest.raises(ProducerFencedError):
        old.commit()
    assert old.fenced
    new.begin()
    new.send(rec("events", "a", b"live"))
    new.commit()
    assert [r.value for r in log1.read("events", 0)] == [b"live"]


def test_abort_and_state_errors(broker):
    log = broker()
    p = log.transactional_producer("t")
    with pytest.raises(TransactionStateError):
        p.commit()
    p.begin()
    p.send(rec("events", "a", b"dead"))
    p.abort()
    assert log.end_offset("events", 0) == 0
    r = p.send_immediate(rec("events", "a", b"imm"))
    assert r.offset == 0


def test_tombstone_and_headers_round_trip(broker):
    log = broker()
    log.create_topic(TopicSpec("state", 1, compacted=True))
    p = log.transactional_producer("t")
    p.begin()
    p.send(LogRecord(topic="state", key="k", value=b"v",
                     headers={"traceparent": "00-x"}))
    p.send(LogRecord(topic="state", key="gone", value=b"x"))
    p.send(LogRecord(topic="state", key="gone", value=None))  # tombstone
    p.commit()
    recs = log.read("state", 0)
    assert recs[0].headers == {"traceparent": "00-x"}
    assert recs[2].value is None and recs[2].key == "gone"
    assert "gone" not in log.latest_by_key("state", 0)


def test_commit_replay_is_idempotent(broker):
    """Reply loss: retrying a commit with the same txn_seq must not append twice
    — the server answers the replayed seq from its dedup cache (ADVICE r3 #1)."""
    log = broker()
    log.create_topic(TopicSpec("events", 1))
    p = log.transactional_producer("txn-0")
    p.begin()
    p.send(rec("events", "a", b"e1"))
    p.send(rec("events", "a", b"e2"))
    first = p.commit()
    # simulate the lost-reply retry: same token, same seq, same records
    replay = log._transact(p._token, "commit",
                           [rec("events", "a", b"e1"), rec("events", "a", b"e2")],
                           seq=1)
    assert replay.ok
    assert [m.offset for m in replay.records] == [r.offset for r in first]
    assert [r.value for r in log.read("events", 0)] == [b"e1", b"e2"]  # no dupes
    unseq = log._transact(p._token, "commit", [rec("events", "a", b"e3")], seq=0)
    assert unseq.ok  # seq=0 opts out of dedup (appends normally)
    p.begin(); p.send(rec("events", "a", b"e4"))
    assert p.commit()[0].offset == 3  # producer's own seq advanced to 2
    # now seq=1 is older than last_seq=2: rejected, nothing appended
    older = log._transact(p._token, "commit", [rec("events", "a", b"e5")], seq=1)
    assert not older.ok and older.error_kind == "state"
    assert log.end_offset("events", 0) == 4


def test_wait_for_append_wakes_on_commit(broker):
    log = broker()
    log.create_topic(TopicSpec("events", 1))

    async def scenario():
        waiter = asyncio.ensure_future(log.wait_for_append("events", 0, 0))
        await asyncio.sleep(0.1)
        assert not waiter.done()
        p = log.transactional_producer("t")
        p.begin(); p.send(rec("events", "a", b"x")); p.commit()
        await asyncio.wait_for(waiter, 5.0)

    asyncio.run(scenario())


def test_engine_end_to_end_over_grpc_log(broker):
    """The whole engine (publisher transactions, indexer tailing, entity recovery)
    against the networked broker — the EmbeddedKafka-style integration test."""
    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.engine.entity import CommandSuccess
    from surge_tpu.models import counter

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 2,
    })

    def logic():
        return SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting())

    async def scenario():
        log = broker()
        engine = create_engine(logic(), log=log, config=cfg)
        await engine.start()
        for i in range(10):
            agg = f"agg-{i % 3}"
            r = await engine.aggregate_for(agg).send_command(counter.Increment(agg))
            assert isinstance(r, CommandSuccess)
        st = await engine.aggregate_for("agg-0").get_state()
        assert st.count == 4
        await engine.stop()

        # a SECOND engine (fresh process equivalent) recovers state from the broker
        engine2 = create_engine(logic(), log=broker(), config=cfg)
        await engine2.start()
        st = await engine2.aggregate_for("agg-0").get_state()
        assert st is not None and st.count == 4
        await engine2.stop()

    asyncio.run(scenario())


# -- pipelined transactions (bounded in-flight window + in-order apply gate) -------------


def test_pipelined_commits_dispatch_without_awaiting_replies(broker):
    """commit_pipelined ships a window of Transacts without waiting for
    earlier replies; every commit lands exactly once, in seq order."""
    log = broker()
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("pipe")
    handles = []
    for i in range(8):
        p.begin()
        p.send(rec("t", f"k{i}", b"v%d" % i))
        handles.append(p.commit_pipelined())
    for i, h in enumerate(handles):
        committed = h.future.result(timeout=10)
        assert [r.value for r in committed] == [b"v%d" % i]
    assert [r.value for r in log.read("t", 0)] == [b"v%d" % i for i in range(8)]


def test_out_of_order_pipelined_seqs_apply_in_order(broker):
    """The broker's in-order gate holds a seq that arrives ahead of its
    predecessor until the predecessor applies — wire reordering cannot
    reorder the log."""
    import threading
    import time as _time

    log = broker()
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("gate")
    results = {}

    def send(seq, value, delay):
        _time.sleep(delay)
        results[seq] = log._transact(p._token, "commit",
                                     [rec("t", "k", value)], seq=seq)

    t2 = threading.Thread(target=send, args=(2, b"second", 0.0))
    t1 = threading.Thread(target=send, args=(1, b"first", 0.25))
    t2.start()  # seq 2 arrives FIRST and must wait at the gate
    t1.start()
    t1.join(); t2.join()
    assert results[1].ok and results[2].ok
    assert [r.value for r in log.read("t", 0)] == [b"first", b"second"]


def test_replay_of_non_latest_seq_answered_from_dedup_window(broker):
    """A pipelined client can lose the reply of ANY in-flight seq: replaying a
    non-latest seq is answered from the windowed cache (same offsets), and a
    different payload under a used seq is refused."""
    log = broker()
    log.create_topic(TopicSpec("t", 1))
    p = log.transactional_producer("window")
    replies = []
    for i in range(4):
        p.begin()
        p.send(rec("t", f"k{i}", b"v%d" % i))
        p.commit()
    # replay seq 2 (non-latest) with the identical payload
    replay = log._transact(p._token, "commit", [rec("t", "k1", b"v1")], seq=2)
    assert replay.ok
    assert [m.value for m in replay.records] == [b"v1"]
    assert log.end_offset("t", 0) == 4  # nothing re-appended
    # same seq, different payload: refused loudly
    bad = log._transact(p._token, "commit", [rec("t", "k1", b"OTHER")], seq=2)
    assert not bad.ok and bad.error_kind == "state"


def test_inorder_gate_timeout_answers_retriable():
    """A seq whose predecessor never arrives gets a RETRIABLE answer (the
    client retries the same seq), not a hang and not an append."""
    server = LogServer(InMemoryLog(), config=__import__(
        "surge_tpu.config", fromlist=["default_config"]).default_config()
        .with_overrides({"surge.log.txn-inorder-timeout-ms": 200}))
    port = server.start()
    log = GrpcLogTransport(f"127.0.0.1:{port}")
    try:
        log.create_topic(TopicSpec("t", 1))
        p = log.transactional_producer("gap")
        p.begin(); p.send(rec("t", "a", b"v1")); p.commit()  # seq 1
        # raw request (the client's retry loop would convert the exhausted
        # retriable into its fenced/reopen ladder — here we want the reply)
        from surge_tpu.log import log_service_pb2 as pb
        from surge_tpu.log.server import record_to_msg

        reply = log._calls["Transact"](pb.TxnRequest(
            producer_token=p._token, op="commit", txn_seq=3,
            records=[record_to_msg(rec("t", "a", b"v3"))]), timeout=10.0)
        assert not reply.ok and reply.error_kind == "retriable"
        assert log.end_offset("t", 0) == 1  # the gapped seq never applied
        # the missing predecessor arrives; both seqs then land in order
        assert log._transact(p._token, "commit", [rec("t", "a", b"v2")],
                             seq=2).ok
        assert log._transact(p._token, "commit", [rec("t", "a", b"v3")],
                             seq=3).ok
        assert [r.value for r in log.read("t", 0)] == [b"v1", b"v2", b"v3"]
    finally:
        log.close()
        server.stop()


def test_dedup_window_survives_broker_restart(tmp_path):
    """__txn_state persists the recent-seq locator WINDOW: after a broker
    restart, a replay of a non-latest seq is still answered from the durable
    locators instead of double-appending."""
    from surge_tpu.log.file import FileLog

    root = str(tmp_path / "broker")
    server = LogServer(FileLog(root))
    port = server.start()
    log = GrpcLogTransport(f"127.0.0.1:{port}")
    try:
        log.create_topic(TopicSpec("t", 1))
        p = log.transactional_producer("durable")
        for i in range(3):
            p.begin()
            p.send(rec("t", f"k{i}", b"v%d" % i))
            p.commit()
    finally:
        log.close()
        server.stop()
    server2 = LogServer(FileLog(root))
    port2 = server2.start()
    log2 = GrpcLogTransport(f"127.0.0.1:{port2}")
    try:
        p2 = log2.transactional_producer("durable")
        assert p2._next_seq == 4  # numbering resumed past the recovered seqs
        # replay of a NON-latest seq rebuilt from its windowed locator
        replay = log2._transact(p2._token, "commit",
                                [rec("t", "k1", b"v1")], seq=2)
        assert replay.ok
        assert [m.value for m in replay.records] == [b"v1"]
        assert log2.end_offset("t", 0) == 3  # nothing re-appended
    finally:
        log2.close()
        server2.stop()


def test_publisher_pipelines_over_grpc_exactly_once(broker):
    """End to end: a publisher lane over the gRPC transport keeps a pipelined
    window in flight and every command lands exactly once, in per-aggregate
    order."""
    from surge_tpu.config import default_config
    from surge_tpu.engine.publisher import PartitionPublisher
    from surge_tpu.store import StateStoreIndexer

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.linger-ms": 0,
        "surge.producer.max-in-flight": 4,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
    })

    async def scenario():
        log = broker()
        log.create_topic(TopicSpec("events", 1))
        log.create_topic(TopicSpec("state", 1, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=cfg)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer, config=cfg)
        await pub.start()
        await pub.wait_ready(10.0)
        assert pub._pipeline_capable()

        async def stream(agg, n):
            for i in range(n):
                await asyncio.wait_for(pub.publish(
                    agg, [rec("events", agg, b"%s:%d" % (agg.encode(), i))],
                    f"{agg}-{i}"), 10.0)

        await asyncio.gather(*(stream(f"g{j}", 8) for j in range(4)))
        values = [r.value for r in log.read("events", 0)]
        assert len(values) == 32 and len(set(values)) == 32
        for j in range(4):
            seq = [v for v in values if v.startswith(b"g%d:" % j)]
            assert seq == sorted(seq, key=lambda v: int(v.split(b":")[-1]))
        assert pub.stats.inflight_peak >= 1
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_dump_traces_rpc_round_trip():
    """DumpTraces on the log service (ISSUE 14): broker-side tail-kept spans
    (with the measured leg attrs) come back in the merge-ready envelope;
    an untraced broker answers an explicit state error."""
    from surge_tpu.config import Config
    from surge_tpu.tracing import Tracer

    cfg = Config(overrides={"surge.trace.tail.latency-ms": 0})
    server = LogServer(InMemoryLog(), tracer=Tracer(service="broker"),
                       config=cfg)
    port = server.start()
    log = GrpcLogTransport(f"127.0.0.1:{port}")
    try:
        log.create_topic(TopicSpec("t", 1))
        p = log.transactional_producer("txn-ring")
        p.begin()
        p.send(rec("t", "k", b"v"))
        p.commit()
        dump = log.trace_dump()
        assert dump["role"] == "broker"
        assert dump["recorder"] == server.advertised
        spans = [s for e in dump["traces"] for s in e["spans"]]
        transacts = [s for s in spans if s["name"] == "log.server.transact"]
        assert transacts
        # the broker MEASURES its journal leg onto the span (anatomy source)
        assert any("leg.fsync-ms" in s["attributes"] for s in transacts)
        # spans carry both clocks for the skew-proof assembly
        assert all(s["start_mono"] is not None and s["end_mono"] is not None
                   for s in spans)
        assert len(log.trace_dump(last=1)["traces"]) == 1
    finally:
        log.close()
        server.stop()

    server2 = LogServer(InMemoryLog())
    port2 = server2.start()
    log2 = GrpcLogTransport(f"127.0.0.1:{port2}")
    try:
        with pytest.raises(RuntimeError, match="no trace ring"):
            log2.trace_dump()
    finally:
        log2.close()
        server2.stop()
