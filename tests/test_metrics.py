"""Metrics registry + statistics providers (metrics/statistics *Spec analogs)."""

import time

from surge_tpu.metrics import MetricInfo, Metrics, RecordingLevel, engine_metrics
from surge_tpu.metrics.statistics import (
    Count,
    ExponentialWeightedMovingAverage,
    Max,
    Min,
    MostRecentValue,
    RateHistogram,
    TimeBucketHistogram,
)


def test_basic_providers():
    now = time.time()
    c, mr, mn, mx = Count(), MostRecentValue(), Min(), Max()
    for v in (5.0, 1.0, 3.0):
        for p in (c, mr, mn, mx):
            p.update(v, now)
    assert c.get_value() == 9.0
    assert mr.get_value() == 3.0
    assert mn.get_value() == 1.0
    assert mx.get_value() == 5.0
    assert Min().get_value() == 0.0  # empty


def test_ewma_smoothing():
    e = ExponentialWeightedMovingAverage(alpha=0.5)
    e.update(100.0, 0)
    assert e.get_value() == 100.0  # first value initializes
    e.update(0.0, 0)
    assert e.get_value() == 50.0
    e.update(0.0, 0)
    assert e.get_value() == 25.0


def test_rate_histogram_window_eviction():
    r = RateHistogram(window_s=60.0)
    now = time.time()
    for i in range(120):
        r.update(1.0, now - 90 + i)  # half the marks are older than the window
    assert abs(r.get_value() - 60 / 60.0) < 0.2


def test_time_bucket_histogram_percentile():
    h = TimeBucketHistogram(buckets_ms=(10, 100, 1000), percentile=0.99)
    assert h.get_value() == 0.0
    for _ in range(99):
        h.update(5.0, 0)
    h.update(500.0, 0)
    assert h.get_value() == 10  # 99% of samples sit in the 10ms bucket
    for _ in range(10):
        h.update(500.0, 0)  # fatten the tail past 1%
    assert h.get_value() == 1000  # p99 now lands in the 1000ms bucket bound


def test_registry_instruments_and_export():
    m = Metrics()
    m.counter(MetricInfo("c", "a counter")).record(2)
    m.counter(MetricInfo("c")).record(3)
    m.gauge(MetricInfo("g")).record(7)
    t = m.timer(MetricInfo("t"))
    t.record_ms(10.0)
    with t.time():
        pass
    m.rate(MetricInfo("r")).record()

    snap = m.get_metrics()
    assert snap["c"] == 5.0
    assert snap["g"] == 7.0
    assert snap["t.max"] >= snap["t.min"] >= 0.0
    assert snap["r.one-minute-rate"] > 0
    assert m.metric_descriptions()["c"] == "a counter"
    assert "<table>" in m.as_html() and "<td>c</td>" in m.as_html()


def test_recording_level_filters():
    m = Metrics(recording_level=RecordingLevel.INFO)
    debug = m.counter(MetricInfo("d"), level=RecordingLevel.DEBUG)
    debug.record(5)
    assert m.get_metrics()["d"] == 0.0  # DEBUG sensor disabled at INFO level

    m2 = Metrics(recording_level=RecordingLevel.TRACE)
    m2.counter(MetricInfo("d"), level=RecordingLevel.DEBUG).record(5)
    assert m2.get_metrics()["d"] == 5.0


def test_engine_metrics_quiver_names():
    em = engine_metrics()
    snap = em.registry.get_metrics()
    for name in ("surge.aggregate.state-fetch-timer",
                 "surge.aggregate.command-handling-timer",
                 "surge.aggregate.event-publish-timer",
                 "surge.producer.flush-timer",
                 "surge.replay.rebuild-timer",
                 "surge.engine.command-rate.one-minute-rate",
                 "surge.producer.fences",
                 "surge.engine.live-entities",
                 "surge.state-store.standby-lag",
                 "surge.replay.profile.encode-timer",
                 "surge.replay.profile.fetch-timer"):
        assert name in snap, name


def test_engine_metrics_fields_all_declared():
    """Regression: standby_lag was assigned in __post_init__ without a
    field(init=False) declaration like its siblings — every attribute the
    quiver assigns must be a declared dataclass field."""
    import dataclasses

    from surge_tpu.metrics import EngineMetrics

    em = engine_metrics()
    declared = {f.name for f in dataclasses.fields(EngineMetrics)}
    assigned = set(vars(em))
    assert assigned <= declared, assigned - declared
    assert "standby_lag" in declared


def test_timer_time_async():
    import asyncio

    async def scenario():
        m = Metrics()
        t = m.timer(MetricInfo("async-t"))

        async def work():
            await asyncio.sleep(0.01)
            return 42

        assert await t.time_async(work()) == 42
        # exceptions still record the elapsed time and propagate
        async def boom():
            await asyncio.sleep(0.01)
            raise RuntimeError("x")

        try:
            await t.time_async(boom())
        except RuntimeError:
            pass
        return m.get_metrics()

    snap = asyncio.run(scenario())
    assert snap["async-t.min"] >= 5.0  # both awaits took >= ~10ms
    assert snap["async-t.max"] >= snap["async-t.min"]


def test_rate_histogram_injectable_clock():
    now = [60.0]
    r = RateHistogram(window_s=60.0, clock=lambda: now[0])
    for i in range(60):
        r.update(1.0, float(i))  # ts 0..59, all inside the frozen window
    assert r.get_value() == 1.0
    now[0] = 90.0  # half the marks age out, deterministically
    assert r.get_value() == 0.5
    now[0] = 200.0
    assert r.get_value() == 0.0


def test_time_bucket_histogram_overflow_is_finite():
    h = TimeBucketHistogram(buckets_ms=(10, 100), percentile=0.99)
    for _ in range(100):
        h.update(5000.0, 0)  # everything lands past the last bound
    v = h.get_value()
    assert v == 100  # largest FINITE bound, never float("inf")
    # the unbounded tail is still visible in the histogram series
    buckets = h.bucket_counts()
    assert buckets[-1] == (float("inf"), 100)
    assert buckets[-2] == (100, 0)
    assert h.total_count == 100
    assert h.sum_value == 500000.0
