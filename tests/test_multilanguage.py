"""Multilanguage bridge: full polyglot loop over real gRPC sockets.

The MultilanguageGatewayServiceImplSpec analog (SURVEY.md §4.5): a "business app"
(pure CQRSModel + JSON SerDeser, scala-sdk-sample Main.scala analog) serves the
BusinessLogic service; the engine runs the generic byte-payload model whose
process_command/handle_events are gRPC calls to it; the app drives commands through
the gateway service and reads state back. Everything over loopback sockets — two
real processes' worth of protocol on one loop.
"""

import asyncio
import json

import grpc
import pytest

from surge_tpu import default_config
from surge_tpu.dsl import create_engine
from surge_tpu.multilanguage import (
    BusinessLogicServer,
    CommandRejectedByApp,
    CQRSModel,
    MultilanguageGatewayServer,
    SerDeser,
    SurgeClient,
    generic_business_logic,
)

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
    "surge.aggregate.init-retry-interval-ms": 5,
    "surge.engine.num-partitions": 2,
})


# --- the "polyglot" app: a bank account in plain dicts + JSON --------------------------


def process_command(state, command):
    kind = command["kind"]
    if kind == "create":
        if state is not None:
            return []
        return [{"kind": "created", "owner": command["owner"],
                 "balance": command["balance"]}]
    if state is None:
        raise CommandRejectedByApp("account does not exist")
    if kind == "credit":
        return [{"kind": "updated", "balance": state["balance"] + command["amount"]}]
    if kind == "debit":
        if state["balance"] < command["amount"]:
            raise CommandRejectedByApp("insufficient funds")
        return [{"kind": "updated", "balance": state["balance"] - command["amount"]}]
    raise CommandRejectedByApp(f"unknown command {kind}")


def handle_events(state, events):
    for e in events:
        if e["kind"] == "created":
            state = {"owner": e["owner"], "balance": e["balance"]}
        elif e["kind"] == "updated" and state is not None:
            state = {**state, "balance": e["balance"]}
    return state


def json_serdes() -> SerDeser:
    enc = lambda o: json.dumps(o, sort_keys=True).encode()
    dec = lambda b: json.loads(b)
    return SerDeser(enc, dec, enc, dec, enc, dec)


def test_full_polyglot_loop():
    async def scenario():
        serdes = json_serdes()
        app_server = BusinessLogicServer(
            CQRSModel(process_command, handle_events), serdes)
        app_port = await app_server.start()

        business_channel = grpc.aio.insecure_channel(f"127.0.0.1:{app_port}")
        engine = create_engine(
            generic_business_logic("bank", business_channel), config=CFG)
        await engine.start()
        gateway = MultilanguageGatewayServer(engine)
        gw_port = await gateway.start()

        gw_channel = grpc.aio.insecure_channel(f"127.0.0.1:{gw_port}")
        client = SurgeClient(gw_channel, serdes)

        # create + credit + debit through the full loop
        ok, state, _ = await client.forward_command(
            "acct-1", {"kind": "create", "owner": "pat", "balance": 100})
        assert ok and state == {"owner": "pat", "balance": 100}
        ok, state, _ = await client.forward_command(
            "acct-1", {"kind": "credit", "amount": 50})
        assert ok and state["balance"] == 150
        ok, state, reason = await client.forward_command(
            "acct-1", {"kind": "debit", "amount": 1000})
        assert not ok and "insufficient" in reason

        # rejection for a missing aggregate
        ok, _, reason = await client.forward_command(
            "acct-404", {"kind": "credit", "amount": 1})
        assert not ok and "does not exist" in reason

        # read path + health
        state = await client.get_state("acct-1")
        assert state == {"owner": "pat", "balance": 150}
        assert await client.get_state("acct-404") is None
        assert await client.health() == "up"

        # the engine really persisted opaque payloads: events topic holds the app's
        # JSON, state topic the serialized state — all uninterpreted by the engine
        evs = []
        for p in range(2):
            evs += [json.loads(r.value) for r in engine.log.read("bank-events", p)]
        assert {e["kind"] for e in evs} == {"created", "updated"}

        await gateway.stop()
        await engine.stop()
        await app_server.stop()
        await business_channel.close()
        await gw_channel.close()

    asyncio.run(scenario())


def test_engine_restart_refolds_through_app(tmp_path):
    """Cold restart: the engine re-reads opaque state bytes it cannot interpret and
    the app keeps working — proving state ownership stays app-side."""
    async def scenario():
        from surge_tpu.log import InMemoryLog

        serdes = json_serdes()
        app_server = BusinessLogicServer(
            CQRSModel(process_command, handle_events), serdes)
        app_port = await app_server.start()
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{app_port}")
        log = InMemoryLog()

        engine = create_engine(generic_business_logic("bank", ch), log=log, config=CFG)
        await engine.start()
        gw = MultilanguageGatewayServer(engine)
        port = await gw.start()
        client = SurgeClient(grpc.aio.insecure_channel(f"127.0.0.1:{port}"), serdes)
        await client.forward_command("a", {"kind": "create", "owner": "x", "balance": 7})
        await gw.stop()
        await engine.stop()

        engine2 = create_engine(generic_business_logic("bank", ch), log=log, config=CFG)
        await engine2.start()
        gw2 = MultilanguageGatewayServer(engine2)
        port2 = await gw2.start()
        client2 = SurgeClient(grpc.aio.insecure_channel(f"127.0.0.1:{port2}"), serdes)
        ok, state, _ = await client2.forward_command("a", {"kind": "credit", "amount": 3})
        assert ok and state == {"owner": "x", "balance": 10}
        await gw2.stop()
        await engine2.stop()
        await app_server.stop()

    asyncio.run(scenario())


def test_empty_bytes_state_round_trips_as_existing():
    """Regression: an app state serializing to ZERO bytes (any all-default proto
    message) must survive restart as exists=True — not collapse to 'no aggregate'.
    None state instead writes a tombstone."""
    async def scenario():
        from surge_tpu.log import InMemoryLog

        # state is a plain counter int; 0 serializes to b"" on purpose
        def ser_state(n):
            return b"" if n == 0 else str(n).encode()

        def deser_state(b):
            return 0 if b == b"" else int(b)

        enc = lambda o: json.dumps(o).encode()
        dec = lambda b: json.loads(b)
        serdes = SerDeser(ser_state, deser_state, enc, dec, enc, dec)

        def pc(state, command):
            if command["kind"] == "init":
                if state is not None:
                    raise CommandRejectedByApp("already exists")
                return [{"kind": "set", "value": 0}]
            return [{"kind": "set", "value": command["value"]}]

        def he(state, events):
            for e in events:
                state = e["value"]
            return state

        app = BusinessLogicServer(CQRSModel(pc, he), serdes)
        port = await app.start()
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        log = InMemoryLog()

        engine = create_engine(generic_business_logic("ctr", ch), log=log, config=CFG)
        await engine.start()
        gw = MultilanguageGatewayServer(engine)
        gwp = await gw.start()
        client = SurgeClient(grpc.aio.insecure_channel(f"127.0.0.1:{gwp}"), serdes)
        ok, state, _ = await client.forward_command("c1", {"kind": "init"})
        assert ok and state == 0
        await gw.stop(); await engine.stop()

        # restart: the zero-byte state must still exist (init is rejected)
        engine2 = create_engine(generic_business_logic("ctr", ch), log=log, config=CFG)
        await engine2.start()
        gw2 = MultilanguageGatewayServer(engine2)
        gwp2 = await gw2.start()
        client2 = SurgeClient(grpc.aio.insecure_channel(f"127.0.0.1:{gwp2}"), serdes)
        ok, _, reason = await client2.forward_command("c1", {"kind": "init"})
        assert not ok and "already exists" in reason
        state = await client2.get_state("c1")
        assert state == 0
        await gw2.stop(); await engine2.stop(); await app.stop()

    asyncio.run(scenario())


def test_async_only_model_cannot_bulk_restore():
    """fold_events must fail with a clear error for async-only models instead of
    an AttributeError deep in the restore path."""
    from surge_tpu.engine.model import fold_events
    from surge_tpu.multilanguage.gateway import GrpcBusinessModel

    class _FakeChannel:
        def unary_unary(self, *a, **kw):
            return lambda req: None

    model = GrpcBusinessModel(_FakeChannel())
    with pytest.raises(TypeError, match="async-only"):
        fold_events(model, None, [b"x"])
