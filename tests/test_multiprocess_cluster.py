"""Genuine multi-process cluster: separate OS processes, real gRPC everywhere.

The reference's multi-jvm spec analog (SurgePartitionRouterImplMultiJvmSpec,
SURVEY.md §4.6) upgraded to real processes: a broker process (shared log + control
plane), two engine worker processes routing commands both ways over the node
transport, then SIGKILL of one worker — heartbeat expiry must rebalance its
partitions to the survivor, which serves the dead worker's aggregates with state
recovered from the shared log (VERDICT r2 missing #3 done-criterion)."""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ,
       "JAX_PLATFORMS": "cpu",
       "SURGE_TEST_PLATFORM": "cpu"}
ENV.pop("PALLAS_AXON_POOL_IPS", None)


def _wait_file(path: str, timeout: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {path}")


def _spawn(args, **kw):
    return subprocess.Popen([sys.executable, *args], cwd=REPO, env=ENV, **kw)


def test_two_process_cluster_routes_and_survives_kill(tmp_path):
    procs = []
    try:
        broker = _spawn(["tests/_cluster_broker.py", "4"],
                        stdout=subprocess.PIPE, text=True)
        procs.append(broker)
        ports = json.loads(broker.stdout.readline())
        cp = f"127.0.0.1:{ports['cp_port']}"
        log = f"127.0.0.1:{ports['log_port']}"

        res_a = str(tmp_path / "a")
        res_b = str(tmp_path / "b")
        worker_a = _spawn(["tests/_cluster_worker.py", cp, log, "alpha", "beta", res_a])
        worker_b = _spawn(["tests/_cluster_worker.py", cp, log, "beta", "alpha", res_b])
        procs += [worker_a, worker_b]

        # round 1: each worker drove 12 aggregates spread over all partitions —
        # with two members each owning 2 of 4 partitions, some commands crossed
        # processes over the node transport in each direction
        r1_a = _wait_file(res_a + ".r1")
        r1_b = _wait_file(res_b + ".r1")
        assert all(c == 1 for c in r1_a.values()), r1_a
        assert all(c == 1 for c in r1_b.values()), r1_b

        # kill worker B without ceremony: heartbeat expiry must hand its
        # partitions to A, which then serves BOTH aggregate sets (B's state
        # recovered from the shared log broker)
        worker_b.send_signal(signal.SIGKILL)
        worker_b.wait(10)
        open(res_a + ".go2", "w").close()
        r2 = _wait_file(res_a + ".r2", timeout=90.0)
        for agg in [f"alpha-{i}" for i in range(12)]:
            assert r2[agg] == 2, (agg, r2[agg])
        for agg in [f"beta-{i}" for i in range(12)]:
            assert r2[agg] == 2, (agg, r2[agg])  # 1 from B pre-kill + 1 now
        # the takeover was a standby PROMOTION, not a log re-scan: while B was
        # still alive and owned its partitions, A's indexer had already tailed
        # them (num-standby-replicas=1) — every non-owned partition shows a
        # nonzero watermark captured BEFORE the kill trigger (VERDICT r3 #4)
        owned_before = set(r2["_owned_before_kill"])
        assert len(owned_before) == 2, r2
        non_owned = {str(p) for p in range(4)} - owned_before
        assert set(r2["_standby_partitions"]) == non_owned, r2
        for p in non_owned:
            assert r2["_standby_watermarks"][p] > 0, (p, r2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(5)
            except Exception:  # noqa: BLE001
                pass
