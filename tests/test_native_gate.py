"""Native broker hot path (csrc/txn.cc via log/native_gate) — the fallback
bit-identity contract, plus the exactly-once battery parametrized over
native-on/native-off.

The acceptance bar (ISSUE 10): the pure-Python twins must produce IDENTICAL
gate decisions and IDENTICAL journal bytes for any batch, so a native broker
and a fallback broker are interchangeable on disk, and an unbuilt checkout
behaves byte-for-byte the same. The randomized property tests here drive both
implementations over the same inputs; the FileLog round-trip drives whole
logs through both paths under a pinned clock and compares raw artifacts.
"""

from __future__ import annotations

import asyncio
import os
import random
import string
import time

import pytest

from surge_tpu.config import default_config
from surge_tpu.log import native_gate as ng
from surge_tpu.log import segment as seg
from surge_tpu.log.file import FileLog
from surge_tpu.log.transport import LogRecord, TopicSpec

needs_native = pytest.mark.skipif(
    not ng.available(),
    reason="libsurge_txn.so not built (csrc/build.sh needs g++)")

NATIVE_MODES = [
    pytest.param(True, id="native-on",
                 marks=pytest.mark.skipif(
                     not ng.available(),
                     reason="libsurge_txn.so not built")),
    pytest.param(False, id="native-off"),
]


def _cfg(native: bool):
    return default_config().with_overrides(
        {"surge.log.native.enabled": native})


# -- randomized batch generator ---------------------------------------------------------


def _rand_text(rng: random.Random, lo: int = 0, hi: int = 12) -> str:
    # includes DEL (0x7f) and a C0 control: CPython json escapes every byte
    # outside 0x20..0x7E — the native escaper must agree (a 0x7f
    # misclassification once slipped past an ASCII-only alphabet here)
    alphabet = string.ascii_letters + string.digits + "-._é✓\x7f\x01\""
    return "".join(rng.choice(alphabet) for _ in range(rng.randint(lo, hi)))


def _rand_records(rng: random.Random, n_topics: int = 3) -> list:
    topics = [f"t{_rand_text(rng, 1, 6)}-{i}" for i in range(n_topics)]
    out = []
    for _ in range(rng.randint(1, 24)):
        headers = {}
        for _h in range(rng.randint(0, 3)):
            headers[_rand_text(rng, 1, 8)] = _rand_text(rng, 0, 16)
        tombstone = rng.random() < 0.15
        out.append(LogRecord(
            topic=rng.choice(topics),
            key=None if rng.random() < 0.2 else _rand_text(rng, 1, 20),
            value=None if tombstone else rng.randbytes(rng.randint(0, 400)),
            partition=rng.randint(0, 2),
            headers=headers))
    return out


def _group_geometry(records):
    """(bases, positions) per first-occurrence (topic, partition) group —
    arbitrary but shared by both formatters."""
    order = []
    seen = set()
    for r in records:
        k = (r.topic, r.partition)
        if k not in seen:
            seen.add(k)
            order.append(k)
    rng = random.Random(hash(tuple(order)) & 0xFFFF)
    return ([rng.randint(0, 10_000) for _ in order],
            [rng.randint(0, 1 << 20) for _ in order])


# -- property: identical journal bytes --------------------------------------------------


@needs_native
@pytest.mark.parametrize("seed", range(40))
def test_format_journal_bit_identical(seed):
    """For randomized batches (multi-topic, tombstones, unicode keys/topics,
    headers, empty values) the native formatter and the Python twin produce
    identical journal lines, identical block bytes, identical group
    bookkeeping and identical assigned offsets."""
    rng = random.Random(seed)
    records = _rand_records(rng)
    bases, positions = _group_geometry(records)
    ts = 1_723_456_789.0 + seed / 7.0
    embed_max = rng.choice([0, 64, 256 << 10])  # incl. forcing "oversized"
    batch = ng.pack_records(records)
    assert batch is not None
    try:
        n_line, n_blocks, n_gouts, n_offsets = batch.format(
            bases, positions, ts, embed_max)
    finally:
        batch.close()
    p_line, p_blocks, p_gouts, p_offsets = ng.py_format_journal(
        records, bases, positions, ts, embed_max)
    assert n_line == p_line
    assert n_blocks == p_blocks
    assert n_gouts == p_gouts
    assert list(n_offsets) == list(p_offsets)


@needs_native
@pytest.mark.parametrize("seed", range(10))
def test_format_from_request_wire_matches(seed):
    """The same batch decoded from serialized TxnRequest bytes (the broker's
    zero-Python decode) formats to the same journal bytes as the packed and
    pure-Python paths."""
    from surge_tpu.log import log_service_pb2 as pb
    from surge_tpu.log.server import record_to_msg

    rng = random.Random(1000 + seed)
    records = _rand_records(rng)
    bases, positions = _group_geometry(records)
    ts = 1_700_000_000.25
    req = pb.TxnRequest(producer_token=9, op="commit", txn_seq=seed + 1,
                        records=[record_to_msg(r) for r in records])
    batch = ng.batch_from_request(req)
    assert batch is not None
    try:
        assert batch.nrecords == len(records)
        n_line, n_blocks, _, n_offsets = batch.format(
            bases, positions, ts, 256 << 10)
    finally:
        batch.close()
    p_line, p_blocks, _, p_offsets = ng.py_format_journal(
        records, bases, positions, ts, 256 << 10)
    assert n_line == p_line
    assert n_blocks == p_blocks
    assert list(n_offsets) == list(p_offsets)


# -- property: identical gate decisions -------------------------------------------------


@needs_native
def test_gate_decisions_bit_identical():
    """Exhaustive small grid + randomized fuzz: the native decision kernel
    and the Python twin classify every (seq, last, applied, fresh) the same
    way (accept / replay / reopen-absorption candidate / in-order wait /
    finalizing)."""
    for seq in range(0, 7):
        for last in range(0, 7):
            for applied in range(0, 7):
                for fresh in (False, True):
                    assert ng.decide(seq, last, applied, fresh) == \
                        ng.py_decide(seq, last, applied, fresh), \
                        (seq, last, applied, fresh)
    rng = random.Random(7)
    for _ in range(5000):
        seq = rng.randint(0, 1 << 48)
        last = rng.randint(0, 1 << 48)
        applied = rng.randint(0, 1 << 48)
        fresh = rng.random() < 0.5
        assert ng.decide(seq, last, applied, fresh) == \
            ng.py_decide(seq, last, applied, fresh)


# -- property: identical segment decode -------------------------------------------------


@needs_native
@pytest.mark.parametrize("seed", range(10))
def test_native_segment_decode_identical(seed):
    """The native record-index decoder (the resident plane's refresh-loop
    decode leg) reproduces the Python walk's LogRecords exactly."""
    rng = random.Random(2000 + seed)
    records = [r for r in _rand_records(rng) if True]
    # one block = one (topic, partition) run
    run = [LogRecord(topic="t", key=r.key, value=r.value, partition=0,
                     headers=r.headers, offset=i, timestamp=1.5 + i)
           for i, r in enumerate(records)]
    block = seg.encode_block(run, 0)
    assert ng.decode_enabled()
    native = seg.decode_block(block, 0, "t", 0)[0]
    ng._decode_enabled = False
    try:
        python = seg.decode_block(block, 0, "t", 0)[0]
    finally:
        ng._decode_enabled = None
    assert native == python == run


# -- whole-log round trip under a pinned clock ------------------------------------------


class _PinnedTime:
    """time-module stand-in for surge_tpu.log.file: pinned time() so the
    native and Python appends stamp identical record timestamps."""

    def __init__(self, t: float) -> None:
        self._t = t

    def time(self) -> float:
        return self._t

    def perf_counter(self) -> float:
        return time.perf_counter()


@needs_native
def test_filelog_artifacts_identical_native_vs_python(tmp_path, monkeypatch):
    """Drive the SAME commit sequence through a native-on and a native-off
    FileLog under a pinned clock: the journal bytes and (post-close) segment
    files must be byte-identical, and reads must agree record-for-record."""
    import surge_tpu.log.file as file_mod

    monkeypatch.setattr(file_mod, "time", _PinnedTime(1_722_000_000.5))
    rng = random.Random(99)
    batches = [_rand_records(rng, n_topics=2) for _ in range(12)]
    roots = {}
    for native in (True, False):
        root = tmp_path / ("native" if native else "python")
        log = FileLog(str(root), config=_cfg(native))
        for t in {r.topic for b in batches for r in b}:
            log.create_topic(TopicSpec(t, 3))
        prod = log.transactional_producer("p1")
        for b in batches:
            prod.begin()
            for r in b:
                prod.send(r)
            prod.commit()
        reads = {}
        for t in sorted({r.topic for b in batches for r in b}):
            for p in range(3):
                reads[(t, p)] = list(log.read(t, p))
        log.close()
        roots[native] = (root, reads)
    (nroot, nreads), (proot, preads) = roots[True], roots[False]
    assert nreads == preads
    njournal = (nroot / "commits.log").read_bytes()
    pjournal = (proot / "commits.log").read_bytes()
    assert njournal == pjournal
    ndata = sorted(os.listdir(nroot / "data"))
    assert ndata == sorted(os.listdir(proot / "data"))
    for name in ndata:
        assert (nroot / "data" / name).read_bytes() == \
            (proot / "data" / name).read_bytes(), name


@needs_native
def test_filelog_lazy_pending_served_and_recovered(tmp_path):
    """Lazy segment materialization: a commit's block may exist only in the
    pending tail + journal; reads serve it immediately, and a reopen that
    never saw the flush backfills the segment from the journal payload."""
    cfg = _cfg(True)
    root = str(tmp_path / "log")
    log = FileLog(root, config=cfg)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("p")
    prod.begin()
    for i in range(5):
        prod.send(LogRecord(topic="t", key=f"k{i}", value=b"v%d" % i))
    committed = prod.commit()
    assert [r.offset for r in committed] == list(range(5))
    got = list(log.read("t", 0))
    assert [r.key for r in got] == [f"k{i}" for i in range(5)]
    # simulate a crash that loses any unflushed pending tail: do NOT close()
    # — reopen from disk; the journal's embedded payloads must reconstruct
    with log._lock:
        for part in log._parts.values():
            part.pending.clear()
            part.pending_bytes = 0
    log2 = FileLog(root, config=cfg)
    got2 = list(log2.read("t", 0))
    assert [(r.key, r.value) for r in got2] == \
        [(f"k{i}", b"v%d" % i) for i in range(5)]
    log2.close()
    log.close()


# -- the exactly-once battery over both gates -------------------------------------------


def _mk_server(log, cfg, **kw):
    from surge_tpu.log.server import LogServer

    return LogServer(log, port=0, config=cfg, **kw)


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_out_of_order_seq_gating(tmp_path, native):
    """PR-3 battery, both gates: a pipelined seq arriving ahead of its
    predecessor waits at the in-order gate and answers retriable on timeout;
    the predecessor's arrival releases it."""
    from surge_tpu.log import log_service_pb2 as pb
    from surge_tpu.log.server import record_to_msg

    cfg = _cfg(native).with_overrides(
        {"surge.log.txn-inorder-timeout-ms": 300})
    log = FileLog(str(tmp_path / "log"), config=cfg)
    log.create_topic(TopicSpec("t", 1))
    server = _mk_server(log, cfg)
    try:
        opened = server.OpenProducer(
            pb.OpenProducerRequest(transactional_id="p"), None)
        tok = opened.producer_token

        def txn(seqno, key):
            return pb.TxnRequest(
                producer_token=tok, op="commit", txn_seq=seqno,
                records=[record_to_msg(LogRecord(topic="t", key=key,
                                                 value=key.encode()))])

        # seq 2 with no seq 1: retriable after the gate timeout
        r2 = server.Transact(txn(2, "b"), None)
        assert not r2.ok and r2.error_kind == "retriable"
        r1 = server.Transact(txn(1, "a"), None)
        assert r1.ok
        r2b = server.Transact(txn(2, "b"), None)
        assert r2b.ok
        assert [m.offset for m in r1.records] == [0]
        assert [m.offset for m in r2b.records] == [1]
        # the native path must actually have engaged when enabled+built
        if native and ng.available():
            reg = server.broker_metrics.registry.get_metrics()
            assert reg["surge.log.native.gate-batches"] >= 2
    finally:
        server.stop()
        log.close()


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_dedup_replay_and_restart(tmp_path, native):
    """PR-3/4 battery, both gates: a replayed seq answers from the dedup
    window without re-appending — including after a broker restart (locator
    rebuild from __txn_state)."""
    from surge_tpu.log import log_service_pb2 as pb
    from surge_tpu.log.server import record_to_msg

    cfg = _cfg(native)
    root = str(tmp_path / "log")
    log = FileLog(root, config=cfg)
    log.create_topic(TopicSpec("t", 1))
    server = _mk_server(log, cfg)
    tok = server.OpenProducer(
        pb.OpenProducerRequest(transactional_id="p"), None).producer_token

    def txn(seqno, key):
        return pb.TxnRequest(
            producer_token=tok, op="commit", txn_seq=seqno,
            records=[record_to_msg(LogRecord(topic="t", key=key,
                                             value=key.encode()))])

    r1 = server.Transact(txn(1, "a"), None)
    r2 = server.Transact(txn(2, "b"), None)
    assert r1.ok and r2.ok
    # same-life replay: answered from cache, nothing re-appends
    again = server.Transact(txn(1, "a"), None)
    assert again.ok
    assert [m.offset for m in again.records] == [m.offset
                                                for m in r1.records]
    assert log.end_offset("t", 0) == 2
    # replayed seq with a DIFFERENT payload: refused, never appended
    bad = server.Transact(txn(2, "DIFFERENT"), None)
    assert not bad.ok and bad.error_kind == "state"
    server.stop()
    log.close()
    # restart: dedup survives via __txn_state; replaying seq 2 re-reads the
    # committed records instead of appending twice
    log2 = FileLog(root, config=cfg)
    server2 = _mk_server(log2, cfg)
    try:
        opened = server2.OpenProducer(
            pb.OpenProducerRequest(transactional_id="p"), None)
        assert opened.last_txn_seq == 2
        tok = opened.producer_token
        replay = server2.Transact(txn(2, "b"), None)
        assert replay.ok
        assert [m.key for m in replay.records] == ["b"]
        assert log2.end_offset("t", 0) == 2
    finally:
        server2.stop()
        log2.close()


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_torn_journal_write_recovery(tmp_path, native):
    """PR-3 battery, both gates: a torn journal line (crash mid-write) is
    discarded on recovery; everything before it survives. With faults armed
    the native path routes journal writes through the direct (tearable)
    leg, preserving the crash semantics."""
    from surge_tpu.testing.faults import (FaultPlane, FaultRule,
                                          SimulatedCrash)

    cfg = _cfg(native)
    root = str(tmp_path / "log")
    plane = FaultPlane(seed=3)
    log = FileLog(root, config=cfg, faults=plane)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("p")
    prod.begin()
    prod.send(LogRecord(topic="t", key="a", value=b"1"))
    prod.commit()
    plane.arm([FaultRule(site="journal.write", action="torn", fraction=0.5)])
    prod.begin()
    prod.send(LogRecord(topic="t", key="b", value=b"2"))
    with pytest.raises(SimulatedCrash):
        prod.commit()
    # recovery: the torn line is truncated away; the first commit survives
    log2 = FileLog(root, config=cfg)
    got = list(log2.read("t", 0))
    assert [(r.key, r.value) for r in got] == [("a", b"1")]
    log2.close()
    log.close()


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_reopen_alias_window(tmp_path, native):
    """PR-4 battery, both gates: a producer reopened over applied-but-unacked
    seqs payload-matches its first transacts against the in-limbo window
    instead of appending the same batch twice."""
    from surge_tpu.log import log_service_pb2 as pb
    from surge_tpu.log.server import record_to_msg

    cfg = _cfg(native)
    log = FileLog(str(tmp_path / "log"), config=cfg)
    log.create_topic(TopicSpec("t", 1))
    server = _mk_server(log, cfg)
    try:
        tok = server.OpenProducer(
            pb.OpenProducerRequest(transactional_id="p"), None).producer_token

        def txn(tok_, seqno, key):
            return pb.TxnRequest(
                producer_token=tok_, op="commit", txn_seq=seqno,
                records=[record_to_msg(LogRecord(topic="t", key=key,
                                                 value=key.encode(),
                                                 headers={"h": key}))])

        assert server.Transact(txn(tok, 1, "a"), None).ok
        # make seq 1 look applied-but-unacked at the next open: push
        # applied_seq past last_seq the way an in-flight commit would
        state = server._producers[tok]
        state.dedup.applied_seq = 2
        # craft the in-limbo batch seq 2 would have carried
        committed = [LogRecord(topic="t", key="x", value=b"x",
                               headers={"h": "x"}, offset=1,
                               timestamp=1.0)]
        from surge_tpu.log.server import _ReplItem

        item = _ReplItem([], committed, "p", 2)
        server._repl_pending[("p", 2)] = item
        opened = server.OpenProducer(
            pb.OpenProducerRequest(transactional_id="p"), None)
        # numbering starts past the in-limbo seq; the alias window is armed
        assert opened.last_txn_seq == 2
        tok2 = opened.producer_token
        state2 = server._producers[tok2]
        assert state2.alias_budget == 1
        assert (state2.alias_floor, state2.alias_ceiling) == (1, 2)
        # the reopened producer's first transact IS the verbatim retry of
        # the in-limbo batch, under a NEW seq: it must JOIN, not append.
        # Resolve the item as the replication worker would, then verify the
        # join answered from it.
        import threading

        def finalize():
            time.sleep(0.2)
            reply = pb.TxnReply(ok=True,
                                records=[record_to_msg(committed[0])])
            with state2.lock:
                server._ack_seq("p", state2.dedup, 2, reply, committed)
                server._repl_pending.pop(("p", 2), None)
                item.done.set()
                state2.cond.notify_all()

        t = threading.Thread(target=finalize)
        t.start()
        retry = pb.TxnRequest(
            producer_token=tok2, op="commit", txn_seq=3,
            records=[record_to_msg(LogRecord(topic="t", key="x", value=b"x",
                                             headers={"h": "x"}))])
        r = server.Transact(retry, None)
        t.join()
        assert r.ok
        assert [m.key for m in r.records] == ["x"]
        assert log.end_offset("t", 0) == 1  # nothing appended twice
    finally:
        server.stop()
        log.close()


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_engine_end_to_end_both_gates(tmp_path, native):
    """The full command path (engine -> publisher -> FileLog) under each
    gate: commands land exactly once and reads agree."""
    from surge_tpu import (CommandSuccess, SurgeCommandBusinessLogic,
                           create_engine)
    from surge_tpu.models import counter

    cfg = _cfg(native)

    async def scenario():
        log = FileLog(str(tmp_path / "log"), config=cfg)
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=log, config=cfg)
        await engine.start()
        try:
            for i in range(20):
                r = await engine.aggregate_for("agg-1").send_command(
                    counter.Increment("agg-1"))
                assert isinstance(r, CommandSuccess)
            assert r.state.count == 20
        finally:
            await engine.stop()
            log.close()

    asyncio.run(scenario())


@pytest.mark.parametrize("native", NATIVE_MODES)
def test_empty_commit_writes_nothing(tmp_path, native):
    """An empty transaction must write NO journal line on either gate (the
    native path once staged a phantom '{"parts": [], "blk": []}' entry that
    also wedged the rotation quiesce check)."""
    cfg = _cfg(native)
    log = FileLog(str(tmp_path / "log"), config=cfg)
    log.create_topic(TopicSpec("t", 1))
    prod = log.transactional_producer("p")
    prod.begin()
    committed = prod.commit()
    assert list(committed) == []
    prod.begin()
    handle = prod.commit_pipelined()
    handle.future.result(timeout=5)
    with log._gc_cv:
        assert log._gc_written == log._gc_durable
    log.close()
    assert (tmp_path / "log" / "commits.log").read_bytes() == b""
