"""Native C++ state store: build-on-demand, parity with the in-memory store.

The native backend replaces the reference's RocksDB persistence plugin
(SurgeKafkaStreamsPersistencePlugin.scala:12-51); same KeyValueStore contract, same
plugin-loader seam (``create_store("native")``).
"""

import os
import random
import shutil
import string
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native_store_cls():
    lib = os.path.join(ROOT, "csrc", "build", "libsurge_store.so")
    src_mtime = max(
        os.path.getmtime(os.path.join(ROOT, "csrc", f))
        for f in ("store.cc", "build.sh"))
    stale = os.path.exists(lib) and os.path.getmtime(lib) < src_mtime
    if not os.path.exists(lib) or stale:
        if shutil.which("g++") is None:
            pytest.skip("g++ unavailable and native library not prebuilt")
        subprocess.run([os.path.join(ROOT, "csrc", "build.sh")], check=True)
    from surge_tpu.store.native import NativeKeyValueStore, native_available

    assert native_available()
    return NativeKeyValueStore


def test_basic_ops(native_store_cls):
    s = native_store_cls()
    assert s.get("missing") is None
    s.put("a", b"1")
    s.put("a", b"2")  # overwrite
    assert s.get("a") == b"2"
    assert s.approximate_num_entries() == 1
    s.delete("a")
    assert s.get("a") is None
    s.delete("a")  # idempotent
    assert s.approximate_num_entries() == 0


def test_binary_values_and_empty(native_store_cls):
    s = native_store_cls()
    blob = bytes(range(256)) * 3  # embedded NULs must survive
    s.put("blob", blob)
    assert s.get("blob") == blob
    s.put("empty", b"")
    assert s.get("empty") == b""


def test_parity_with_memory_store_randomized(native_store_cls):
    from surge_tpu.store.kv import InMemoryKeyValueStore

    rng = random.Random(7)
    native, mem = native_store_cls(), InMemoryKeyValueStore()
    keys = ["".join(rng.choices(string.ascii_lowercase, k=6)) for _ in range(400)]
    for _ in range(5000):
        op = rng.random()
        k = rng.choice(keys)
        if op < 0.6:
            v = rng.randbytes(rng.randrange(0, 64))
            native.put(k, v), mem.put(k, v)
        elif op < 0.8:
            native.delete(k), mem.delete(k)
        else:
            assert native.get(k) == mem.get(k)
    assert native.approximate_num_entries() == mem.approximate_num_entries()
    assert list(native.all_items()) == list(mem.all_items())
    assert list(native.range_items("a", "m")) == list(mem.range_items("a", "m"))


def test_grow_through_resizes(native_store_cls):
    s = native_store_cls()
    n = 20_000  # forces several table grows past the 1024 initial capacity
    for i in range(n):
        s.put(f"k{i}", str(i).encode())
    assert s.approximate_num_entries() == n
    for i in range(0, n, 997):
        assert s.get(f"k{i}") == str(i).encode()
    for i in range(0, n, 2):
        s.delete(f"k{i}")
    assert s.approximate_num_entries() == n // 2
    # tombstone-heavy table still inserts and finds correctly
    for i in range(1, n, 2):
        assert s.get(f"k{i}") == str(i).encode()


def test_create_store_plugin_seam(native_store_cls):
    from surge_tpu.store.kv import create_store

    s = create_store("native")
    s.put("x", b"y")
    assert s.get("x") == b"y"
