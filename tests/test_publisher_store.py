"""Publisher FSM + state-store indexer + bulk restore.

The KafkaProducerActorImplSpec / AggregateStateStoreKafkaStreamsSpec analogs
(SURVEY.md §4): init gating on store lag, one-transaction flush batching,
in-flight tracking behind is_aggregate_state_current, zombie fencing with
restart-or-shutdown, request dedup, and the cpu-vs-tpu byte-identical cold rebuild."""

import asyncio

import pytest

from surge_tpu.config import default_config
from surge_tpu.engine.publisher import (
    PartitionPublisher,
    PublishFailedError,
    PublisherNotReadyError,
)
from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
from surge_tpu.models import counter
from surge_tpu.store import (
    InMemoryKeyValueStore,
    StateStoreIndexer,
    restore_from_events,
    restore_from_state_topic,
)

CFG = default_config().with_overrides({
    "surge.producer.flush-interval-ms": 5,
    "surge.producer.ktable-check-interval-ms": 5,
    "surge.state-store.commit-interval-ms": 20,
})


def make_log():
    log = InMemoryLog()
    log.create_topic(TopicSpec("events", 1))
    log.create_topic(TopicSpec("state", 1, compacted=True))
    return log


def state_rec(agg, value):
    return LogRecord(topic="state", key=agg, value=value, partition=0)


def event_rec(agg, value):
    return LogRecord(topic="events", key=agg, value=value, partition=0)


async def start_stack(log, **pub_kwargs):
    indexer = StateStoreIndexer(log, "state", config=CFG)
    await indexer.start()
    pub = PartitionPublisher(log, "state", "events", 0, indexer, config=CFG, **pub_kwargs)
    await pub.start()
    await pub.wait_ready(5.0)
    return indexer, pub


def test_init_commits_flush_record_and_waits_for_lag_zero():
    async def scenario():
        log = make_log()
        # pre-existing state records the indexer must chew through before ready
        seed = log.transactional_producer("seed")
        seed.begin()
        for i in range(20):
            seed.send(state_rec(f"a{i}", b"s"))
        seed.commit()

        indexer = StateStoreIndexer(log, "state", config=CFG)
        pub = PartitionPublisher(log, "state", "events", 0, indexer, config=CFG)
        start = asyncio.ensure_future(pub.start())
        await asyncio.sleep(0.05)
        assert pub.state == "waiting_for_ktable"  # indexer not running yet
        await indexer.start()
        await start
        await pub.wait_ready(5.0)
        assert pub.state == "processing"
        # flush record landed on the state topic but is ignored by the store
        assert log.end_offset("state", 0) == 21
        assert indexer.store.approximate_num_entries() == 20
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_flush_batches_multiple_publishes_into_one_transaction():
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)
        base_state = log.end_offset("state", 0)

        await asyncio.gather(
            pub.publish("a", [event_rec("a", b"e1"), state_rec("a", b"sa")], "r1"),
            pub.publish("b", [event_rec("b", b"e2"), state_rec("b", b"sb")], "r2"),
        )
        assert pub.stats.flushes == 1  # both rode one transaction
        assert pub.stats.records_published == 4
        assert [r.value for r in log.read("events", 0)] == [b"e1", b"e2"]
        assert log.end_offset("state", 0) == base_state + 2
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_is_aggregate_state_current_tracks_indexing_gap():
    async def scenario():
        log = make_log()
        indexer = StateStoreIndexer(log, "state", config=CFG)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer, config=CFG)
        await pub.start()
        await pub.wait_ready(5.0)
        await indexer.stop()  # freeze indexing to observe the in-flight window

        await pub.publish("agg", [state_rec("agg", b"s1")], "r1")
        pub._refresh_watermark()
        assert not pub.is_aggregate_state_current("agg")  # published, not yet indexed
        assert pub.is_aggregate_state_current("other")

        await indexer.start()
        await asyncio.sleep(0.05)
        pub._refresh_watermark()
        assert pub.is_aggregate_state_current("agg")
        assert indexer.get_aggregate_bytes("agg") == b"s1"
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_zombie_fenced_batch_fails_and_shuts_down_when_not_owner():
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, still_owner=lambda: False)
        events_before = log.end_offset("events", 0)

        # an impostor takes over the transactional id (new process owns the partition)
        log.transactional_producer(pub.transactional_id)
        with pytest.raises((PublishFailedError, PublisherNotReadyError)):
            # ownership is gone: the publisher shuts down and the held
            # batch's waiter is released with the shutdown error
            await pub.publish("a", [event_rec("a", b"zombie")], "r1")
        assert pub.stats.fences == 1
        assert pub.state == "stopped"  # not owner -> shutdown
        assert log.end_offset("events", 0) == events_before  # nothing half-written
        await indexer.stop()

    asyncio.run(scenario())


def test_fenced_but_still_owner_reinitializes_and_serves_again():
    """Fencing while still the owner is now TRANSPARENT to the caller: the
    in-flight batch rides the verbatim-retry stash across the re-init (new
    epoch) and commits exactly once — no error surfaces, nothing doubles."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log, still_owner=lambda: True)

        log.transactional_producer(pub.transactional_id)  # fence it once
        await pub.publish("a", [event_rec("a", b"held")], "r1")
        await pub.wait_ready(5.0)  # re-initialized with a fresh epoch
        assert pub.stats.reinitializations == 1
        assert pub.state == "processing"
        assert [r.value for r in log.read("events", 0)] == [b"held"]

        await pub.publish("a", [event_rec("a", b"next")], "r2")
        assert [r.value for r in log.read("events", 0)] == [b"held", b"next"]
        # a late same-request_id retry of the held batch is absorbed
        await pub.publish("a", [event_rec("a", b"held")], "r1")
        assert [r.value for r in log.read("events", 0)] == [b"held", b"next"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_request_id_dedup_suppresses_double_write():
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)
        await pub.publish("a", [event_rec("a", b"e1")], "req-1")
        await pub.publish("a", [event_rec("a", b"e1")], "req-1")  # retry after success
        assert pub.stats.dedup_hits == 1
        assert [r.value for r in log.read("events", 0)] == [b"e1"]
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_indexer_tombstones_and_wipe_on_start():
    async def scenario():
        log = make_log()
        p = log.transactional_producer("seed")
        p.begin()
        p.send(state_rec("a", b"s1"))
        p.send(state_rec("b", b"s2"))
        p.send(state_rec("a", None))  # tombstone deletes a
        p.commit()

        indexer = StateStoreIndexer(log, "state", config=CFG)
        await indexer.start()
        await asyncio.sleep(0.05)
        assert indexer.get_aggregate_bytes("a") is None
        assert indexer.get_aggregate_bytes("b") == b"s2"
        assert indexer.indexed_watermark("state", 0) == 3
        assert indexer.total_lag() == 0
        await indexer.stop()

        wipe_cfg = CFG.with_overrides({"surge.state-store.wipe-state-on-start": True})
        indexer2 = StateStoreIndexer(log, "state", store=indexer.store, config=wipe_cfg)
        await indexer2.start()  # wipe clears, then re-indexes from offset 0
        await asyncio.sleep(0.05)
        assert indexer2.get_aggregate_bytes("b") == b"s2"
        await indexer2.stop()

    asyncio.run(scenario())


# -- bulk restore -----------------------------------------------------------------------


def _seed_counter_events(log, num_aggregates=40):
    """Write counter event histories to the events topic via the real model+formats."""
    model = counter.CounterModel()
    fmt = counter.event_formatting()
    p = log.transactional_producer("seed")
    expected = {}
    for i in range(num_aggregates):
        agg = f"agg{i:03d}"
        state = None
        cmds = ([counter.Increment(agg)] * (i % 7 + 1)
                + [counter.Decrement(agg)] * (i % 3)
                + [counter.CreateNoOpEvent(agg)] * (i % 2))
        p.begin()
        for cmd in cmds:
            events = model.process_command(state, cmd)
            for ev in events:
                msg = fmt.write_event(ev)
                p.send(LogRecord(topic="events", key=msg.key, value=msg.value, partition=0))
                state = model.handle_event(state, ev)
        p.commit()
        expected[agg] = state
    return expected


def test_restore_from_events_cpu_and_tpu_byte_identical():
    log = make_log()
    expected = _seed_counter_events(log)
    model = counter.CounterModel()
    evt_fmt = counter.event_formatting()
    state_fmt = counter.state_formatting()

    def deserialize_event(data: bytes):
        from surge_tpu.serialization import SerializedMessage

        return evt_fmt.read_event(SerializedMessage(key="", value=data))

    def serialize_state(agg_id: str, state) -> bytes:
        return state_fmt.write_state(state).value

    kwargs = dict(deserialize_event=deserialize_event, serialize_state=serialize_state,
                  model=model, replay_spec=counter.make_replay_spec())
    cpu_store, tpu_store = InMemoryKeyValueStore(), InMemoryKeyValueStore()
    r_cpu = restore_from_events(
        log, "events", cpu_store,
        config=default_config().with_overrides({"surge.replay.backend": "cpu"}), **kwargs)
    r_tpu = restore_from_events(
        log, "events", tpu_store,
        config=default_config().with_overrides({"surge.replay.backend": "tpu",
                                                "surge.replay.batch-size": 16,
                                                "surge.replay.time-chunk": 8}), **kwargs)

    assert r_cpu.backend == "cpu" and r_tpu.backend == "tpu"
    assert r_cpu.num_aggregates == r_tpu.num_aggregates == len(expected)
    assert list(cpu_store.all_items()) == list(tpu_store.all_items())  # byte-identical
    # and both match the live fold the seeding ran
    for agg, state in expected.items():
        assert cpu_store.get(agg) == state_fmt.write_state(state).value
    assert r_cpu.watermarks == r_tpu.watermarks == {0: log.end_offset("events", 0)}


def test_restore_from_state_topic_latest_snapshot_wins():
    log = make_log()
    p = log.transactional_producer("seed")
    p.begin()
    p.send(state_rec("a", b"old"))
    p.send(state_rec("a", b"new"))
    p.send(state_rec("b", b"bv"))
    p.commit()
    store = InMemoryKeyValueStore()
    res = restore_from_state_topic(log, "state", store)
    assert store.get("a") == b"new" and store.get("b") == b"bv"
    assert res.watermarks == {0: 3}

    # priming an indexer with restore watermarks means it does not re-apply history
    async def scenario():
        indexer = StateStoreIndexer(log, "state", store=store, config=CFG)
        indexer.prime(res.watermarks)
        await indexer.start()
        await asyncio.sleep(0.02)
        assert indexer.indexed_watermark("state", 0) == 3
        await indexer.stop()

    asyncio.run(scenario())


def test_restore_from_events_bank_account_vocab_paths_identical():
    """cpu (domain fold) vs tpu (vocab-encoded tensor fold + decode_state) must agree."""
    from surge_tpu.models import bank_account as ba
    from surge_tpu.serialization import SerializedMessage

    log = make_log()
    model = ba.BankAccountModel()
    evt_fmt = ba.event_formatting()
    state_fmt = ba.state_formatting()
    p = log.transactional_producer("seed")
    for i in range(17):
        acct = f"acct{i:02d}"
        state = None
        cmds = [ba.CreateAccount(acct, f"o{i}", "pw", 100.0)]
        cmds += [ba.CreditAccount(acct, 0.25 * (j + 1)) for j in range(i % 4)]
        p.begin()
        for cmd in cmds:
            for ev in model.process_command(state, cmd):
                m = evt_fmt.write_event(ev)
                p.send(LogRecord(topic="events", key=m.key, value=m.value, partition=0))
                state = model.handle_event(state, ev)
        p.commit()

    vocab = ba.Vocab()
    kwargs = dict(
        deserialize_event=lambda b: evt_fmt.read_event(SerializedMessage(key="", value=b)),
        serialize_state=lambda a, st: state_fmt.write_state(st).value,
        model=model, replay_spec=ba.make_replay_spec(),
        encode_event=lambda e: ba.encode_event(vocab, e),
        decode_state=lambda a, rec: ba.decode_state(vocab, a, rec))
    s_cpu, s_tpu = InMemoryKeyValueStore(), InMemoryKeyValueStore()
    restore_from_events(log, "events", s_cpu,
                        config=default_config().with_overrides({"surge.replay.backend": "cpu"}),
                        **kwargs)
    restore_from_events(log, "events", s_tpu,
                        config=default_config().with_overrides({"surge.replay.backend": "tpu",
                                                                "surge.replay.batch-size": 8,
                                                                "surge.replay.time-chunk": 4}),
                        **kwargs)
    assert list(s_cpu.all_items()) == list(s_tpu.all_items())
    assert s_cpu.approximate_num_entries() == 17


def test_cancelled_publish_withdrawn_no_double_commit():
    """A publish whose caller times out must be withdrawn from the pending batch so the
    same-request_id retry does not commit the records twice (review r2 finding)."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)
        task = asyncio.ensure_future(
            pub.publish("a", [event_rec("a", b"e1")], "req-1"))
        await asyncio.sleep(0)  # queued, not yet flushed
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await pub.publish("a", [event_rec("a", b"e1")], "req-1")  # the retry
        await asyncio.sleep(0.05)
        assert [r.value for r in log.read("events", 0)] == [b"e1"]  # exactly once
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_retry_joins_in_flight_commit_instead_of_requeueing():
    """A retry arriving while its batch is mid-commit must join the commit outcome,
    not enqueue a second copy (review r2: double-commit via slow transaction)."""
    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)
        outcome = asyncio.get_running_loop().create_future()
        pub._committing["req-1"] = outcome  # simulate: batch with req-1 committing now

        join = asyncio.ensure_future(
            pub.publish("a", [event_rec("a", b"dup")], "req-1"))
        await asyncio.sleep(0.02)
        assert not join.done()          # waiting on the in-flight commit
        assert pub._pending == []       # nothing re-queued
        outcome.set_result(None)        # the original commit lands
        await join                      # retry resolves successfully
        assert log.end_offset("events", 0) == 0  # and wrote nothing new

        # failure outcome propagates to the joiner as PublishFailedError
        outcome2 = asyncio.get_running_loop().create_future()
        pub._committing["req-2"] = outcome2
        join2 = asyncio.ensure_future(
            pub.publish("b", [event_rec("b", b"x")], "req-2"))
        await asyncio.sleep(0)
        outcome2.set_result(RuntimeError("commit failed"))
        with pytest.raises(PublishFailedError):
            await join2
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_non_transactional_publisher_mode():
    """surge.producer.enable-transactions=false: every record appends individually
    (no atomicity) but fencing and read-your-writes gating still hold."""
    import asyncio

    from surge_tpu import SurgeCommandBusinessLogic, create_engine, default_config
    from surge_tpu.models import counter

    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 10,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 1,
        "surge.producer.enable-transactions": False,
    })

    async def scenario():
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            config=cfg)
        await engine.start()
        for i in range(5):
            r = await engine.aggregate_for("nt-1").send_command(
                counter.Increment("nt-1"))
        assert r.state.count == 5
        st = await engine.aggregate_for("nt-1").get_state()
        assert st.count == 5
        # events + state really landed on the log
        assert engine.log.end_offset("counter-events", 0) == 5
        await engine.stop()

    asyncio.run(scenario())


def test_non_transactional_mid_batch_failure_resumes_exactly_once():
    """Regression (r2 advisor): a mid-batch failure in non-transactional mode must
    not re-append already-written records on the same-request_id retry, and the
    retry's success bookkeeping must stay offset-aligned with every request."""
    cfg = CFG.with_overrides({"surge.producer.enable-transactions": False})

    async def scenario():
        log = make_log()
        indexer = StateStoreIndexer(log, "state", config=cfg)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer, config=cfg)
        await pub.start()
        await pub.wait_ready(5.0)

        class Boom(RuntimeError):
            pass

        real_send = pub._producer.send_immediate
        calls = {"n": 0}

        def flaky_send(record):
            calls["n"] += 1
            if calls["n"] == 4:  # r1 fully appended, r2 half appended, r3 untouched
                raise Boom()
            return real_send(record)

        pub._producer.send_immediate = flaky_send
        t1 = asyncio.ensure_future(
            pub.publish("a", [event_rec("a", b"e-a"), state_rec("a", b"s-a")], "r1"))
        t2 = asyncio.ensure_future(
            pub.publish("b", [event_rec("b", b"e-b"), state_rec("b", b"s-b")], "r2"))
        t3 = asyncio.ensure_future(
            pub.publish("c", [event_rec("c", b"e-c"), state_rec("c", b"s-c")], "r3"))
        await asyncio.sleep(0)
        await pub.flush_now()
        for t in (t1, t2, t3):
            with pytest.raises(PublishFailedError):
                await t
        pub._producer.send_immediate = real_send

        # entity retry ladder: same request ids, same records. The indexer is
        # frozen first so the in-flight offsets below can't be cleared by a
        # watermark that races past them (group commits ack fast now).
        await indexer.stop()
        r1 = asyncio.ensure_future(
            pub.publish("a", [event_rec("a", b"e-a"), state_rec("a", b"s-a")], "r1"))
        r2 = asyncio.ensure_future(
            pub.publish("b", [event_rec("b", b"e-b"), state_rec("b", b"s-b")], "r2"))
        r3 = asyncio.ensure_future(
            pub.publish("c", [event_rec("c", b"e-c"), state_rec("c", b"s-c")], "r3"))
        await asyncio.sleep(0)
        await pub.flush_now()
        await asyncio.gather(r1, r2, r3)

        # exactly-once on the log: no duplicated events despite the retry
        assert [r.value for r in log.read("events", 0)] == [b"e-a", b"e-b", b"e-c"]
        state_values = [r.value for r in log.read("state", 0) if r.value != b""]
        assert state_values == [b"s-a", b"s-b", b"s-c"]
        assert not pub._partial_records  # resume state fully drained

        # offset alignment: every aggregate's in-flight offset is its real state
        # offset, and the watermark clears them once indexed
        for agg in ("a", "b", "c"):
            rec = next(r for r in log.read("state", 0) if r.key == agg)
            off = pub._in_flight.get(agg)
            if off is not None:
                assert off == rec.offset
            else:
                # entry already cleared: only legal when the indexed
                # watermark passed the record (e.g. "a", whose state record
                # landed on the FIRST attempt and was indexed before the
                # indexer froze)
                assert pub._watermark > rec.offset, agg
        await indexer.start()
        await asyncio.sleep(0.1)  # let the indexer catch up
        pub._refresh_watermark()
        for agg in ("a", "b", "c"):
            assert pub.is_aggregate_state_current(agg), agg

        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_background_loops_survive_internal_bugs():
    """The flush loop, progress loop, and indexer partition loops must never
    die silently on an unexpected exception (the partition would stall with
    no root cause): one poisoned iteration logs and the next works."""
    import unittest.mock as mock

    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)

        # 1. flush loop: one publish blows up unexpectedly -> the batch's
        # waiter gets an error eventually (or times out), but the NEXT tick
        # still publishes
        real = PartitionPublisher._publish_batch
        calls = {"n": 0}

        async def boom(self, batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("bookkeeping bug")
            return await real(self, batch)

        with mock.patch.object(PartitionPublisher, "_publish_batch", boom):
            t1 = asyncio.ensure_future(pub.publish(
                "a", [event_rec("a", b"e1"), state_rec("a", b"s1")], "r1"))
            # first tick eats the bug; the loop must survive it
            await asyncio.sleep(0.15)
            assert pub._flush_task.running
            t2 = asyncio.ensure_future(pub.publish(
                "a", [event_rec("a", b"e2"), state_rec("a", b"s2")], "r2"))
            await asyncio.wait_for(t2, 5.0)
        assert calls["n"] >= 2
        end_after = log.end_offset("state", 0)
        assert end_after >= 2  # init flush record + the second batch
        # the poisoned batch's waiter is FAILED (never left hanging): the
        # entity ladder retries with the same request_id
        with pytest.raises(Exception):
            await asyncio.wait_for(t1, 2.0)

        # 2. progress loop: watermark refresh raising must not kill it
        with mock.patch.object(type(indexer), "indexed_watermark",
                               side_effect=RuntimeError("store glitch")):
            await asyncio.sleep(0.05)
        assert pub._progress_task.running

        # 3. indexer loop: transient read failures retry instead of dying
        real_read = log.read
        fails = {"n": 0}

        def flaky_read(topic, partition, *a, **k):
            if fails["n"] < 2:
                fails["n"] += 1
                raise ConnectionError("broker briefly unreachable")
            return real_read(topic, partition, *a, **k)

        with mock.patch.object(log, "read", side_effect=flaky_read):
            prod = log.transactional_producer("seed")
            prod.begin()
            prod.send(state_rec("z", b"zv"))
            prod.commit()
            for _ in range(100):
                if indexer.store.get("z") == b"zv":
                    break
                await asyncio.sleep(0.05)
        assert indexer.store.get("z") == b"zv"
        assert fails["n"] == 2

        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


# -- group-commit failure semantics (the lanes/pipelining contract) ----------------------


def test_verbatim_retry_batch_replays_before_new_pendings():
    """An unknown-outcome batch must retry VERBATIM (same payload) before any
    new pending commits: its records land AHEAD of later publishes on the
    log, exactly once, and the original waiters resolve on the retry."""
    import unittest.mock as mock

    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)

        real_commit = pub._producer.commit
        boom = {"armed": True}

        def flaky_commit():
            if boom["armed"]:
                raise ConnectionError("transport died mid-commit")
            return real_commit()

        with mock.patch.object(pub._producer, "commit", flaky_commit):
            t1 = asyncio.ensure_future(
                pub.publish("a", [event_rec("a", b"first")], "r1"))
            for _ in range(100):
                await asyncio.sleep(0.005)
                if pub._retry_batches:
                    break
            assert pub._retry_batches, "batch should be stashed for retry"
            assert not t1.done()  # waiter rides the verbatim retry
            t2 = asyncio.ensure_future(
                pub.publish("b", [event_rec("b", b"second")], "r2"))
            await asyncio.sleep(0.02)
            boom["armed"] = False  # transport heals
            await asyncio.gather(t1, t2)
        # retry-before-new-pendings: first's record precedes second's
        assert [r.value for r in log.read("events", 0)] == [b"first", b"second"]
        assert not pub._retry_batches
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_caller_timeout_rejoins_in_limbo_batch_exactly_once():
    """A caller that times out while its batch is IN LIMBO and retries with
    the same request_id must join the batch's eventual outcome — never queue
    a second copy (double-append) nor inherit the old cancellation."""
    import unittest.mock as mock

    async def scenario():
        log = make_log()
        indexer, pub = await start_stack(log)

        real_commit = pub._producer.commit
        fail = {"n": 2}  # fail the first attempt AND the first verbatim retry

        def flaky_commit():
            if fail["n"] > 0:
                fail["n"] -= 1
                raise ConnectionError("transport flapping")
            return real_commit()

        with mock.patch.object(pub._producer, "commit", flaky_commit):
            t1 = asyncio.ensure_future(
                pub.publish("a", [event_rec("a", b"e1")], "req-1"))
            for _ in range(200):  # until the failed batch is stashed
                await asyncio.sleep(0.005)
                if pub._retry_batches:
                    break
            assert pub._retry_batches
            t1.cancel()  # the caller's publish timeout fires
            try:
                await t1
            except asyncio.CancelledError:
                pass
            # entity ladder retries the SAME request while the batch is in limbo
            rejoin = asyncio.ensure_future(
                pub.publish("a", [event_rec("a", b"e1")], "req-1"))
            await asyncio.wait_for(rejoin, 5.0)
        assert pub.stats.dedup_hits == 1
        assert [r.value for r in log.read("events", 0)] == [b"e1"]  # exactly once
        await pub.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_lane_independence_one_lanes_broker_error_spares_the_other():
    """Per-partition lanes fail independently: a broker error on one
    partition's lane must not fail (or block) another lane's batch."""
    import unittest.mock as mock

    async def scenario():
        log = InMemoryLog()
        log.create_topic(TopicSpec("events", 2))
        log.create_topic(TopicSpec("state", 2, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=CFG)
        await indexer.start()
        pub0 = PartitionPublisher(log, "state", "events", 0, indexer, config=CFG)
        pub1 = PartitionPublisher(log, "state", "events", 1, indexer, config=CFG)
        await pub0.start()
        await pub1.start()
        await pub0.wait_ready(5.0)
        await pub1.wait_ready(5.0)

        def dead_commit():
            raise ConnectionError("broker gone for partition 0")

        with mock.patch.object(pub0._producer, "commit", dead_commit):
            t0 = asyncio.ensure_future(pub0.publish(
                "a", [LogRecord(topic="events", key="a", value=b"x0",
                                partition=0)], "r0"))
            # lane 1 commits happily while lane 0 churns its retry ladder
            for i in range(3):
                await asyncio.wait_for(pub1.publish(
                    f"b{i}", [LogRecord(topic="events", key=f"b{i}",
                                        value=b"y%d" % i, partition=1)],
                    f"r1-{i}"), 5.0)
        assert [r.value for r in log.read("events", 1)] == [b"y0", b"y1", b"y2"]
        assert log.read("events", 0) == []  # nothing half-written on lane 0
        assert not t0.done()  # still riding lane 0's verbatim retry
        # broker heals: the in-limbo batch commits exactly once
        await asyncio.wait_for(t0, 5.0)
        assert [r.value for r in log.read("events", 0)] == [b"x0"]
        await pub0.stop()
        await pub1.stop()
        await indexer.stop()

    asyncio.run(scenario())


def test_fencing_mid_lane_with_pipelined_filelog_commits(tmp_path):
    """FileLog lanes are pipeline-capable (group-sync rounds): fencing the
    producer between pipelined dispatches must stash the affected batch,
    re-initialize, and commit exactly once — no loss, no double-apply."""
    from surge_tpu.log.file import FileLog

    async def scenario():
        log = FileLog(str(tmp_path / "log"))
        log.create_topic(TopicSpec("events", 1))
        log.create_topic(TopicSpec("state", 1, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=CFG)
        await indexer.start()
        pub = PartitionPublisher(log, "state", "events", 0, indexer,
                                 config=CFG, still_owner=lambda: True)
        await pub.start()
        await pub.wait_ready(5.0)
        assert pub._pipeline_capable()  # FileLog exposes commit_pipelined

        await pub.publish("a", [event_rec("a", b"before")], "r0")
        log.transactional_producer(pub.transactional_id)  # fence mid-lane
        await asyncio.wait_for(
            pub.publish("a", [event_rec("a", b"held")], "r1"), 10.0)
        await pub.wait_ready(5.0)
        assert pub.stats.reinitializations == 1
        # a late same-request retry of the held batch is absorbed
        await pub.publish("a", [event_rec("a", b"held")], "r1")
        assert [r.value for r in log.read("events", 0)] == [b"before", b"held"]
        await pub.stop()
        await indexer.stop()
        log.close()

    asyncio.run(scenario())


def test_pipelined_window_overlaps_commits_on_filelog(tmp_path):
    """max-in-flight > 1 on a pipelined transport: multiple batches may be in
    flight concurrently, every ack is durable, and nothing is lost or
    reordered within an aggregate."""
    from surge_tpu.log.file import FileLog

    async def scenario():
        log = FileLog(str(tmp_path / "log"))
        log.create_topic(TopicSpec("events", 1))
        log.create_topic(TopicSpec("state", 1, compacted=True))
        indexer = StateStoreIndexer(log, "state", config=CFG)
        await indexer.start()
        cfg = CFG.with_overrides({"surge.producer.linger-ms": 0,
                                  "surge.producer.max-in-flight": 4})
        pub = PartitionPublisher(log, "state", "events", 0, indexer, config=cfg)
        await pub.start()
        await pub.wait_ready(5.0)

        async def stream(agg, n):
            for i in range(n):
                await pub.publish(agg, [event_rec(agg, b"%s-%d" % (
                    agg.encode(), i))], f"{agg}-{i}")

        await asyncio.gather(*(stream(f"agg{j}", 10) for j in range(6)))
        values = [r.value for r in log.read("events", 0)]
        assert len(values) == 60 and len(set(values)) == 60  # exactly once
        for j in range(6):
            seq = [v for v in values if v.startswith(b"agg%d-" % j)]
            assert seq == sorted(seq, key=lambda v: int(v.split(b"-")[-1]))
        await pub.stop()
        await indexer.stop()
        log.close()

    asyncio.run(scenario())
