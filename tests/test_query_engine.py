"""TPU scan engine over committed columnar segments (surge_tpu.replay.query).

The analytics half of the KTable analogy: projection/filter/grouped-aggregate
scans over struct-of-arrays chunks, on device (and mesh-sharded), must equal
the pure-numpy host reference on every op — and the admin ``ScanSegments`` /
``QueryStates`` RPCs must serve the same rows end to end."""

import asyncio
import os
import random

import numpy as np
import pytest

from surge_tpu.codec.tensor import encode_events_columnar
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import fold_events
from surge_tpu.log.columnar import ColumnarSegmentWriter, read_segment
from surge_tpu.models import bank_account, counter
from surge_tpu.replay import ReplayEngine
from surge_tpu.replay.query import (
    Aggregate,
    Predicate,
    QueryEngine,
    ScanQuery,
    StateQuery,
    scan_reference,
    state_query_reference,
)

SPEC = counter.make_replay_spec()


def counter_logs(n, max_len, seed):
    rng = random.Random(seed)
    logs = []
    for i in range(n):
        seq = 0
        log = []
        for _ in range(rng.randrange(max_len + 1)):
            seq += 1
            kind = rng.randrange(3)
            if kind == 0:
                log.append(counter.CountIncremented(str(i), rng.randrange(1, 4),
                                                    seq))
            elif kind == 1:
                log.append(counter.CountDecremented(str(i), rng.randrange(1, 4),
                                                    seq))
            else:
                log.append(counter.NoOpEvent(str(i), seq))
        logs.append(log)
    return logs


def chunked_colev(logs, chunk_aggs, id_prefix="agg"):
    """Disjoint-aggregate chunks, the columnar-segment layout."""
    chunks = []
    for lo in range(0, len(logs), chunk_aggs):
        sub = logs[lo: lo + chunk_aggs]
        colev = encode_events_columnar(SPEC.registry, sub)
        colev.aggregate_ids = [f"{id_prefix}-{lo + j}" for j in range(len(sub))]
        chunks.append(colev)
    return chunks


QUERIES = [
    # unfiltered whole-scan, every aggregate op at once
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("sum", "increment_by"),
                          Aggregate("min", "increment_by"),
                          Aggregate("max", "sequence_number"))),
    # typed pushdown: only increments count
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("sum", "increment_by")),
              event_types=("CountIncremented",)),
    # conjunctive predicates incl. type_id, mixing filter and agg columns
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("max", "sequence_number")),
              predicates=(Predicate("sequence_number", ">", 3),
                          Predicate("type_id", "!=", 2))),
    # predicate that matches nothing: zero-match rows report 0 everywhere
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("min", "sequence_number"),
                          Aggregate("max", "increment_by")),
              predicates=(Predicate("sequence_number", ">=", 10_000),)),
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_scan_chunks_equals_numpy_reference(qi):
    logs = counter_logs(213, 23, seed=qi + 1)
    chunks = chunked_colev(logs, 64)
    q = QUERIES[qi]
    eng = QueryEngine(SPEC, config=Config({"surge.query.chunk-events": 1024}))
    got = eng.scan_chunks(chunks, q)
    want = scan_reference(chunked_colev(logs, 64), q, SPEC.registry)
    assert got.aggregate_ids == want.aggregate_ids
    assert got.num_aggregates == want.num_aggregates == 213
    assert got.matched_events == want.matched_events
    assert set(got.columns) == set(want.columns)
    for name in want.columns:
        assert np.array_equal(got.columns[name], want.columns[name]), name


def test_mesh_sharded_scan_equals_reference(mesh8):
    """The event axis sharded over the 8-device mesh (one psum/pmin/pmax per
    output) must equal the single-device scan AND the numpy reference."""
    logs = counter_logs(157, 31, seed=7)
    chunks = chunked_colev(logs, 80)
    cfg = Config({"surge.query.chunk-events": 1024})
    for q in QUERIES:
        want = scan_reference(chunked_colev(logs, 80), q, SPEC.registry)
        got = QueryEngine(SPEC, config=cfg, mesh=mesh8).scan_chunks(chunks, q)
        for name in want.columns:
            assert np.array_equal(got.columns[name], want.columns[name]), name


def test_scan_segment_projection_pushdown(tmp_path):
    """Scanning a real segment FILE only decompresses the touched columns,
    and the results match the full-read reference."""
    logs = counter_logs(130, 17, seed=11)
    path = str(tmp_path / "events.scol")
    with ColumnarSegmentWriter(path) as w:
        for colev in chunked_colev(logs, 48):
            w.append(colev)
    q = ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("sum", "increment_by")),
                  predicates=(Predicate("increment_by", ">", 1),))
    # the pushdown really projects: untouched columns never materialize
    for colev in read_segment(path, columns=q.columns_needed()):
        assert sorted(colev.cols) == ["increment_by"]
    eng = QueryEngine(SPEC, config=Config({"surge.query.chunk-events": 1024}))
    got = eng.scan_segment(path, q)
    want = scan_reference(read_segment(path), q, SPEC.registry)
    assert got.aggregate_ids == want.aggregate_ids
    for name in want.columns:
        assert np.array_equal(got.columns[name], want.columns[name]), name


def test_bank_account_float_columns_scan(mesh8):
    """Float union columns (bank_account new_balance) through the sharded
    scan: sum/min/max in device f32, equal to the reference bit for bit."""
    vocab = bank_account.Vocab()
    rng = random.Random(5)
    spec = bank_account.make_replay_spec()
    enc_logs = []
    for i in range(66):
        log = [bank_account.BankAccountCreated(str(i), f"o{i}", "s", 100.0)]
        bal = 100.0
        for _ in range(rng.randrange(0, 9)):
            bal += rng.randrange(1, 30) * 0.25
            log.append(bank_account.BankAccountUpdated(str(i), bal))
        enc_logs.append([bank_account.encode_event(vocab, e) for e in log])
    colev = encode_events_columnar(spec.registry, enc_logs)
    colev.aggregate_ids = [str(i) for i in range(66)]
    q = ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("max", "new_balance"),
                              Aggregate("min", "new_balance"),
                              Aggregate("sum", "new_balance")),
                  event_types=("EncodedUpdated",))  # the registered class
    want = scan_reference([colev], q, spec.registry)
    for mesh in (None, mesh8):
        got = QueryEngine(spec, config=Config(
            {"surge.query.chunk-events": 1024}), mesh=mesh).scan_chunks(
            [colev], q)
        for name in want.columns:
            assert np.array_equal(got.columns[name], want.columns[name]), \
                (name, mesh is not None)


def test_query_states_fold_filter_project(mesh8):
    """StateQuery: fold chunks to current state (mesh replay engine), filter
    on state columns, project — equal to the scalar-fold numpy oracle."""
    logs = counter_logs(97, 19, seed=13)
    chunks = chunked_colev(logs, 40)
    model = counter.CounterModel()
    truth = {"count": [], "version": []}
    for log in logs:
        st = fold_events(model, None, log)
        truth["count"].append(st.count if st else 0)
        truth["version"].append(st.version if st else 0)
    states = {k: np.asarray(v, dtype=np.int32) for k, v in truth.items()}
    ids = [f"agg-{i}" for i in range(97)]
    q = StateQuery(select=("count",),
                   predicates=(Predicate("count", ">=", 2),
                               Predicate("version", "<", 15)),
                   limit=50)
    want = state_query_reference(states, ids, q)
    qeng = QueryEngine(SPEC, config=Config({"surge.query.chunk-events": 1024}))
    for mesh in (None, mesh8):
        reng = ReplayEngine(SPEC, config=Config(
            {"surge.replay.batch-size": 32, "surge.replay.time-chunk": 8}),
            mesh=mesh)
        got = qeng.query_states(chunked_colev(logs, 40), q, reng)
        assert got.aggregate_ids == want.aggregate_ids
        assert list(got.columns) == ["count"]
        assert np.array_equal(got.columns["count"], want.columns["count"])


def test_fractional_predicate_on_integer_column():
    """A fractional predicate value against an integer column must compare
    numerically (in f32), not truncate to the column dtype: `< 2.5` keeps
    {1, 2}, `>= 2.5` keeps {3}."""
    logs = counter_logs(40, 9, seed=21)
    chunks = chunked_colev(logs, 40)
    for op, pred_val in (("<", 2.5), (">=", 2.5), ("==", 2.5), ("!=", 2.5)):
        q = ScanQuery(aggregates=(Aggregate("count"),),
                      predicates=(Predicate("increment_by", op, pred_val),))
        got = QueryEngine(SPEC, config=Config(
            {"surge.query.chunk-events": 1024})).scan_chunks(chunks, q)
        # truth from exact numeric comparison (increment_by in {0..3})
        colev = chunks[0]
        vals = colev.cols["increment_by"].astype(np.float64)
        mask = {"<": vals < 2.5, ">=": vals >= 2.5,
                "==": vals == 2.5, "!=": vals != 2.5}[op]
        want = np.zeros((40,), np.int32)
        np.add.at(want, colev.agg_idx, mask.astype(np.int32))
        assert np.array_equal(got.columns["count"], want), op
        ref = scan_reference(chunked_colev(logs, 40), q, SPEC.registry)
        assert np.array_equal(got.columns["count"], ref.columns["count"]), op


def test_aggregate_over_type_id_pseudo_column():
    """type_id works as an aggregate column, not just a predicate column
    (it rides the chunk's structural columns — never the projection)."""
    logs = counter_logs(30, 11, seed=23)
    chunks = chunked_colev(logs, 30)
    q = ScanQuery(aggregates=(Aggregate("count"), Aggregate("max", "type_id")))
    assert q.columns_needed() == []  # nothing to decompress at all
    got = QueryEngine(SPEC, config=Config(
        {"surge.query.chunk-events": 1024})).scan_chunks(chunks, q)
    want = scan_reference(chunked_colev(logs, 30), q, SPEC.registry)
    assert np.array_equal(got.columns["max_type_id"],
                          want.columns["max_type_id"])


def test_non_pow2_chunk_events_still_shards(mesh8):
    """A non-power-of-two surge.query.chunk-events must normalize to a bucket
    every mesh divides — the knob seeds the ladder, it is not the bucket."""
    logs = counter_logs(25, 7, seed=29)
    chunks = chunked_colev(logs, 25)
    q = ScanQuery(aggregates=(Aggregate("count"),))
    eng = QueryEngine(SPEC, config=Config(
        {"surge.query.chunk-events": 1100}), mesh=mesh8)
    assert eng._event_bucket % 8 == 0 and eng._event_bucket >= 1100
    got = eng.scan_chunks(chunks, q)
    want = scan_reference(chunked_colev(logs, 25), q, SPEC.registry)
    assert np.array_equal(got.columns["count"], want.columns["count"])


def test_scan_merges_extended_segment_delta_chunks(tmp_path):
    """Auto-extended segments append delta chunks REPEATING base-chunk
    aggregates: the scan must merge them into one row per id (count/sum add,
    min/max combine, zero-match normalization after the merge) — never emit
    duplicate rows with split partials."""
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.log.columnar import (build_segment_from_topic,
                                        extend_segment_from_topic)
    from surge_tpu.serialization import SerializedMessage

    evt = counter.event_formatting()
    log = InMemoryLog()
    log.create_topic(TopicSpec("ev", 1))

    def publish(agg, events):
        prod = log.transactional_producer("t")
        prod.begin()
        for e in events:
            prod.send(LogRecord(topic="ev", key=agg,
                                value=evt.write_event(e).value, partition=0))
        prod.commit()

    publish("a", [counter.CountIncremented("a", 2, 1),
                  counter.CountIncremented("a", 3, 2)])
    publish("b", [counter.CountIncremented("b", 1, 1)])
    path = str(tmp_path / "seg.scol")
    deser = lambda m: evt.read_event(m)  # noqa: E731
    build_segment_from_topic(log, "ev", SPEC.registry, deser, path)
    # post-build delta: 'a' continues, 'c' is new
    publish("a", [counter.CountIncremented("a", 3, 3)])  # 2-bit wire: ≤ 3
    publish("c", [counter.CountIncremented("c", 1, 1)])
    extend_segment_from_topic(log, "ev", SPEC.registry, deser, path)

    q = ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("sum", "increment_by"),
                              Aggregate("min", "increment_by"),
                              Aggregate("max", "increment_by")))
    eng = QueryEngine(SPEC, config=Config({"surge.query.chunk-events": 1024}))
    got = eng.scan_segment(path, q)
    rows = {r["aggregate_id"]: r for r in got.rows()}
    assert len(got.aggregate_ids) == len(set(got.aggregate_ids)) == 3
    assert rows["a"] == {"aggregate_id": "a", "count": 3,
                         "sum_increment_by": 8, "min_increment_by": 2,
                         "max_increment_by": 3}
    assert rows["b"]["count"] == 1 and rows["c"]["count"] == 1
    # the reference merges identically
    ref = scan_reference(read_segment(path), q, SPEC.registry)
    assert ref.aggregate_ids == got.aggregate_ids
    for name in ref.columns:
        assert np.array_equal(ref.columns[name], got.columns[name]), name

    # state query: the delta chunk folds as a CONTINUATION of the base
    # carry — one complete row per id, never a from-init partial
    sq = StateQuery(select=("count", "version"))
    sres = eng.query_states_segment(path, sq, ReplayEngine(SPEC, config=Config(
        {"surge.replay.batch-size": 16, "surge.replay.time-chunk": 8})))
    srows = {a: {k: v[j] for k, v in sres.columns.items()}
             for j, a in enumerate(sres.aggregate_ids)}
    assert len(srows) == 3
    assert srows["a"] == {"count": 8, "version": 3}
    assert srows["b"] == {"count": 1, "version": 1}
    assert srows["c"] == {"count": 1, "version": 1}


OR_GROUP_QUERIES = [
    # one OR group over one column: increment_by == 1 OR increment_by == 3
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("sum", "increment_by")),
              or_groups=((Predicate("increment_by", "==", 1),
                          Predicate("increment_by", "==", 3)),)),
    # CNF: conjunctive predicate AND two OR groups mixing columns + type_id
    ScanQuery(aggregates=(Aggregate("count"),
                          Aggregate("max", "sequence_number")),
              predicates=(Predicate("sequence_number", ">", 1),),
              or_groups=((Predicate("type_id", "==", 0),
                          Predicate("type_id", "==", 2)),
                         (Predicate("increment_by", "<=", 1),
                          Predicate("sequence_number", ">=", 5)))),
    # fractional OR-group legs against an integer column (f32 compare path)
    ScanQuery(aggregates=(Aggregate("count"),),
              or_groups=((Predicate("increment_by", "<", 1.5),
                          Predicate("increment_by", ">", 2.5)),)),
]


@pytest.mark.parametrize("qi", range(len(OR_GROUP_QUERIES)))
def test_or_groups_equal_numpy_reference(qi):
    """Each OR group is a disjunction; groups AND with each other and the
    conjunctive predicates — bit-identical to the extended reference."""
    logs = counter_logs(143, 21, seed=31 + qi)
    chunks = chunked_colev(logs, 48)
    q = OR_GROUP_QUERIES[qi]
    got = QueryEngine(SPEC, config=Config(
        {"surge.query.chunk-events": 1024})).scan_chunks(chunks, q)
    want = scan_reference(chunked_colev(logs, 48), q, SPEC.registry)
    assert got.aggregate_ids == want.aggregate_ids
    assert got.matched_events == want.matched_events
    for name in want.columns:
        assert np.array_equal(got.columns[name], want.columns[name]), name
    # the OR really widens: each leg alone matches fewer events
    if qi == 0:
        for v in (1, 3):
            leg = QueryEngine(SPEC, config=Config(
                {"surge.query.chunk-events": 1024})).scan_chunks(
                chunked_colev(logs, 48),
                ScanQuery(aggregates=(Aggregate("count"),),
                          predicates=(Predicate("increment_by", "==", v),)))
            assert leg.matched_events < got.matched_events


def test_group_by_event_column_equals_reference():
    """group_by keys rows by distinct event-column values instead of
    aggregate id; the same value recurring across chunks merges into one
    row, exactly like a repeated aggregate id."""
    logs = counter_logs(97, 17, seed=41)
    q = ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("sum", "sequence_number"),
                              Aggregate("max", "sequence_number")),
                  group_by="increment_by",
                  event_types=("CountIncremented", "CountDecremented"))
    got = QueryEngine(SPEC, config=Config(
        {"surge.query.chunk-events": 1024})).scan_chunks(
        chunked_colev(logs, 32), q)
    want = scan_reference(chunked_colev(logs, 32), q, SPEC.registry)
    assert got.aggregate_ids == want.aggregate_ids
    # groups form over ALL events' column values (NoOp rows carry the union
    # default 0); the type filter then zero-matches the "0" group
    assert sorted(got.aggregate_ids) == ["0", "1", "2", "3"]
    for name in want.columns:
        assert np.array_equal(got.columns[name], want.columns[name]), name
    # truth per group from the flat event stream
    flat = [e for log in logs for e in log
            if not isinstance(e, counter.NoOpEvent)]
    for j, key in enumerate(got.aggregate_ids):
        # decrements store no increment_by: their union column fills 0
        members = [e for e in flat
                   if getattr(e, "increment_by", 0) == int(key)]
        assert got.columns["count"][j] == len(members)
        assert got.columns["sum_sequence_number"][j] == sum(
            e.sequence_number for e in members)

    # group_by type_id: rows keyed by the structural type ids
    qt = ScanQuery(aggregates=(Aggregate("count"),), group_by="type_id")
    got_t = QueryEngine(SPEC, config=Config(
        {"surge.query.chunk-events": 1024})).scan_chunks(
        chunked_colev(logs, 32), qt)
    want_t = scan_reference(chunked_colev(logs, 32), qt, SPEC.registry)
    assert got_t.aggregate_ids == want_t.aggregate_ids
    assert np.array_equal(got_t.columns["count"], want_t.columns["count"])
    assert int(got_t.columns["count"].sum()) == sum(
        len(log) for log in logs)


def test_or_groups_and_group_by_mesh_sharded(mesh8):
    """The extended predicate compiler + group-by dispatch under the 8-device
    mesh must stay bit-identical to the reference."""
    logs = counter_logs(121, 19, seed=43)
    queries = OR_GROUP_QUERIES + [
        ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("sum", "sequence_number")),
                  group_by="increment_by",
                  or_groups=((Predicate("sequence_number", "<", 4),
                              Predicate("sequence_number", ">", 9)),)),
    ]
    cfg = Config({"surge.query.chunk-events": 1024})
    for q in queries:
        want = scan_reference(chunked_colev(logs, 40), q, SPEC.registry)
        got = QueryEngine(SPEC, config=cfg, mesh=mesh8).scan_chunks(
            chunked_colev(logs, 40), q)
        assert got.aggregate_ids == want.aggregate_ids
        for name in want.columns:
            assert np.array_equal(got.columns[name], want.columns[name]), name


def test_query_json_round_trip():
    q = QUERIES[2]
    assert ScanQuery.from_json(q.as_json()) == q
    q2 = OR_GROUP_QUERIES[1]
    d = q2.as_json()
    assert "or_groups" in d
    assert ScanQuery.from_json(d) == q2
    q3 = ScanQuery(aggregates=(Aggregate("count"),), group_by="increment_by")
    assert ScanQuery.from_json(q3.as_json()) == q3
    assert q3.columns_needed() == ["increment_by"]  # group col projects
    # plain queries serialize without the new keys (wire compat)
    assert "or_groups" not in QUERIES[0].as_json()
    assert "group_by" not in QUERIES[0].as_json()
    with pytest.raises(ValueError):
        ScanQuery(aggregates=(Aggregate("count"),), or_groups=((),))
    sq = StateQuery(select=("count",), predicates=(
        Predicate("count", ">", 1),), limit=7)
    assert StateQuery.from_json(sq.as_json()) == sq
    with pytest.raises(ValueError):
        Predicate("c", "~", 1)
    with pytest.raises(ValueError):
        Aggregate("sum")  # needs a column
    with pytest.raises(ValueError):
        QueryEngine(SPEC).resolve_type_ids(["NoSuchEvent"])


def test_engine_query_rpc_round_trip(tmp_path):
    """SurgeEngine.query()/query_states() + the admin ScanSegments/QueryStates
    RPCs: commands publish events, the segment builds on first query, and the
    RPC rows equal the numpy reference over that segment."""
    import grpc

    from surge_tpu import SurgeCommandBusinessLogic, create_engine
    from surge_tpu.admin import AdminClient, AdminServer

    seg_path = str(tmp_path / "counter.scol")
    cfg = default_config().with_overrides({
        "surge.producer.flush-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.engine.num-partitions": 2,
        "surge.replay.segment-path": seg_path,
        "surge.query.max-rows": 4,
    })

    async def scenario():
        engine = create_engine(SurgeCommandBusinessLogic(
            aggregate_name="counter", model=counter.CounterModel(),
            state_format=counter.state_formatting(),
            event_format=counter.event_formatting()), config=cfg)
        await engine.start()
        try:
            for i in range(6):
                ref = engine.aggregate_for(f"q-{i}")
                for _ in range(i + 1):
                    await ref.send_command(counter.Increment(f"q-{i}"))

            q = {"aggregates": [{"op": "count"},
                                {"op": "sum", "column": "increment_by"}],
                 "event_types": ["CountIncremented"]}
            result = await engine.query(q)
            assert os.path.exists(seg_path)  # built on first query
            want = scan_reference(read_segment(seg_path),
                                  ScanQuery.from_json(q), SPEC.registry)
            assert result.aggregate_ids == want.aggregate_ids
            for name in want.columns:
                assert np.array_equal(result.columns[name],
                                      want.columns[name]), name
            by_id = dict(zip(result.aggregate_ids, result.columns["count"]))
            assert by_id["q-5"] == 6 and by_id["q-0"] == 1

            admin = AdminServer(engine)
            port = await admin.start()
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            client = AdminClient(channel)
            try:
                payload = await client.scan_segments(q)
                assert payload["num_aggregates"] == 6
                assert payload["truncated"] is True  # max-rows=4 capped
                assert len(payload["rows"]) == 4
                row = next(r for r in payload["rows"]
                           if r["aggregate_id"] == "q-3")
                assert row["count"] == 4 and row["sum_increment_by"] == 4

                sq = {"select": ["count"],
                      "predicates": [{"column": "count", "op": ">=",
                                      "value": 4}]}
                payload = await client.query_states(sq)
                got_ids = sorted(r["aggregate_id"] for r in payload["rows"])
                assert got_ids == ["q-3", "q-4", "q-5"]
                assert all(set(r) == {"aggregate_id", "count"}
                           for r in payload["rows"])

                with pytest.raises(RuntimeError):
                    await client.scan_segments(
                        {"aggregates": [{"op": "sum", "column": "nope"}]})

                # query metrics fed the predeclared instruments
                vals = engine.metrics_registry.get_metrics()
                assert vals["surge.query.scanned-events"] > 0
                assert vals["surge.query.result-rows"] == 3
            finally:
                await channel.close()
                await admin.stop()
        finally:
            await engine.stop()

    asyncio.run(scenario())
